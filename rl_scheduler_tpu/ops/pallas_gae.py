"""Pallas TPU kernel for Generalized Advantage Estimation.

GAE is the one hot op in the PPO update that XLA cannot tile well: a
length-``T`` *sequential* recurrence over a ``[T, N]`` rollout. As a
``lax.scan`` it compiles to ``T`` tiny fused loop bodies with loop-carried
dependencies and per-iteration dynamic-slice traffic; as a Pallas kernel the
whole recurrence runs in one launch — each grid program pins a ``[T, BN]``
column block in VMEM and walks the time axis backwards with the two
recurrence carries (advantage, next value) held in VMEM scratch, so HBM is
touched exactly once per element in and once out.

The reference computes GAE in numpy on the Ray driver after experience is
shipped across the object store (RLlib postprocessing, SURVEY.md §3.1); here
it stays on-chip inside the jitted update.

The kernel is numerically identical to :func:`rl_scheduler_tpu.ops.gae.gae`
(equivalence-tested) and runs in interpret mode on CPU so the same code path
is testable without a TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Column-block width: multiple of the 128-lane VPU width; 512 keeps each
# (1, BN) row op at 4 vector registers while the [T, BN] block (T=100
# rollouts => ~200 KB x 4 buffers) sits comfortably in ~16 MB VMEM.
DEFAULT_BLOCK_N = 512


def _gae_kernel(rew_ref, val_ref, nd_ref, lastv_ref, adv_ref, adv_c, val_c, *,
                gamma: float, lam: float, num_steps: int):
    """One column block: reverse-time GAE recurrence held in VMEM.

    Refs are ``[T, BN]`` blocks except ``lastv_ref`` ``[1, BN]``;
    ``adv_c``/``val_c`` are ``[1, BN]`` VMEM scratch carrying the recurrence.
    """
    adv_c[:] = jnp.zeros_like(adv_c)
    val_c[:] = lastv_ref[:]

    def body(i, _):
        t = num_steps - 1 - i
        reward = rew_ref[pl.ds(t, 1), :]
        value = val_ref[pl.ds(t, 1), :]
        nd = nd_ref[pl.ds(t, 1), :]
        delta = reward + gamma * val_c[:] * nd - value
        adv = delta + gamma * lam * nd * adv_c[:]
        adv_ref[pl.ds(t, 1), :] = adv
        adv_c[:] = adv
        val_c[:] = value
        return 0

    jax.lax.fori_loop(0, num_steps, body, 0)


@functools.partial(
    jax.jit, static_argnames=("gamma", "lam", "block_n", "interpret")
)
def gae_pallas(
    rewards: jnp.ndarray,     # [T, N]
    values: jnp.ndarray,      # [T, N] V(s_t)
    dones: jnp.ndarray,       # [T, N] episode ended at t (any dtype)
    last_value: jnp.ndarray,  # [N] V(s_T) bootstrap
    gamma: float,
    lam: float,
    block_n: int = DEFAULT_BLOCK_N,
    interpret: bool | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Pallas GAE: ``(advantages [T, N], targets [T, N])``.

    Matches :func:`rl_scheduler_tpu.ops.gae.gae` bit-for-bit in f32. ``N``
    is zero-padded up to a multiple of ``block_n`` (columns are independent,
    so padding never leaks into real outputs). ``interpret=None`` auto-picks
    interpreter mode off-TPU so tests run on CPU.
    """
    if interpret is None:
        from rl_scheduler_tpu.ops.gae import default_platform

        interpret = default_platform() != "tpu"
    num_steps, n = rewards.shape
    rewards = rewards.astype(jnp.float32)
    values = values.astype(jnp.float32)
    not_done = 1.0 - dones.astype(jnp.float32)
    lastv = last_value.astype(jnp.float32).reshape(1, n)

    n_pad = pl.cdiv(n, block_n) * block_n
    if n_pad != n:
        pad = ((0, 0), (0, n_pad - n))
        rewards = jnp.pad(rewards, pad)
        values = jnp.pad(values, pad)
        not_done = jnp.pad(not_done, pad)
        lastv = jnp.pad(lastv, pad)

    col_spec = pl.BlockSpec(
        (num_steps, block_n), lambda j: (0, j), memory_space=pltpu.VMEM
    )
    advs = pl.pallas_call(
        functools.partial(
            _gae_kernel, gamma=gamma, lam=lam, num_steps=num_steps
        ),
        grid=(n_pad // block_n,),
        in_specs=[
            col_spec,
            col_spec,
            col_spec,
            pl.BlockSpec((1, block_n), lambda j: (0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=col_spec,
        out_shape=jax.ShapeDtypeStruct((num_steps, n_pad), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((1, block_n), jnp.float32),
            pltpu.VMEM((1, block_n), jnp.float32),
        ],
        interpret=interpret,
    )(rewards, values, not_done, lastv)

    advs = advs[:, :n]
    return advs, advs + values[:, :n]
