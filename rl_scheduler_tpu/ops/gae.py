"""Generalized Advantage Estimation as a reverse ``lax.scan``.

The reference delegates GAE to RLlib's numpy postprocessing on the driver
process; here it runs on-device inside the jitted update, over the whole
``[T, N]`` rollout at once. ``done`` marks episode boundaries from
auto-reset, cutting the bootstrap across episodes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def default_platform() -> str:
    """Platform the default device lives on.

    ``jax_default_device`` may hold a ``Device`` or (since JAX accepts
    platform strings) a plain ``str`` like ``"cpu"`` — handle both.
    """
    pinned = jax.config.jax_default_device
    if pinned is None:
        return jax.default_backend()
    return getattr(pinned, "platform", str(pinned))


def resolve_impl(impl: str) -> str:
    """Resolve ``"auto"`` to the concrete GAE impl for the default device."""
    if impl == "auto":
        return "pallas" if default_platform() == "tpu" else "scan"
    if impl not in ("scan", "pallas"):
        raise ValueError(f"unknown GAE impl {impl!r}; choose scan|pallas|auto")
    return impl


def gae(
    rewards: jnp.ndarray,     # [T, N]
    values: jnp.ndarray,      # [T, N] V(s_t)
    dones: jnp.ndarray,       # [T, N] episode ended at t
    last_value: jnp.ndarray,  # [N] V(s_{T}) bootstrap
    gamma: float,
    lam: float,
    impl: str = "auto",
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Returns ``(advantages [T, N], targets [T, N])`` with
    ``targets = advantages + values`` (the value-function regression target).

    ``impl``: ``"scan"`` (reverse ``lax.scan``), ``"pallas"`` (one-launch
    VMEM-resident kernel, :mod:`rl_scheduler_tpu.ops.pallas_gae`), or
    ``"auto"`` — pallas when the computation lands on TPU, scan elsewhere.
    Both are numerically identical (equivalence-tested). ``auto`` resolves
    from ``jax.default_device`` when pinned, else the default backend; code
    that jit-compiles for a non-default device should pass ``impl``
    explicitly.
    """
    impl = resolve_impl(impl)
    if impl == "pallas":
        from rl_scheduler_tpu.ops.pallas_gae import gae_pallas

        return gae_pallas(rewards, values, dones, last_value, gamma, lam)
    if impl != "scan":
        raise ValueError(f"unknown GAE impl {impl!r}; choose scan|pallas|auto")
    not_done = 1.0 - dones.astype(jnp.float32)

    def body(carry, xs):
        next_adv, next_value = carry
        reward, value, nd = xs
        delta = reward + gamma * next_value * nd - value
        adv = delta + gamma * lam * nd * next_adv
        return (adv, value), adv

    (_, _), advs = jax.lax.scan(
        body,
        (jnp.zeros_like(last_value), last_value),
        (rewards, values, not_done),
        reverse=True,
    )
    return advs, advs + values


def discounted_returns(
    rewards: jnp.ndarray, dones: jnp.ndarray, last_value: jnp.ndarray, gamma: float
) -> jnp.ndarray:
    """Discounted return-to-go per step (GAE with lam=1 target)."""
    not_done = 1.0 - dones.astype(jnp.float32)

    def body(next_ret, xs):
        reward, nd = xs
        ret = reward + gamma * nd * next_ret
        return ret, ret

    _, rets = jax.lax.scan(body, last_value, (rewards, not_done), reverse=True)
    return rets
