"""Single-chip flash attention for large node sets (Pallas TPU kernel).

The set policy's dense attention materializes the ``[B, N, N]`` score
tensor, which sets the single-chip memory wall at fleet-giant N
(docs/scaling.md §3: max minibatch collapses as ``B*N^2 ~ 4 GB``). The
Pallas TPU flash kernel (``jax.experimental.pallas.ops.tpu``) computes
exact attention blockwise with an online softmax — the global score
matrix never materializes — trading arithmetic speed for feasibility:

- **Speed, measured (round 5, chip A/B)**: at N=256 (B=1250, 1 head,
  head_dim 64) flash runs the fwd+bwd **5.2x slower** than XLA's dense
  attention (13.1 vs 2.5 ms) — at sizes where the score tensor fits,
  dense wins outright, consistent with this framework's other
  hand-kernel negative results. Do NOT use flash below the memory wall.
- **Memory, measured**: dense attention fails to compile at
  (B=1024, N=2048) and (B=512, N=8192) on the bench chip; flash runs
  both (and fails at B=4096, N=2048) — roughly a 2-4x extension of the
  feasible single-chip minibatch in the N >= 1k regime, the middle
  ground before sequence parallelism (`--sp`) becomes structural.

Kernel constraints (default block sizes): ``N`` must be a multiple of
128; bf16/f32 inputs. The wrapper enforces the shape constraint with an
actionable error at trace time.

Reference parity anchor: the reference has no attention anywhere
(``rl_scheduler/agent/*.py`` are flat MLPs); this is TPU-native
capability beyond it, composing with ``SetTransformerPolicy``'s
``attention_fn`` seam exactly like ring attention does.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

FLASH_MIN_NODES = 128  # default pallas block size; N must divide by it


def make_flax_flash_attention_fn(kernel_fn=None):
    """An ``attention_fn`` for ``nn.MultiHeadDotProductAttention`` that
    runs the Pallas TPU flash kernel.

    flax hands ``query/key/value`` as ``[batch..., seq, heads, head_dim]``
    and expects the same layout back; the kernel wants
    ``[batch, heads, seq, head_dim]``.

    ``kernel_fn``: override for the attention inner, with the KERNEL's
    calling convention (``fn(q, k, v, sm_scale=...)`` on the folded
    ``[batch, heads, seq, head_dim]`` layout). The Pallas TPU flash
    kernel has no CPU/interpret lowering in this JAX version, so the CPU
    suite injects a dense reference here to pin the wrapper's
    fold/unfold layout and constraint logic off-chip
    (``tests/test_fleet.py``); production callers leave it ``None``.
    """
    if kernel_fn is None:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention,
        )

        kernel_fn = flash_attention

    # bias/mask/dropout_rate are DECLARED (not **kwargs): flax only
    # delivers kwargs whose names appear in the fn's signature, so a
    # catch-all would silently swallow a future mask instead of refusing.
    def attention_fn(query, key, value, bias=None, mask=None,
                     dropout_rate=0.0, **kwargs):
        if bias is not None or mask is not None or dropout_rate:
            raise ValueError(
                "flash attention: bias/mask/dropout are not supported "
                "(the set policy attends all-to-all with no masking)"
            )
        n = query.shape[-3]
        if n % FLASH_MIN_NODES:
            raise ValueError(
                f"flash attention needs the node axis ({n}) to be a "
                f"multiple of {FLASH_MIN_NODES} (the kernel's block "
                "size); use the dense default below that"
            )
        # [B..., S, H, D] -> [B, H, S, D] (flatten leading batch dims)
        batch_shape = query.shape[:-3]
        fold = lambda x: jnp.moveaxis(
            x.reshape((-1,) + x.shape[-3:]), -2, -3
        )
        scale = 1.0 / math.sqrt(query.shape[-1])
        out = kernel_fn(
            fold(query), fold(key), fold(value), sm_scale=scale
        )
        out = jnp.moveaxis(out, -3, -2)
        return out.reshape(batch_shape + out.shape[-3:])

    return attention_fn
