"""TPU-friendly indexing primitives shared by losses and env code.

``jnp.take_along_axis`` over a small trailing axis compiles to a random
gather, which TPUs execute element-wise through the scalar unit; profiled
at 4096 envs x 100 steps these gathers were most of the fused PPO update
(the action-column selects in the loss ~0.35 ms per 32768-row minibatch,
the reward-column selects ~6 ms per horizon). A one-hot multiply-reduce is
a fully vectorized elementwise op and profiles as ~free at these shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def select_along_last(values: jnp.ndarray, indices: jnp.ndarray) -> jnp.ndarray:
    """``take_along_axis(values, indices[..., None], -1)[..., 0]`` as a
    one-hot contraction over the (small) trailing axis.

    Contract: ``indices`` must be in ``[0, values.shape[-1])`` (out-of-range
    yields 0.0 rather than take_along_axis's fill value). Unselected columns
    may be non-finite: the select masks with ``where`` rather than a
    multiply, so ``-inf`` padding logits (action masking) cannot poison the
    sum with ``0 * inf = NaN``. Prefer ``take_along_axis`` for wide or
    untrusted index spaces.
    """
    one_hot = jax.nn.one_hot(indices, values.shape[-1], dtype=jnp.bool_)
    return jnp.sum(jnp.where(one_hot, values, 0), axis=-1)
