"""TPU-friendly indexing primitives shared by losses and env code.

``jnp.take_along_axis`` over a small trailing axis compiles to a random
gather, which TPUs execute element-wise through the scalar unit; profiled
at 4096 envs x 100 steps these gathers were most of the fused PPO update
(the action-column selects in the loss ~0.35 ms per 32768-row minibatch,
the reward-column selects ~6 ms per horizon). A one-hot multiply-reduce is
a fully vectorized elementwise op and profiles as ~free at these shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def select_along_last(values: jnp.ndarray, indices: jnp.ndarray) -> jnp.ndarray:
    """``take_along_axis(values, indices[..., None], -1)[..., 0]`` as a
    one-hot contraction over the (small) trailing axis.

    Contract: ``indices`` must be in ``[0, values.shape[-1])`` (out-of-range
    yields 0.0 rather than take_along_axis's fill value). Unselected columns
    may be non-finite: the select masks with ``where`` rather than a
    multiply, so ``-inf`` padding logits (action masking) cannot poison the
    sum with ``0 * inf = NaN``. Prefer ``take_along_axis`` for wide or
    untrusted index spaces.
    """
    one_hot = jax.nn.one_hot(indices, values.shape[-1], dtype=jnp.bool_)
    return jnp.sum(jnp.where(one_hot, values, 0), axis=-1)


def shuffle_block_perm(key: jnp.ndarray, num_blocks: int) -> jnp.ndarray:
    """Epoch-shuffle permutation as ONE argsort over random bits.

    ``jax.random.permutation`` runs multiple bit-draw + sort rounds to make
    the permutation exactly uniform under key collisions; for minibatch
    shuffling that exactness buys nothing, so the graftpipe fused prologue
    (``agent/ppo.py``) draws one uint32 word per block and argsorts it —
    one fused sort, no extra rounds. Ties (~``num_blocks^2 / 2^33``
    probability — <2% even at the set_fleet64 block count of 12800)
    resolve by the stable sort's index order: statistically immaterial for
    minibatch mixing, and deterministic per key either way.
    """
    bits = jax.random.bits(key, (num_blocks,), jnp.uint32)
    return jnp.argsort(bits)


def gather_shuffled_minibatch(
    packed_blocks: jnp.ndarray,   # [num_blocks, blk * K] packed sample rows
    perm: jnp.ndarray,            # [num_blocks] epoch permutation
    minibatch_index: jnp.ndarray, # scalar int (traced: the SGD scan index)
    blocks_per_minibatch: int,
) -> jnp.ndarray:
    """The fused shuffle-gather: minibatch ``i`` of a shuffled epoch,
    gathered straight from the UNSHUFFLED packed batch.

    The classic formulation materializes the whole shuffled batch
    (``packed_blocks[perm]`` — a full [B, K] HBM write + read per epoch)
    and then slices minibatches out of the copy. Here each minibatch
    dynamic-slices its own ``blocks_per_minibatch`` window of ``perm`` and
    gathers exactly those rows — same minibatch content for the same
    ``perm`` (equivalence-tested), with the full-batch shuffled
    materialization gone. Returns ``[blocks_per_minibatch, blk * K]``;
    the caller reshapes rows to samples.
    """
    idx = jax.lax.dynamic_slice_in_dim(
        perm, minibatch_index * blocks_per_minibatch, blocks_per_minibatch
    )
    return jnp.take(packed_blocks, idx, axis=0)
