"""PPO and DQN losses, matching RLlib's torch implementations in behavior.

PPO: clipped surrogate + clipped value loss + entropy bonus with RLlib's
default coefficients (vf_loss_coeff=1.0, entropy_coeff=0.0, clip 0.3,
vf_clip 10.0), so the reference's named hyperparameter presets behave
comparably (SURVEY.md §7.3).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from rl_scheduler_tpu.ops.indexing import select_along_last


class PPOLossConfig(NamedTuple):
    clip_eps: float = 0.3        # RLlib PPO default clip_param
    vf_clip: float = 10.0        # RLlib default vf_clip_param
    vf_coeff: float = 1.0
    entropy_coeff: float = 0.0
    normalize_advantages: bool = True
    # graftscope (utils/metrics.py): when set (a static tuple of bucket
    # edges), the metrics dict gains "hist_ratio" — per-minibatch ratio
    # counts, bucketized HERE so the [B] ratio array is reduced in place
    # instead of stacking through the SGD scan. None (the default) leaves
    # the loss byte-identical to the un-instrumented build.
    ratio_hist_edges: tuple | None = None
    # Anti-latch auxiliary penalty (ROADMAP 3b, docs/studies.md): weight
    # on :func:`argmax_concentration` — the collision probability of the
    # batch-pooled near-argmax policy. The measured fleet failure mode is
    # a near-uniform policy whose argmax latches onto ONE static node
    # premium across every state; per-state entropy cannot see it (the
    # distribution is already near-uniform), but the pooled sharpened
    # policy concentrates on the latched node, so this term does.
    # 0.0 (the default) leaves the loss byte-identical.
    argmax_penalty_coeff: float = 0.0
    # Logit multiplier for the penalty's soft argmax: softmax(beta *
    # logits) approaches the one-hot argmax as beta grows, keeping the
    # term differentiable. Gradients exist at any beta; 16 separates the
    # measured near-uniform fleet logits well.
    argmax_penalty_sharpness: float = 16.0


def categorical_log_prob(logits: jnp.ndarray, actions: jnp.ndarray) -> jnp.ndarray:
    logp = jax.nn.log_softmax(logits)
    return select_along_last(logp, actions)


def categorical_entropy(logits: jnp.ndarray) -> jnp.ndarray:
    logp = jax.nn.log_softmax(logits)
    return -jnp.sum(jnp.exp(logp) * logp, axis=-1)


def argmax_concentration(logits: jnp.ndarray,
                         sharpness: float = 16.0) -> jnp.ndarray:
    """Collision probability of the batch-pooled soft-argmax policy.

    ``softmax(sharpness * logits)`` per state approximates the one-hot
    argmax (differentiably); pooling it over every leading axis and
    summing the squares gives the probability that two states' argmaxes
    collide. A policy whose argmax latches onto one static node scores
    near 1.0 regardless of its per-state entropy — the measured fleet
    failure signature (docs/scaling.md §1b: 52% of placements on one
    favorite node) — while an argmax that rotates over k nodes scores
    ~1/k. Range ``[1/num_actions, 1]``. The PPO auxiliary penalty
    (``PPOLossConfig.argmax_penalty_coeff``) minimizes this directly.
    """
    sharp = jax.nn.softmax(logits * sharpness, axis=-1)
    pooled = jnp.mean(sharp.reshape(-1, sharp.shape[-1]), axis=0)
    return jnp.sum(jnp.square(pooled))


def ppo_loss(
    logits: jnp.ndarray,        # [B, A] current policy logits
    values: jnp.ndarray,        # [B] current value predictions
    actions: jnp.ndarray,       # [B]
    old_log_probs: jnp.ndarray, # [B] behavior-policy log probs
    old_values: jnp.ndarray,    # [B] behavior-policy values (for value clip)
    advantages: jnp.ndarray,    # [B]
    targets: jnp.ndarray,       # [B] value regression targets
    cfg: PPOLossConfig = PPOLossConfig(),
):
    """Returns ``(loss, metrics dict)``."""
    if cfg.normalize_advantages:
        advantages = (advantages - advantages.mean()) / (advantages.std() + 1e-8)

    log_probs = categorical_log_prob(logits, actions)
    ratio = jnp.exp(log_probs - old_log_probs)
    surr1 = ratio * advantages
    surr2 = jnp.clip(ratio, 1.0 - cfg.clip_eps, 1.0 + cfg.clip_eps) * advantages
    policy_loss = -jnp.mean(jnp.minimum(surr1, surr2))

    # RLlib-style clipped value loss.
    vf_err = jnp.square(values - targets)
    v_clipped = old_values + jnp.clip(values - old_values, -cfg.vf_clip, cfg.vf_clip)
    vf_err_clipped = jnp.square(v_clipped - targets)
    value_loss = 0.5 * jnp.mean(jnp.maximum(vf_err, vf_err_clipped))

    entropy = jnp.mean(categorical_entropy(logits))
    total = policy_loss + cfg.vf_coeff * value_loss - cfg.entropy_coeff * entropy
    concentration = None
    if cfg.argmax_penalty_coeff:
        concentration = argmax_concentration(
            logits, cfg.argmax_penalty_sharpness)
        total = total + cfg.argmax_penalty_coeff * concentration

    approx_kl = jnp.mean(old_log_probs - log_probs)
    clip_frac = jnp.mean((jnp.abs(ratio - 1.0) > cfg.clip_eps).astype(jnp.float32))
    metrics = {
        "policy_loss": policy_loss,
        "value_loss": value_loss,
        "entropy": entropy,
        "approx_kl": approx_kl,
        "clip_fraction": clip_frac,
    }
    if concentration is not None:
        metrics["argmax_concentration"] = concentration
    if cfg.ratio_hist_edges is not None:
        from rl_scheduler_tpu.utils.metrics import hist_observe

        metrics["hist_ratio"] = hist_observe(ratio, cfg.ratio_hist_edges)
    return total, metrics


def dqn_loss(
    q_values: jnp.ndarray,        # [B, A] online network Q(s, .)
    target_q_next: jnp.ndarray,   # [B, A] target network Q(s', .)
    online_q_next: jnp.ndarray,   # [B, A] online network Q(s', .) for double-DQN
    actions: jnp.ndarray,         # [B]
    rewards: jnp.ndarray,         # [B]
    dones: jnp.ndarray,           # [B]
    gamma: float,
    huber_delta: float = 1.0,
):
    """Double-DQN TD error with Huber loss. Returns ``(loss, metrics)``."""
    q_sa = select_along_last(q_values, actions)
    next_actions = jnp.argmax(online_q_next, axis=-1)
    q_next = select_along_last(target_q_next, next_actions)
    target = rewards + gamma * (1.0 - dones.astype(jnp.float32)) * q_next
    td = q_sa - jax.lax.stop_gradient(target)
    abs_td = jnp.abs(td)
    loss = jnp.mean(
        jnp.where(
            abs_td <= huber_delta,
            0.5 * jnp.square(td),
            huber_delta * (abs_td - 0.5 * huber_delta),
        )
    )
    return loss, {"td_abs_mean": jnp.mean(abs_td), "q_mean": jnp.mean(q_sa)}
