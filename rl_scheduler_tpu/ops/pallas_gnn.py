"""Fused Pallas TPU kernel for the whole GNN policy forward AND backward.

WHY: the config-5 profile (docs/status.md) showed the GNN PPO update is
bandwidth-bound — per-minibatch cost is linear in batch and width because
XLA materializes every ``[B, N, dim]`` activation in HBM between layers
(~0.8 GB of activation traffic per 65536-row minibatch; fused-matmul,
remat, and minibatch-size variants all measured neutral or worse). The
TPU-native fix is to keep the activations in VMEM across ALL layers: one
kernel computes embed -> GCN convs -> pointer/value heads per row block,
touching HBM once for the observations in and once for logits/value out.

HOW: flattening the node axis into features turns the GCN into a plain
MLP with Kronecker-structured weights, so the kernel is pure 2D matmuls
(MXU-shaped, no batched/3D ops):

    h'_i = relu(h_i W_self + sum_j A_hat[i,j] h_j W_nbr)      (per node i)
    <=>  H' = relu(H_flat @ W_big + b_big)                    (flat [B, N*dim])
    with W_big = kron(I_N, W_self) + kron(A_hat^T, W_nbr)

The big matrices are rebuilt from the small checkpoint parameters by XLA
on every call (microseconds: N*dim = 512 wide), and the backward kernel
recomputes the forward from the obs block in VMEM (in-kernel remat) then
accumulates the BIG weight gradients across the sequential TPU grid;
plain einsum contractions outside the kernel map them back to the small
parameters (the transpose of the kron construction). Wrapped in
``jax.custom_vjp``, so the PPO loss differentiates straight through.

Parity: numerically equivalent (f32) to ``models.gnn.GNNPolicy`` — same
parameter tree, tested for forward and gradient agreement. Runs in
interpret mode on CPU so tests cover the same code path without a TPU.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Rows per grid step. VMEM: ~10 live [blk, N*dim] f32 activations plus the
# weights and grad accumulators; 256 rows x 512 features keeps the backward
# kernel around 10 MB of the ~16 MB budget.
DEFAULT_BLOCK_B = 256

def _make_mm(compute_dtype):
    """Matmul helpers with f32 accumulation; ``compute_dtype=bfloat16``
    feeds the MXU its native precision (the kron-flattened weights are 4x
    the structural FLOPs, so matmul rate — not bandwidth — bounds the
    fused kernel; bf16 params/grads still live in f32)."""

    def mm(a, b):
        return jnp.dot(a.astype(compute_dtype), b.astype(compute_dtype),
                       preferred_element_type=jnp.float32)

    def mm_t_left(a, b):
        # ``a^T @ b`` contracting the leading (row/batch) axis — MXU-shaped
        # without materializing a transpose.
        return jax.lax.dot_general(
            a.astype(compute_dtype), b.astype(compute_dtype),
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        )

    return mm, mm_t_left


# --------------------------------------------------------------- kernels


def _fwd_kernel(obs_ref, we_ref, be_ref, w1_ref, b1_ref, w2_ref, b2_ref,
                w3_ref, b3_ref, wsc_ref, bsc_ref, pool_ref, wv1_ref, bv1_ref,
                wv2_ref, bv2_ref, logits_ref, value_ref, *, depth: int,
                compute_dtype):
    _MM, _ = _make_mm(compute_dtype)
    # Heads stay f32 regardless of compute_dtype, mirroring GNNPolicy's
    # "heads stay f32" contract (models/gnn.py casts h to f32 before the
    # head) — the near-zero-init pointer logits and value targets are
    # precision-sensitive.
    _MMH, _ = _make_mm(jnp.float32)
    x = obs_ref[:]
    h = jnp.maximum(_MM(x, we_ref[:]) + be_ref[:], 0.0)
    conv_w = (w1_ref, w2_ref, w3_ref)[:depth]
    conv_b = (b1_ref, b2_ref, b3_ref)[:depth]
    for w, b in zip(conv_w, conv_b):
        h = jnp.maximum(_MM(h, w[:]) + b[:], 0.0)
    logits_ref[:] = _MMH(h, wsc_ref[:]) + bsc_ref[:]
    pooled = _MMH(h, pool_ref[:])
    v1 = jnp.tanh(_MMH(pooled, wv1_ref[:]) + bv1_ref[:])
    value_ref[:] = _MMH(v1, wv2_ref[:]) + bv2_ref[:]


def _bwd_kernel(obs_ref, we_ref, be_ref, w1_ref, b1_ref, w2_ref, b2_ref,
                w3_ref, b3_ref, wsc_ref, bsc_ref, pool_ref, wv1_ref, bv1_ref,
                wv2_ref, bv2_ref, dlogits_ref, dvalue_ref,
                dwe_ref, dbe_ref, dw1_ref, db1_ref, dw2_ref, db2_ref,
                dw3_ref, db3_ref, dwsc_ref, dbsc_ref, dwv1_ref, dbv1_ref,
                dwv2_ref, dbv2_ref, *, depth: int, compute_dtype):
    _MM, _dotT_left = _make_mm(compute_dtype)
    # Head math stays f32 (see _fwd_kernel).
    _MMH, _dotT_leftH = _make_mm(jnp.float32)
    # Zero the accumulators on the first grid step; TPU grid steps run
    # sequentially on the core, so plain += accumulation is race-free.
    @pl.when(pl.program_id(0) == 0)
    def _():
        for ref in (dwe_ref, dbe_ref, dw1_ref, db1_ref, dw2_ref, db2_ref,
                    dw3_ref, db3_ref, dwsc_ref, dbsc_ref, dwv1_ref, dbv1_ref,
                    dwv2_ref, dbv2_ref):
            ref[:] = jnp.zeros_like(ref)

    # Recompute the forward for this block entirely in VMEM (in-kernel
    # remat: re-reading stored activations from HBM is what made the XLA
    # path bandwidth-bound in the first place).
    x = obs_ref[:]
    h0 = jnp.maximum(_MM(x, we_ref[:]) + be_ref[:], 0.0)
    conv_w = (w1_ref, w2_ref, w3_ref)[:depth]
    conv_b = (b1_ref, b2_ref, b3_ref)[:depth]
    hs = [h0]
    for w, b in zip(conv_w, conv_b):
        hs.append(jnp.maximum(_MM(hs[-1], w[:]) + b[:], 0.0))
    h_last = hs[-1]
    pooled = _MMH(h_last, pool_ref[:])
    v1 = jnp.tanh(_MMH(pooled, wv1_ref[:]) + bv1_ref[:])

    dlogits = dlogits_ref[:]
    dvalue = dvalue_ref[:]

    # Value head.
    dwv2_ref[:] += _dotT_leftH(v1, dvalue)
    dbv2_ref[:] += jnp.sum(dvalue, axis=0, keepdims=True)
    dv1 = _MMH(dvalue, wv2_ref[:].T)
    dzv1 = dv1 * (1.0 - v1 * v1)
    dwv1_ref[:] += _dotT_leftH(pooled, dzv1)
    dbv1_ref[:] += jnp.sum(dzv1, axis=0, keepdims=True)
    dpooled = _MMH(dzv1, wv1_ref[:].T)

    # Pointer head + pool both feed the last hidden state.
    dwsc_ref[:] += _dotT_leftH(h_last, dlogits)
    dbsc_ref[:] += jnp.sum(dlogits, axis=0, keepdims=True)
    dh = _MMH(dlogits, wsc_ref[:].T) + _MMH(dpooled, pool_ref[:].T)

    # Conv stack, walked backwards.
    dw_refs = (dw1_ref, dw2_ref, dw3_ref)[:depth]
    db_refs = (db1_ref, db2_ref, db3_ref)[:depth]
    for i in range(depth - 1, -1, -1):
        dz = dh * (hs[i + 1] > 0.0)
        dw_refs[i][:] += _dotT_left(hs[i], dz)
        db_refs[i][:] += jnp.sum(dz, axis=0, keepdims=True)
        dh = _MM(dz, conv_w[i][:].T)

    dz0 = dh * (h0 > 0.0)
    dwe_ref[:] += _dotT_left(x, dz0)
    dbe_ref[:] += jnp.sum(dz0, axis=0, keepdims=True)


# ------------------------------------------------- weight (de)flattening


def _big_weights(p: dict, norm_adj: jnp.ndarray, num_nodes: int, depth: int):
    """Small checkpoint params -> the flat-MLP weight list (f32)."""
    eye = jnp.eye(num_nodes, dtype=jnp.float32)
    ones = jnp.ones((num_nodes, 1), jnp.float32)

    def kron(m, w):
        return jnp.kron(m, w.astype(jnp.float32))

    we = kron(eye, p["embed"]["kernel"])
    be = jnp.tile(p["embed"]["bias"].astype(jnp.float32), num_nodes)[None, :]
    convs = []
    for i in range(depth):
        c = p[f"conv_{i}"]
        w_big = kron(eye, c["w_self"]["kernel"]) + kron(
            norm_adj.T, c["w_nbr"]["kernel"]
        )
        b_big = jnp.tile(
            (c["w_self"]["bias"] + c["w_nbr"]["bias"]).astype(jnp.float32),
            num_nodes,
        )[None, :]
        convs.append((w_big, b_big))
    head = p["head"]
    wsc = kron(eye, head["score_head"]["kernel"])          # [N*dim, N]
    bsc = jnp.tile(head["score_head"]["bias"].astype(jnp.float32),
                   num_nodes)[None, :]
    dim = p["embed"]["kernel"].shape[1]
    pool = kron(ones, jnp.eye(dim, dtype=jnp.float32)) / num_nodes  # [N*dim, dim]
    wv1 = head["value_hidden"]["kernel"].astype(jnp.float32)
    bv1 = head["value_hidden"]["bias"].astype(jnp.float32)[None, :]
    wv2 = head["value_head"]["kernel"].astype(jnp.float32)
    bv2 = head["value_head"]["bias"].astype(jnp.float32)[None, :]
    return we, be, convs, wsc, bsc, pool, wv1, bv1, wv2, bv2


def _small_grads(p: dict, big: dict, norm_adj: jnp.ndarray, num_nodes: int,
                 depth: int) -> dict:
    """Contract big-matrix cotangents back to the checkpoint param tree
    (the transpose of the kron construction in :func:`_big_weights`)."""
    n = num_nodes
    dim = p["embed"]["kernel"].shape[1]
    feat = p["embed"]["kernel"].shape[0]

    def like(ref, x):
        return x.astype(ref.dtype)

    g_embed = big["dwe"].reshape(n, feat, n, dim)
    out = {
        "embed": {
            "kernel": like(p["embed"]["kernel"],
                           jnp.einsum("iaic->ac", g_embed)),
            "bias": like(p["embed"]["bias"],
                         big["dbe"].reshape(n, dim).sum(0)),
        },
        "head": {
            "score_head": {
                "kernel": like(
                    p["head"]["score_head"]["kernel"],
                    jnp.einsum(
                        "iai->a", big["dwsc"].reshape(n, dim, n)
                    )[:, None],
                ),
                "bias": like(p["head"]["score_head"]["bias"],
                             big["dbsc"].sum()[None]),
            },
            "value_hidden": {
                "kernel": like(p["head"]["value_hidden"]["kernel"], big["dwv1"]),
                "bias": like(p["head"]["value_hidden"]["bias"], big["dbv1"][0]),
            },
            "value_head": {
                "kernel": like(p["head"]["value_head"]["kernel"], big["dwv2"]),
                "bias": like(p["head"]["value_head"]["bias"], big["dbv2"][0]),
            },
        },
    }
    for i in range(depth):
        g = big["dconv"][i].reshape(n, dim, n, dim)
        db = big["dbconv"][i].reshape(n, dim).sum(0)
        c = p[f"conv_{i}"]
        out[f"conv_{i}"] = {
            "w_self": {
                "kernel": like(c["w_self"]["kernel"],
                               jnp.einsum("iaic->ac", g)),
                "bias": like(c["w_self"]["bias"], db),
            },
            "w_nbr": {
                "kernel": like(c["w_nbr"]["kernel"],
                               jnp.einsum("ij,jaic->ac", norm_adj, g)),
                "bias": like(c["w_nbr"]["bias"], db),
            },
        }
    return out


# ------------------------------------------------------------ entry point


def _full_spec():
    return pl.BlockSpec(memory_space=pltpu.VMEM)


def _run_forward(weights, obs_flat, num_nodes, depth, block_b, interpret,
                 compute_dtype):
    b, flat_in = obs_flat.shape
    we, be, convs, wsc, bsc, pool, wv1, bv1, wv2, bv2 = weights
    width = we.shape[1]
    # depth < 3 still passes three conv slots (static kernel signature);
    # pad with unused dummies.
    cw = [c[0] for c in convs] + [jnp.zeros((width, width), jnp.float32)] * (3 - depth)
    cb = [c[1] for c in convs] + [jnp.zeros((1, width), jnp.float32)] * (3 - depth)
    row_spec = pl.BlockSpec((block_b, flat_in), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    logits, value = pl.pallas_call(
        functools.partial(_fwd_kernel, depth=depth,
                          compute_dtype=compute_dtype),
        grid=(b // block_b,),
        in_specs=[row_spec] + [_full_spec()] * 15,
        out_specs=[
            pl.BlockSpec((block_b, num_nodes), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_b, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, num_nodes), jnp.float32),
            jax.ShapeDtypeStruct((b, 1), jnp.float32),
        ],
        interpret=interpret,
    )(obs_flat, we, be, cw[0], cb[0], cw[1], cb[1], cw[2], cb[2],
      wsc, bsc, pool, wv1, bv1, wv2, bv2)
    return logits, value


def _run_backward(weights, obs_flat, dlogits, dvalue, num_nodes, depth,
                  block_b, interpret, compute_dtype):
    b, flat_in = obs_flat.shape
    we, be, convs, wsc, bsc, pool, wv1, bv1, wv2, bv2 = weights
    width = we.shape[1]
    dim = wv1.shape[0]
    cw = [c[0] for c in convs] + [jnp.zeros((width, width), jnp.float32)] * (3 - depth)
    cb = [c[1] for c in convs] + [jnp.zeros((1, width), jnp.float32)] * (3 - depth)
    row = lambda cols: pl.BlockSpec((block_b, cols), lambda i: (i, 0),
                                    memory_space=pltpu.VMEM)
    # Accumulator outputs: every grid step maps to the same (whole-array)
    # block; the kernel zero-initializes on step 0 and += thereafter.
    acc = lambda shape: pl.BlockSpec(shape, lambda i: (0, 0),
                                     memory_space=pltpu.VMEM)
    out_shapes = [
        ((flat_in, width), "dwe"), ((1, width), "dbe"),
        ((width, width), "dw1"), ((1, width), "db1"),
        ((width, width), "dw2"), ((1, width), "db2"),
        ((width, width), "dw3"), ((1, width), "db3"),
        ((width, num_nodes), "dwsc"), ((1, num_nodes), "dbsc"),
        ((dim, dim), "dwv1"), ((1, dim), "dbv1"),
        ((dim, 1), "dwv2"), ((1, 1), "dbv2"),
    ]
    outs = pl.pallas_call(
        functools.partial(_bwd_kernel, depth=depth,
                          compute_dtype=compute_dtype),
        grid=(b // block_b,),
        in_specs=[row(flat_in)] + [_full_spec()] * 15
        + [row(num_nodes), row(1)],
        out_specs=[acc(s) for s, _ in out_shapes],
        out_shape=[jax.ShapeDtypeStruct(s, jnp.float32) for s, _ in out_shapes],
        interpret=interpret,
    )(obs_flat, we, be, cw[0], cb[0], cw[1], cb[1], cw[2], cb[2],
      wsc, bsc, pool, wv1, bv1, wv2, bv2, dlogits, dvalue)
    named = {name: o for (_, name), o in zip(out_shapes, outs)}
    named["dconv"] = [named[f"dw{i + 1}"] for i in range(depth)]
    named["dbconv"] = [named[f"db{i + 1}"] for i in range(depth)]
    return named


def make_fused_gnn_apply(
    adjacency: np.ndarray,
    depth: int = 3,
    block_b: int = DEFAULT_BLOCK_B,
    interpret: bool | None = None,
    compute_dtype: Any = jnp.float32,
):
    """Build ``apply(params, obs) -> (logits, value)`` running the fused
    kernels, differentiable via ``jax.custom_vjp``.

    ``params`` is a ``models.gnn.GNNPolicy`` param tree (the ``{"params":
    ...}`` dict as returned by ``init``); ``obs`` is ``[B, N, feat]`` (or
    unbatched ``[N, feat]``). ``depth`` must be <= 3 (the kernel's static
    conv slots; the shipped config uses 3). ``compute_dtype=jnp.bfloat16``
    runs the in-kernel matmuls at MXU-native precision with f32
    accumulation (params, biases, activations-out, and gradients stay
    f32) — the perf setting for the big training configs.
    """
    if depth > 3:
        raise ValueError(f"fused GNN kernel supports depth <= 3, got {depth}")
    if interpret is None:
        from rl_scheduler_tpu.ops.gae import default_platform

        interpret = default_platform() != "tpu"
    adjacency = np.asarray(adjacency, np.float32)
    num_nodes = adjacency.shape[0]
    degree = np.maximum(adjacency.sum(axis=1, keepdims=True), 1.0)
    norm_adj = jnp.asarray(adjacency / degree)

    @jax.custom_vjp
    def fused(params, obs_flat):
        weights = _big_weights(params["params"], norm_adj, num_nodes, depth)
        return _run_forward(weights, obs_flat, num_nodes, depth,
                            block_b, interpret, compute_dtype)

    def fused_fwd(params, obs_flat):
        return fused(params, obs_flat), (params, obs_flat)

    def fused_bwd(res, cotangents):
        params, obs_flat = res
        dlogits, dvalue = cotangents
        weights = _big_weights(params["params"], norm_adj, num_nodes, depth)
        big = _run_backward(
            weights, obs_flat, dlogits.astype(jnp.float32),
            dvalue.astype(jnp.float32), num_nodes, depth, block_b, interpret,
            compute_dtype,
        )
        small = _small_grads(params["params"], big, norm_adj, num_nodes, depth)
        # Observations are env data, never differentiated; returning zeros
        # keeps custom_vjp's signature contract without wasted compute
        # (XLA drops the unused cotangent).
        return {"params": small}, jnp.zeros_like(obs_flat)

    fused.defvjp(fused_fwd, fused_bwd)

    def apply(params, obs):
        from rl_scheduler_tpu.models.heads import apply_with_optional_batch

        def forward(batched_obs):
            b = batched_obs.shape[0]
            flat = batched_obs.reshape(
                b, num_nodes * batched_obs.shape[-1]
            ).astype(jnp.float32)
            pad = (-b) % block_b
            if pad:
                flat = jnp.concatenate(
                    [flat, jnp.zeros((pad, flat.shape[1]), jnp.float32)],
                    axis=0,
                )
            logits, value = fused(params, flat)
            return logits[:b], value[:b, 0]

        return apply_with_optional_batch(forward, obs)

    return apply


class FusedGNNPolicy:
    """Drop-in for ``models.gnn.GNNPolicy`` with the fused-kernel forward.

    Duck-typed flax surface (``init``/``apply``): ``init`` delegates to the
    reference module so the parameter tree (and therefore checkpoints) are
    IDENTICAL; ``apply`` runs the Pallas kernels. Use on TPU for the big
    training configs; the reference module remains the source of truth for
    parity tests and serving.
    """

    def __init__(self, adjacency, dim: int = 64, depth: int = 3,
                 block_b: int = DEFAULT_BLOCK_B, interpret: bool | None = None,
                 dtype: Any = None):
        from rl_scheduler_tpu.models import GNNPolicy

        self.inner = GNNPolicy.from_adjacency(
            np.asarray(adjacency), dim=dim, depth=depth
        )
        self.dim = dim
        self.depth = depth
        self.dtype = dtype  # compute dtype (mirrors GNNPolicy's field)
        self._apply = make_fused_gnn_apply(
            np.asarray(adjacency), depth, block_b, interpret,
            compute_dtype=dtype or jnp.float32,
        )

    def init(self, key, obs):
        return self.inner.init(key, obs)

    def apply(self, params, obs):
        return self._apply(params, obs)
