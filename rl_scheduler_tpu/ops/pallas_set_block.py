"""Fused Pallas TPU kernel for the whole set-transformer policy at FLEET
node counts (N=64/256) — forward AND backward.

WHY: the fleet-N roofline rows (docs/roofline.md, round 5) measured the
config-4 SGD body at **8.9-12.4% of its own HBM-bandwidth floor** — 324 ms
per epoch at N=64 against a 24.6 ms floor — because the ~65-op XLA
transformer body streams every ``[B, N, dim]`` activation through HBM
per op. The codebase already proved the cure on a sibling family: the
kron-flattened fused GNN kernel (``ops/pallas_gnn.py``) holds its whole
forward VMEM-resident per row block and reaches ~65% MFU. This kernel is
the same playbook (FlashAttention-style: tile + fuse so intermediates
never materialize in HBM) applied to the set-transformer block at the
shapes where it is finally MXU-friendly.

Explicitly NOT the deleted round-2 N=8 lane-slice design: that suite
fused per-op at shapes that underfill the 8x128 tiles and lost 3-5x to
XLA (negative result, docs/status.md row 4; docs/roofline.md). Here the
per-sample activations are ``[64, 64]`` / ``[256, 64]`` — MXU-shaped
tiles — and the fusion unit is the WHOLE network (embed -> depth x
(LN + single-head attention + MLP + residuals) -> final LN -> pointer/
value heads) per block of samples, touching HBM once for the obs in and
once for logits/value out. The guard below refuses non-fleet N rather
than silently re-entering the measured-bad regime.

HOW: a block of ``block_b`` samples lives as one ``[block_b*N, dim]``
f32 matrix in VMEM, so every per-node op (LayerNorm, qkv/out/MLP
projections, heads) is a single 2D MXU matmul; attention runs per sample
inside a ``fori_loop`` over the block (``[N, dim] x [dim, N]`` scores,
f32 softmax, ``[N, N] x [N, dim]`` context — 2D only, no batched 3D
ops, which keeps the Mosaic lowering simple). The value head's per-
sample mean-pool is a matmul against a block-diagonal ``1/N`` matrix
built from ``broadcasted_iota`` — again 2D. The backward kernel
recomputes the forward from the obs block in VMEM (in-kernel remat — the
whole point is never re-reading stored activations from HBM) and
accumulates parameter gradients across the sequential TPU grid, exactly
the ``pallas_gnn`` accumulator pattern. Wrapped in ``jax.custom_vjp`` so
the PPO loss differentiates straight through.

Parity: computes the IDENTICAL function (f32, tolerance-level — float
reassociation only) to ``SetTransformerPolicy(num_heads=1)`` /
``models/set_fast.py`` on the same flax parameter tree: flax LayerNorm
fast-variance semantics (eps 1e-6), approximate-tanh gelu, softmax over
the key axis in f32, heads in f32. Checkpoints are interchangeable.
Runs in interpret mode on CPU so tests cover the same code path without
a TPU (``tests/test_pallas_set_block.py``).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# The fleet floor: below this the per-sample [N, dim] tiles underfill the
# MXU and the round-2/4 negative result applies (hand fusion measured
# 3-5x WORSE than XLA at N=8, compile failure at N=16) — refuse rather
# than quietly lose. 32 is the smallest N where a [N, 64] f32 tile spans
# 4 full sublane groups; the measured fleet recipes are 64 and 256.
MIN_FLEET_NODES = 32

def is_fleet_node_count(num_nodes: int) -> bool:
    """The kernel's shape constraint, in one place: fleet node counts are
    multiples of 8 (sublane tile) at or above :data:`MIN_FLEET_NODES`.
    The train CLI's auto-selection and validation both call this so they
    cannot drift from the constructor's own guard."""
    return num_nodes >= MIN_FLEET_NODES and num_nodes % 8 == 0


# Rows (= block_b * num_nodes) per grid step. The backward kernel keeps
# ~12 live [rows, dim] f32 activations plus [rows, 2*dim] MLP tensors and
# the grad accumulators; 1024 rows x dim 64 keeps it ~6 MB of the ~16 MB
# VMEM budget.
DEFAULT_BLOCK_ROWS = 1024

_LN_EPS = 1e-6
# jax.nn.gelu(approximate=True) constants — the backward needs the
# analytic derivative of the tanh approximation.
_GELU_C = 0.7978845608028654  # sqrt(2/pi)
_GELU_A = 0.044715

# Packed-parameter layout (all leaves 2D f32, in this order):
#   [we, be] + per block [ln0_s, ln0_b, wq, bq, wk, bk, wv, bv, wo, bo,
#                         ln1_s, ln1_b, w1, b1, w2, b2]
#   + [lnf_s, lnf_b, wsc, bsc, wv1, bv1, wv2, bv2]
_PER_BLOCK = 16
_TAIL = 8


def _n_leaves(depth: int) -> int:
    return 2 + _PER_BLOCK * depth + _TAIL


def _squeeze_head(leaf: jnp.ndarray) -> jnp.ndarray:
    """flax single-head DenseGeneral axis: ``[D, 1, D]`` (q/k/v) or
    ``[1, D, D]`` (out) -> ``[D, D]`` (same squeeze as set_fast._w2)."""
    if leaf.ndim == 3:
        if leaf.shape[0] == 1:
            return leaf.reshape(-1, leaf.shape[-1])
        if leaf.shape[1] == 1:
            return leaf.reshape(leaf.shape[0], -1)
    return leaf


def _pack_params(p: dict, depth: int) -> list:
    """flax ``SetTransformerPolicy(num_heads=1)`` param tree -> the flat
    2D f32 leaf list the kernels consume (order above)."""

    def f32(x):
        return _squeeze_head(x).astype(jnp.float32)

    def row(x):
        return x.astype(jnp.float32).reshape(1, -1)

    out = [f32(p["embed"]["kernel"]), row(p["embed"]["bias"])]
    for i in range(depth):
        b = p[f"block_{i}"]
        attn = b["MultiHeadDotProductAttention_0"]
        out += [row(b["LayerNorm_0"]["scale"]), row(b["LayerNorm_0"]["bias"])]
        for name in ("query", "key", "value", "out"):
            out += [f32(attn[name]["kernel"]), row(attn[name]["bias"])]
        out += [row(b["LayerNorm_1"]["scale"]), row(b["LayerNorm_1"]["bias"]),
                f32(b["Dense_0"]["kernel"]), row(b["Dense_0"]["bias"]),
                f32(b["Dense_1"]["kernel"]), row(b["Dense_1"]["bias"])]
    out += [row(p["final_norm"]["scale"]), row(p["final_norm"]["bias"])]
    head = p["head"]
    out += [f32(head["score_head"]["kernel"]), row(head["score_head"]["bias"]),
            f32(head["value_hidden"]["kernel"]),
            row(head["value_hidden"]["bias"]),
            f32(head["value_head"]["kernel"]), row(head["value_head"]["bias"])]
    return out


def _unpack_grads(p: dict, flat: list, depth: int) -> dict:
    """Flat gradient list (packed order) -> the flax param tree, restoring
    the DenseGeneral head axes and 1D bias/LN shapes."""
    it = iter(flat)

    def like(ref):
        return next(it).reshape(ref.shape).astype(ref.dtype)

    out = {"embed": {"kernel": like(p["embed"]["kernel"]),
                     "bias": like(p["embed"]["bias"])}}
    for i in range(depth):
        b = p[f"block_{i}"]
        attn = b["MultiHeadDotProductAttention_0"]
        blk = {"LayerNorm_0": {"scale": like(b["LayerNorm_0"]["scale"]),
                               "bias": like(b["LayerNorm_0"]["bias"])}}
        mhdpa = {}
        for name in ("query", "key", "value", "out"):
            mhdpa[name] = {"kernel": like(attn[name]["kernel"]),
                           "bias": like(attn[name]["bias"])}
        blk["MultiHeadDotProductAttention_0"] = mhdpa
        blk["LayerNorm_1"] = {"scale": like(b["LayerNorm_1"]["scale"]),
                              "bias": like(b["LayerNorm_1"]["bias"])}
        blk["Dense_0"] = {"kernel": like(b["Dense_0"]["kernel"]),
                          "bias": like(b["Dense_0"]["bias"])}
        blk["Dense_1"] = {"kernel": like(b["Dense_1"]["kernel"]),
                          "bias": like(b["Dense_1"]["bias"])}
        out[f"block_{i}"] = blk
    out["final_norm"] = {"scale": like(p["final_norm"]["scale"]),
                         "bias": like(p["final_norm"]["bias"])}
    head = p["head"]
    out["head"] = {
        "score_head": {"kernel": like(head["score_head"]["kernel"]),
                       "bias": like(head["score_head"]["bias"])},
        "value_hidden": {"kernel": like(head["value_hidden"]["kernel"]),
                         "bias": like(head["value_hidden"]["bias"])},
        "value_head": {"kernel": like(head["value_head"]["kernel"]),
                       "bias": like(head["value_head"]["bias"])},
    }
    return out


# ------------------------------------------------------- in-kernel math


def _mm(a, b, dt):
    return jnp.dot(a.astype(dt), b.astype(dt),
                   preferred_element_type=jnp.float32)


def _mm_nt(a, b, dt):
    """``a @ b.T`` contracting the trailing axes — no materialized
    transpose."""
    return jax.lax.dot_general(a.astype(dt), b.astype(dt),
                               (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _mm_tn(a, b, dt):
    """``a.T @ b`` contracting the leading (row) axes."""
    return jax.lax.dot_general(a.astype(dt), b.astype(dt),
                               (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _ln_fwd(h, scale_row, bias_row):
    """flax ``nn.LayerNorm`` fast-variance forward, f32, over the feature
    (lane) axis of ``[rows, dim]``."""
    mean = jnp.mean(h, axis=1, keepdims=True)
    var = jnp.maximum(jnp.mean(h * h, axis=1, keepdims=True) - mean * mean,
                      0.0)
    inv = jax.lax.rsqrt(var + _LN_EPS)
    return (h - mean) * inv * scale_row + bias_row


def _ln_bwd(x, scale_row, dy):
    """Analytic LayerNorm backward (biased variance): returns
    ``(dx, dscale [1, D], dbias [1, D])``."""
    mean = jnp.mean(x, axis=1, keepdims=True)
    var = jnp.maximum(jnp.mean(x * x, axis=1, keepdims=True) - mean * mean,
                      0.0)
    inv = jax.lax.rsqrt(var + _LN_EPS)
    xhat = (x - mean) * inv
    dscale = jnp.sum(dy * xhat, axis=0, keepdims=True)
    dbias = jnp.sum(dy, axis=0, keepdims=True)
    dxhat = dy * scale_row
    dx = inv * (dxhat - jnp.mean(dxhat, axis=1, keepdims=True)
                - xhat * jnp.mean(dxhat * xhat, axis=1, keepdims=True))
    return dx, dscale, dbias


def _gelu_grad(z):
    """d/dz of jax.nn.gelu(z, approximate=True)."""
    u = _GELU_C * (z + _GELU_A * z * z * z)
    t = jnp.tanh(u)
    return (0.5 * (1.0 + t)
            + 0.5 * z * (1.0 - t * t)
            * _GELU_C * (1.0 + 3.0 * _GELU_A * z * z))


def _attn_fwd(q, k, v, num_nodes, block_b, dt):
    """Per-sample single-head attention over a ``[block_b*N, dim]`` block:
    ``fori_loop`` over samples, 2D matmuls only, f32 softmax over keys."""
    scale = q.shape[-1] ** -0.5

    def body(b, ctx):
        def sl(a):
            return jax.lax.dynamic_slice_in_dim(a, b * num_nodes, num_nodes, 0)

        qb, kb, vb = sl(q), sl(k), sl(v)
        s = _mm_nt(qb, kb, dt) * scale          # [N, N] f32
        p_att = jax.nn.softmax(s, axis=-1)      # over keys, f32
        cb = _mm(p_att, vb, dt)
        return jax.lax.dynamic_update_slice(ctx, cb, (b * num_nodes, 0))

    return jax.lax.fori_loop(0, block_b, body, jnp.zeros_like(q))


def _attn_bwd(q, k, v, dctx, num_nodes, block_b, dt):
    """Backward of :func:`_attn_fwd`: recompute scores/probs per sample
    (cheap, VMEM-resident) and backprop the softmax-attention chain."""
    scale = q.shape[-1] ** -0.5

    def body(b, carry):
        dq, dk, dv = carry

        def sl(a):
            return jax.lax.dynamic_slice_in_dim(a, b * num_nodes, num_nodes, 0)

        qb, kb, vb, dcb = sl(q), sl(k), sl(v), sl(dctx)
        s = _mm_nt(qb, kb, dt) * scale
        p_att = jax.nn.softmax(s, axis=-1)
        dvb = _mm_tn(p_att, dcb, dt)            # [N(keys), dim]
        dp = _mm_nt(dcb, vb, dt)                # [N(q), N(keys)]
        ds = (dp - jnp.sum(dp * p_att, axis=-1, keepdims=True)) \
            * p_att * scale
        dqb = _mm(ds, kb, dt)
        dkb = _mm_tn(ds, qb, dt)

        def up(acc, val):
            return jax.lax.dynamic_update_slice(acc, val, (b * num_nodes, 0))

        return up(dq, dqb), up(dk, dkb), up(dv, dvb)

    zeros = jnp.zeros_like(q)
    return jax.lax.fori_loop(0, block_b, body, (zeros, zeros, zeros))


def _pool_matrix(block_b, num_nodes):
    """Block-diagonal ``[block_b, block_b*N]`` mean-pool matrix (1/N where
    row r belongs to sample i) — the per-sample node mean as one 2D
    matmul, no 3D reshapes in the kernel."""
    rows = block_b * num_nodes
    owner = jax.lax.broadcasted_iota(jnp.int32, (block_b, rows), 1) // num_nodes
    sample = jax.lax.broadcasted_iota(jnp.int32, (block_b, rows), 0)
    return jnp.where(owner == sample, 1.0 / num_nodes, 0.0).astype(jnp.float32)


# --------------------------------------------------------------- kernels


def _forward_body(obs, p_vals, *, depth, num_nodes, block_b, dt,
                  with_saves: bool):
    """Shared forward chain. ``p_vals`` is the packed leaf list (values,
    already read from refs). Returns ``(logits_col, value, saves)`` where
    ``saves`` holds the per-layer residuals the backward needs (None
    entries when ``with_saves`` is False)."""
    it = iter(p_vals)
    nxt = lambda: next(it)

    we, be = nxt(), nxt()
    h = _mm(obs, we, dt) + be                     # linear embed, [R, D] f32
    saves = []
    for _ in range(depth):
        ln0s, ln0b = nxt(), nxt()
        wq, bq, wk, bk, wv, bv, wo, bo = (nxt() for _ in range(8))
        ln1s, ln1b, w1, b1, w2, b2 = (nxt() for _ in range(6))
        h_in = h
        hn = _ln_fwd(h, ln0s, ln0b)
        q = _mm(hn, wq, dt) + bq
        k = _mm(hn, wk, dt) + bk
        v = _mm(hn, wv, dt) + bv
        ctx = _attn_fwd(q, k, v, num_nodes, block_b, dt)
        h_mid = h_in + _mm(ctx, wo, dt) + bo
        m = _ln_fwd(h_mid, ln1s, ln1b)
        z1 = _mm(m, w1, dt) + b1
        g1 = jax.nn.gelu(z1)
        h = h_mid + _mm(g1, w2, dt) + b2
        saves.append((h_in, hn, q, k, v, ctx, h_mid, m, z1, g1)
                     if with_saves else None)

    lnfs, lnfb = nxt(), nxt()
    wsc, bsc, wv1, bv1, wv2, bv2 = (nxt() for _ in range(6))
    hf = _ln_fwd(h, lnfs, lnfb)
    # Heads stay f32 (same contract as set_fast / pallas_gnn: near-zero
    # pointer logits and value targets are precision-sensitive).
    logits_col = _mm(hf, wsc, jnp.float32) + bsc          # [R, 1]
    pool = _pool_matrix(block_b, num_nodes)
    pooled = _mm(pool, hf, jnp.float32)                   # [blk, D]
    v1 = jnp.tanh(_mm(pooled, wv1, jnp.float32) + bv1)
    value = _mm(v1, wv2, jnp.float32) + bv2               # [blk, 1]
    return logits_col, value, (h, hf, pool, pooled, v1, saves)


def _fwd_kernel(*refs, depth, num_nodes, block_b, compute_dtype):
    n_p = _n_leaves(depth)
    obs = refs[0][:]
    p_vals = [r[:] for r in refs[1:1 + n_p]]
    logits_ref, value_ref = refs[1 + n_p], refs[2 + n_p]
    logits_col, value, _ = _forward_body(
        obs, p_vals, depth=depth, num_nodes=num_nodes, block_b=block_b,
        dt=compute_dtype, with_saves=False)
    logits_ref[:] = logits_col
    value_ref[:] = value


def _bwd_kernel(*refs, depth, num_nodes, block_b, compute_dtype):
    n_p = _n_leaves(depth)
    obs = refs[0][:]
    p_vals = [r[:] for r in refs[1:1 + n_p]]
    dlog = refs[1 + n_p][:]                      # [R, 1] f32
    dval = refs[2 + n_p][:]                      # [blk, 1] f32
    grad_refs = refs[3 + n_p:3 + 2 * n_p]
    dt = compute_dtype

    # Zero accumulators on the first grid step; TPU grid steps run
    # sequentially on the core, so plain += accumulation is race-free.
    @pl.when(pl.program_id(0) == 0)
    def _():
        for r in grad_refs:
            r[:] = jnp.zeros_like(r)

    # In-kernel remat: recompute the whole forward for this block in VMEM.
    _, _, (h_last, hf, pool, pooled, v1, saves) = _forward_body(
        obs, p_vals, depth=depth, num_nodes=num_nodes, block_b=block_b,
        dt=dt, with_saves=True)

    it = iter(p_vals)
    we, be = next(it), next(it)
    blocks = [[next(it) for _ in range(_PER_BLOCK)] for _ in range(depth)]
    lnfs, lnfb = next(it), next(it)
    wsc, bsc, wv1, bv1, wv2, bv2 = (next(it) for _ in range(6))

    f32 = jnp.float32
    # Value head (all f32, matching the forward).
    dwv2 = _mm_tn(v1, dval, f32)
    dbv2 = jnp.sum(dval, axis=0, keepdims=True)
    dv1 = _mm_nt(dval, wv2, f32)
    dzv1 = dv1 * (1.0 - v1 * v1)
    dwv1 = _mm_tn(pooled, dzv1, f32)
    dbv1 = jnp.sum(dzv1, axis=0, keepdims=True)
    dpooled = _mm_nt(dzv1, wv1, f32)
    # Pointer head + pool both feed the final-norm output.
    dwsc = _mm_tn(hf, dlog, f32)
    dbsc = jnp.sum(dlog, axis=0, keepdims=True)
    dhf = _mm_nt(dlog, wsc, f32) + _mm_tn(pool, dpooled, f32)
    dh, dlnfs, dlnfb = _ln_bwd(h_last, lnfs, dhf)

    block_grads = []
    for i in range(depth - 1, -1, -1):
        (ln0s, ln0b, wq, bq, wk, bk, wv, bv, wo, bo,
         ln1s, ln1b, w1, b1, w2, b2) = blocks[i]
        h_in, hn, q, k, v, ctx, h_mid, m, z1, g1 = saves[i]
        # MLP branch: h_out = h_mid + gelu(LN1(h_mid) @ w1 + b1) @ w2 + b2
        dw2 = _mm_tn(g1, dh, dt)
        db2 = jnp.sum(dh, axis=0, keepdims=True)
        dg1 = _mm_nt(dh, w2, dt)
        dz1 = dg1 * _gelu_grad(z1)
        dw1 = _mm_tn(m, dz1, dt)
        db1 = jnp.sum(dz1, axis=0, keepdims=True)
        dm = _mm_nt(dz1, w1, dt)
        dm_h, dln1s, dln1b = _ln_bwd(h_mid, ln1s, dm)
        dh_mid = dh + dm_h
        # Attention branch: h_mid = h_in + attn(LN0(h_in)) @ wo + bo
        dwo = _mm_tn(ctx, dh_mid, dt)
        dbo = jnp.sum(dh_mid, axis=0, keepdims=True)
        dctx = _mm_nt(dh_mid, wo, dt)
        dq, dk, dv_ = _attn_bwd(q, k, v, dctx, num_nodes, block_b, dt)
        dwq = _mm_tn(hn, dq, dt)
        dbq = jnp.sum(dq, axis=0, keepdims=True)
        dwk = _mm_tn(hn, dk, dt)
        dbk = jnp.sum(dk, axis=0, keepdims=True)
        dwv = _mm_tn(hn, dv_, dt)
        dbv = jnp.sum(dv_, axis=0, keepdims=True)
        dhn = (_mm_nt(dq, wq, dt) + _mm_nt(dk, wk, dt)
               + _mm_nt(dv_, wv, dt))
        dhn_h, dln0s, dln0b = _ln_bwd(h_in, ln0s, dhn)
        dh = dh_mid + dhn_h
        block_grads.insert(0, [dln0s, dln0b, dwq, dbq, dwk, dbk, dwv, dbv,
                               dwo, dbo, dln1s, dln1b, dw1, db1, dw2, db2])

    dwe = _mm_tn(obs, dh, dt)
    dbe = jnp.sum(dh, axis=0, keepdims=True)

    step_grads = [dwe, dbe]
    for g in block_grads:
        step_grads += g
    step_grads += [dlnfs, dlnfb, dwsc, dbsc, dwv1, dbv1, dwv2, dbv2]
    for r, g in zip(grad_refs, step_grads):
        r[:] += g


# ------------------------------------------------------------ entry point


def _full_spec():
    return pl.BlockSpec(memory_space=pltpu.VMEM)


def _run_forward(flat, obs_flat, num_nodes, depth, block_b, interpret, dt):
    rtot, feat = obs_flat.shape
    rows = block_b * num_nodes
    bpad = rtot // num_nodes

    def row_spec(cols, r=rows):
        return pl.BlockSpec((r, cols), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)

    return pl.pallas_call(
        functools.partial(_fwd_kernel, depth=depth, num_nodes=num_nodes,
                          block_b=block_b, compute_dtype=dt),
        grid=(rtot // rows,),
        in_specs=[row_spec(feat)] + [_full_spec()] * len(flat),
        out_specs=[row_spec(1), row_spec(1, block_b)],
        out_shape=[jax.ShapeDtypeStruct((rtot, 1), jnp.float32),
                   jax.ShapeDtypeStruct((bpad, 1), jnp.float32)],
        interpret=interpret,
    )(obs_flat, *flat)


def _run_backward(flat, obs_flat, dlog, dval, num_nodes, depth, block_b,
                  interpret, dt):
    rtot, feat = obs_flat.shape
    rows = block_b * num_nodes

    def row_spec(cols, r=rows):
        return pl.BlockSpec((r, cols), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)

    # Accumulator outputs: every grid step maps to the same (whole-array)
    # block; the kernel zero-initializes on step 0 and += thereafter.
    def acc_spec(shape):
        return pl.BlockSpec(shape, lambda i: (0, 0),
                            memory_space=pltpu.VMEM)

    return pl.pallas_call(
        functools.partial(_bwd_kernel, depth=depth, num_nodes=num_nodes,
                          block_b=block_b, compute_dtype=dt),
        grid=(rtot // rows,),
        in_specs=[row_spec(feat)] + [_full_spec()] * len(flat)
        + [row_spec(1), row_spec(1, block_b)],
        out_specs=[acc_spec(f.shape) for f in flat],
        out_shape=[jax.ShapeDtypeStruct(f.shape, jnp.float32) for f in flat],
        interpret=interpret,
    )(obs_flat, *flat, dlog, dval)


def make_fused_set_apply(
    num_nodes: int,
    dim: int = 64,
    depth: int = 2,
    block_b: int | None = None,
    interpret: bool | None = None,
    compute_dtype: Any = jnp.float32,
):
    """Build ``apply(params, obs) -> (logits, value)`` running the fused
    whole-network kernels, differentiable via ``jax.custom_vjp``.

    ``params`` is a ``SetTransformerPolicy(num_heads=1)`` param tree (the
    ``{"params": ...}`` dict from ``init``); ``obs`` is ``[B, N, feat]``
    (or unbatched ``[N, feat]``) with ``N == num_nodes`` — the kernel is
    shape-specialized to one fleet size. ``compute_dtype=jnp.bfloat16``
    runs the block matmuls at MXU-native precision with f32 accumulation
    (LayerNorm statistics, softmax, and heads stay f32 — the set_fast
    contract). ``block_b`` is samples per grid step (default sized so
    ``block_b * num_nodes`` ~ :data:`DEFAULT_BLOCK_ROWS`).
    """
    if not is_fleet_node_count(num_nodes):
        raise ValueError(
            f"fused set-block kernel targets fleet node counts "
            f"(multiples of 8, >= {MIN_FLEET_NODES}); got num_nodes="
            f"{num_nodes}. Below the fleet floor the hand-fused kernel "
            "measured 3-5x WORSE than XLA (docs/roofline.md) — use the "
            "dense path (--fused-set / the flax policy) there."
        )
    if dim % 8:
        raise ValueError(
            f"fused set-block kernel needs dim to be a multiple of 8 "
            f"(sublane tile), got dim={dim}"
        )
    if compute_dtype not in (jnp.float32, jnp.bfloat16):
        raise ValueError(
            f"fused set-block kernel computes in float32 or bfloat16, "
            f"got dtype {compute_dtype!r}"
        )
    if interpret is None:
        from rl_scheduler_tpu.ops.gae import default_platform

        interpret = default_platform() != "tpu"
    if block_b is None:
        block_b = max(DEFAULT_BLOCK_ROWS // num_nodes, 1)

    @jax.custom_vjp
    def fused(params, obs_flat):
        flat = _pack_params(params["params"], depth)
        return _run_forward(flat, obs_flat, num_nodes, depth, block_b,
                            interpret, compute_dtype)

    def fused_fwd(params, obs_flat):
        return fused(params, obs_flat), (params, obs_flat)

    def fused_bwd(res, cotangents):
        params, obs_flat = res
        dlog, dval = cotangents
        flat = _pack_params(params["params"], depth)
        grads = _run_backward(
            flat, obs_flat, dlog.astype(jnp.float32),
            dval.astype(jnp.float32), num_nodes, depth, block_b, interpret,
            compute_dtype,
        )
        small = _unpack_grads(params["params"], grads, depth)
        # Observations are env data, never differentiated; zeros keep
        # custom_vjp's signature contract (XLA drops the unused cotangent).
        return {"params": small}, jnp.zeros_like(obs_flat)

    fused.defvjp(fused_fwd, fused_bwd)

    def apply(params, obs):
        from rl_scheduler_tpu.models.heads import apply_with_optional_batch

        def forward(batched_obs):
            b, n, feat = batched_obs.shape
            if n != num_nodes:
                raise ValueError(
                    f"fused set-block kernel was built for num_nodes="
                    f"{num_nodes}; got obs with node axis {n} (rebuild "
                    "the policy at this N — the kernel is shape-"
                    "specialized)"
                )
            flat = batched_obs.reshape(b * n, feat).astype(jnp.float32)
            pad = (-b) % block_b
            if pad:
                flat = jnp.concatenate(
                    [flat, jnp.zeros((pad * n, feat), jnp.float32)], axis=0)
            logits_col, value = fused(params, flat)
            logits = logits_col.reshape(-1, num_nodes)[:b]
            return logits, value[:b, 0]

        return apply_with_optional_batch(forward, obs)

    return apply
