"""Fused Pallas TPU kernels for the set-transformer policy (config 4).

STATUS (round 3): parity-tested but NOT the fast path. The round-2
numbers that motivated these kernels (0.16 ms/minibatch isolated, "55x")
were taken with ``jax.block_until_ready``, which does not synchronize on
the bench backend; measured honestly (fetch-based sync, window slope —
docs/status.md) this kernel suite runs ~48 ms per 32768-row minibatch
vs ~17 ms for the flax module. The measured config-4 fast path is the
batch-minor formulation in ``models/set_fast.py`` (``train_ppo
--fused-set``), which attacks the same layout problem in plain XLA.
These kernels stay as the in-VMEM reference implementation and for the
kernel-authoring techniques documented below.

WHY (round-2 analysis, retained): inside the fused PPO update, each
scanned SGD minibatch of the attention policy compiles to ~970 ops
including ~1.8 ms of pure layout copies, and no XLA-level knob (scan
unroll, shuffle granularity, minibatch shape, lean attention) moved it.
As with the GNN (``ops/pallas_gnn.py``), the escape hatch tried here is
taking layout/fusion decisions away from XLA: one kernel computes the
whole policy per row block with every activation VMEM-resident.

HOW, differently from the GNN kernel: no Kronecker weight blowup. The
node axis lives in the lane dimension as 8 contiguous 64-wide slices of
a flat ``[blk, 512]`` activation, and every per-node op (Dense with the
SHARED weight, LayerNorm, the 8x8 attention pairs) is a static Python
loop over those slices — weights stay at their checkpoint shapes, so
VMEM holds kilobytes of parameters instead of the kron'd megabytes, and
gradients come out in checkpoint shape with no contraction step.

The backward kernel does not hand-derive anything: it recomputes the
forward in VMEM and calls ``jax.vjp`` INSIDE the kernel body (the body
is ordinary traced JAX, so autodiff composes with Pallas), seeding with
the ``(dlogits, dvalue)`` cotangents and accumulating parameter
gradients across the sequential TPU grid. ``jax.custom_vjp`` exposes the
pair as a drop-in differentiable ``apply``.

Parity: numerically equivalent (f32) to ``models.transformer.
SetTransformerPolicy`` with ``num_heads=1`` (the measured-fastest
default) — same parameter tree, forward and gradient agreement tested.
Interpret mode covers the kernels on CPU.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Rows per grid step. The in-kernel vjp keeps every forward intermediate
# of ONE transformer block in VMEM; the per-block backward kernel peaks at
# ~17 MB at 128 rows (1 MB over the 16 MB scoped-vmem limit — measured),
# so 96 is the sweet spot that compiles with headroom.
DEFAULT_BLOCK_B = 96
_LN_EPS = 1e-6


def _slices(x, n, width):
    return [x[:, i * width:(i + 1) * width] for i in range(n)]


def _layer_norm(x64, scale, bias):
    """flax nn.LayerNorm semantics (fast variance, eps 1e-6) on a
    ``[blk, dim]`` per-node slice."""
    mean = jnp.mean(x64, axis=-1, keepdims=True)
    var = jnp.maximum(jnp.mean(x64 * x64, axis=-1, keepdims=True) - mean * mean, 0.0)
    inv = jax.lax.rsqrt(var + _LN_EPS)
    return (x64 - mean) * inv * scale + bias


def _canonical_2d(leaf: jnp.ndarray) -> jnp.ndarray:
    """Pallas TPU refs want 2-D: squeeze the flax MHDPA head axis
    ((64,1,64) / (1,64,64) -> (64,64)) and lift 1-D biases to (1, n)."""
    if leaf.ndim == 3:  # single-head DenseGeneral kernels
        return leaf.reshape(
            leaf.shape[0] * leaf.shape[1], leaf.shape[2]
        ) if leaf.shape[1] == 1 or leaf.shape[0] == 1 else leaf
    if leaf.ndim <= 1:
        return leaf.reshape(1, -1)
    return leaf


def _embed(p: dict, x_flat: jnp.ndarray, num_nodes: int, feat: int):
    """Per-node embed Dense in flat layout (also runs as plain XLA in the
    backward pipeline — a single cheap matmul per node)."""
    mm = functools.partial(jnp.dot, preferred_element_type=jnp.float32)
    w, b = p["embed"]["kernel"], p["embed"]["bias"]
    return jnp.concatenate(
        [mm(s, w) + b for s in _slices(x_flat, num_nodes, feat)], axis=1
    )


def _flat_forward(p: dict, x_flat: jnp.ndarray, num_nodes: int, feat: int,
                  dim: int, depth: int):
    """The SetTransformerPolicy forward in flat-lane layout: embed, then
    the block stack, then the heads — composed from the same functions the
    blockwise backward recomputes, so forward and backward can never
    diverge. ``p`` leaves are canonical 2-D (:func:`_canonical_2d`);
    ``x_flat`` is ``[blk, num_nodes * feat]``; all math f32. Returns
    ``(logits [blk, N], value [blk, 1])``."""
    h = _embed(p, x_flat, num_nodes, feat)
    for bi in range(depth):
        h = _single_block(p[f"block_{bi}"], h, num_nodes, dim)
    return _head_forward(p, h, num_nodes, dim)


def _unflatten(treedef, refs):
    return jax.tree_util.tree_unflatten(treedef, [r[:] for r in refs])


def _fwd_kernel(treedef, num_nodes, feat, dim, depth, obs_ref, *rest):
    w_refs = rest[:-2]
    logits_ref, value_ref = rest[-2:]
    p = _unflatten(treedef, w_refs)
    logits, value = _flat_forward(p, obs_ref[:], num_nodes, feat, dim, depth)
    logits_ref[:] = logits
    value_ref[:] = value


# ---- blockwise backward: Mosaic hits an internal limit somewhere past
# "one transformer block + heads" of reverse-mode chain in a single kernel
# (empirically bisected: block-only and block+heads backward compile; add
# the embed in front, or a second block, and tpu_compile_helper dies). So
# the backward runs as a CHAIN of per-block kernels — classic gradient
# checkpointing at block granularity, with the activation cotangent ``dh``
# handed between kernels through HBM (one [B, N*dim] tensor per boundary,
# still ~10x less traffic than the XLA path's per-op materialization).


def _single_block(p_blk: dict, h: jnp.ndarray, num_nodes: int, dim: int):
    """One pre-LN transformer block in flat layout (weights canonical 2-D)."""
    n = num_nodes
    mm = functools.partial(jnp.dot, preferred_element_type=jnp.float32)

    def node_dense(h_flat, w, b, in_w):
        return jnp.concatenate(
            [mm(s, w) + b for s in _slices(h_flat, n, in_w)], axis=1
        )

    def node_ln(h_flat, ln):
        return jnp.concatenate(
            [_layer_norm(s, ln["scale"], ln["bias"])
             for s in _slices(h_flat, n, dim)],
            axis=1,
        )

    attn = p_blk["MultiHeadDotProductAttention_0"]
    hn = node_ln(h, p_blk["LayerNorm_0"])
    q = node_dense(hn, attn["query"]["kernel"], attn["query"]["bias"], dim)
    k = node_dense(hn, attn["key"]["kernel"], attn["key"]["bias"], dim)
    v = node_dense(hn, attn["value"]["kernel"], attn["value"]["bias"], dim)
    qs, ks, vs = (_slices(t, n, dim) for t in (q, k, v))
    scale = dim ** -0.5
    outs = []
    for i in range(n):
        scores = jnp.concatenate(
            [jnp.sum(qs[i] * ks[j], axis=-1, keepdims=True) * scale
             for j in range(n)],
            axis=1,
        )
        probs = jax.nn.softmax(scores, axis=-1)
        o = probs[:, 0:1] * vs[0]
        for j in range(1, n):
            o = o + probs[:, j:j + 1] * vs[j]
        outs.append(o)
    a = node_dense(jnp.concatenate(outs, axis=1),
                   attn["out"]["kernel"], attn["out"]["bias"], dim)
    h = h + a
    m = node_ln(h, p_blk["LayerNorm_1"])
    m = node_dense(m, p_blk["Dense_0"]["kernel"], p_blk["Dense_0"]["bias"], dim)
    m = jax.nn.gelu(m)
    m = jnp.concatenate(
        [mm(s, p_blk["Dense_1"]["kernel"]) + p_blk["Dense_1"]["bias"]
         for s in _slices(m, num_nodes, 2 * dim)],
        axis=1,
    )
    return h + m


def _head_forward(p: dict, h: jnp.ndarray, num_nodes: int, dim: int):
    """final_norm + pointer/value heads in flat layout."""
    n = num_nodes
    mm = functools.partial(jnp.dot, preferred_element_type=jnp.float32)
    x = jnp.concatenate(
        [_layer_norm(s, p["final_norm"]["scale"], p["final_norm"]["bias"])
         for s in _slices(h, n, dim)],
        axis=1,
    )
    head = p["head"]
    logits = jnp.concatenate(
        [mm(s, head["score_head"]["kernel"]) + head["score_head"]["bias"]
         for s in _slices(x, n, dim)],
        axis=1,
    )
    pooled = sum(_slices(x, n, dim)) / n
    v1 = jnp.tanh(mm(pooled, head["value_hidden"]["kernel"])
                  + head["value_hidden"]["bias"])
    value = mm(v1, head["value_head"]["kernel"]) + head["value_head"]["bias"]
    return logits, value


def _block_fwd_kernel(treedef, num_nodes, dim, h_ref, *rest):
    w_refs = rest[:-1]
    out_ref = rest[-1]
    p_blk = _unflatten(treedef, w_refs)
    out_ref[:] = _single_block(p_blk, h_ref[:], num_nodes, dim)


def _block_bwd_kernel(treedef, num_nodes, dim, h_ref, *rest):
    # call order: (h, *weights, dh_out) inputs, then (dh_in, *grads) outputs
    n_w = treedef.num_leaves
    w_refs = rest[:n_w]
    dh_out_ref = rest[n_w]
    dh_in_ref = rest[n_w + 1]
    grad_refs = rest[n_w + 2:]

    @pl.when(pl.program_id(0) == 0)
    def _():
        for g in grad_refs:
            g[:] = jnp.zeros_like(g)

    p_blk = _unflatten(treedef, w_refs)
    h = h_ref[:]

    def f(h, p_blk):
        return _single_block(p_blk, h, num_nodes, dim)

    _, vjp = jax.vjp(f, h, p_blk)
    dh, gp = vjp(dh_out_ref[:])
    dh_in_ref[:] = dh
    for g_ref, g in zip(grad_refs, jax.tree_util.tree_leaves(gp)):
        g_ref[:] += g


def _head_bwd_kernel(treedef, num_nodes, dim, h_ref, dlogits_ref, dvalue_ref,
                     *rest):
    # call order: (h, dlogits, dvalue, *weights) inputs, then
    # (dh, *grads) outputs
    n_w = treedef.num_leaves
    w_refs = rest[:n_w]
    dh_ref = rest[n_w]
    grad_refs = rest[n_w + 1:]

    @pl.when(pl.program_id(0) == 0)
    def _():
        for g in grad_refs:
            g[:] = jnp.zeros_like(g)

    p = _unflatten(treedef, w_refs)
    h = h_ref[:]

    def f(h, p):
        return _head_forward(p, h, num_nodes, dim)

    _, vjp = jax.vjp(f, h, p)
    dh, gp = vjp((dlogits_ref[:], dvalue_ref[:]))
    dh_ref[:] = dh
    for g_ref, g in zip(grad_refs, jax.tree_util.tree_leaves(gp)):
        g_ref[:] += g


def make_fused_set_apply(
    num_nodes: int = 8,
    feat: int = 6,
    dim: int = 64,
    depth: int = 2,
    block_b: int = DEFAULT_BLOCK_B,
    interpret: bool | None = None,
):
    """Build a differentiable ``apply(params, obs) -> (logits, value)``
    running the fused kernels. ``params`` is a ``SetTransformerPolicy``
    (num_heads=1) tree; ``obs`` is ``[B, N, feat]`` or unbatched."""
    if interpret is None:
        from rl_scheduler_tpu.ops.gae import default_platform

        interpret = default_platform() != "tpu"

    def full_spec(_):
        return pl.BlockSpec(memory_space=pltpu.VMEM)

    width = num_nodes * dim
    row = lambda cols: pl.BlockSpec((block_b, cols), lambda i: (i, 0),
                                    memory_space=pltpu.VMEM)
    acc = lambda l: pl.BlockSpec(l.shape, lambda i: (0, 0),
                                 memory_space=pltpu.VMEM)

    def _canon_tree(tree):
        canon = jax.tree.map(
            lambda l: _canonical_2d(l.astype(jnp.float32)), tree
        )
        bad = [
            jax.tree_util.keystr(path)
            for path, leaf in jax.tree_util.tree_leaves_with_path(canon)
            if leaf.ndim > 2
        ]
        if bad:
            # A num_heads>1 tree's q/k/v kernels stay 3-D after
            # canonicalization; failing here names the real constraint
            # instead of surfacing as an obscure rank error deep inside
            # the Pallas trace.
            raise ValueError(
                f"fused set kernels are single-head (num_heads=1); these "
                f"parameter leaves are still 3-D after canonicalization: "
                f"{bad}. Re-train with num_heads=1 or use the flax policy."
            )
        return canon

    def _run_block_fwd(blk_tree, h):
        leaves, treedef = jax.tree_util.tree_flatten(blk_tree)
        return pl.pallas_call(
            functools.partial(_block_fwd_kernel, treedef, num_nodes, dim),
            grid=(h.shape[0] // block_b,),
            in_specs=[row(width)] + [full_spec(l) for l in leaves],
            out_specs=row(width),
            out_shape=jax.ShapeDtypeStruct(h.shape, jnp.float32),
            interpret=interpret,
        )(h, *leaves)

    def _run_block_bwd(blk_tree, h, dh_out):
        leaves, treedef = jax.tree_util.tree_flatten(blk_tree)
        outs = pl.pallas_call(
            functools.partial(_block_bwd_kernel, treedef, num_nodes, dim),
            grid=(h.shape[0] // block_b,),
            in_specs=[row(width)] + [full_spec(l) for l in leaves]
            + [row(width)],
            out_specs=[row(width)] + [acc(l) for l in leaves],
            out_shape=[jax.ShapeDtypeStruct(h.shape, jnp.float32)]
            + [jax.ShapeDtypeStruct(l.shape, jnp.float32) for l in leaves],
            interpret=interpret,
        )(h, *leaves, dh_out)
        dh_in = outs[0]
        g_tree = jax.tree_util.tree_unflatten(treedef, outs[1:])
        return g_tree, dh_in

    def _run_head_bwd(head_tree, h, dlogits, dvalue):
        leaves, treedef = jax.tree_util.tree_flatten(head_tree)
        outs = pl.pallas_call(
            functools.partial(_head_bwd_kernel, treedef, num_nodes, dim),
            grid=(h.shape[0] // block_b,),
            in_specs=[row(width), row(num_nodes), row(1)]
            + [full_spec(l) for l in leaves],
            out_specs=[row(width)] + [acc(l) for l in leaves],
            out_shape=[jax.ShapeDtypeStruct(h.shape, jnp.float32)]
            + [jax.ShapeDtypeStruct(l.shape, jnp.float32) for l in leaves],
            interpret=interpret,
        )(h, dlogits, dvalue, *leaves)
        dh = outs[0]
        g_tree = jax.tree_util.tree_unflatten(treedef, outs[1:])
        return g_tree, dh

    @jax.custom_vjp
    def fused(params, obs_flat):
        canon = _canon_tree(params["params"])
        leaves, treedef = jax.tree_util.tree_flatten(canon)
        b = obs_flat.shape[0]
        logits, value = pl.pallas_call(
            functools.partial(_fwd_kernel, treedef, num_nodes, feat, dim, depth),
            grid=(b // block_b,),
            in_specs=[row(num_nodes * feat)] + [full_spec(l) for l in leaves],
            out_specs=[row(num_nodes), row(1)],
            out_shape=[
                jax.ShapeDtypeStruct((b, num_nodes), jnp.float32),
                jax.ShapeDtypeStruct((b, 1), jnp.float32),
            ],
            interpret=interpret,
        )(obs_flat, *leaves)
        return logits, value

    def fused_fwd(params, obs_flat):
        return fused(params, obs_flat), (params, obs_flat)

    def fused_bwd(res, cotangents):
        params, obs_flat = res
        dlogits = cotangents[0].astype(jnp.float32)
        dvalue = cotangents[1].astype(jnp.float32)
        canon = _canon_tree(params["params"])

        # Prefix recompute: embed in plain XLA (one matmul per node), then
        # each block as its own fwd kernel — gradient checkpointing at
        # block granularity, forced by the Mosaic chain-length limit.
        hs = [_embed(canon, obs_flat, num_nodes, feat)]
        for bi in range(depth):
            hs.append(_run_block_fwd(canon[f"block_{bi}"], hs[-1]))

        head_tree = {"final_norm": canon["final_norm"], "head": canon["head"]}
        g_head, dh = _run_head_bwd(head_tree, hs[depth], dlogits, dvalue)
        grads = dict(g_head)
        for bi in reversed(range(depth)):
            g_blk, dh = _run_block_bwd(canon[f"block_{bi}"], hs[bi], dh)
            grads[f"block_{bi}"] = g_blk

        # Embed gradients in XLA from the final activation cotangent.
        def embed_fn(embed_tree):
            return _embed({"embed": embed_tree}, obs_flat, num_nodes, feat)

        _, evjp = jax.vjp(embed_fn, canon["embed"])
        (grads["embed"],) = evjp(dh)

        # Un-canonicalize: reshape each 2-D grad back to checkpoint shape.
        gp = jax.tree.map(
            lambda g, l: g.reshape(l.shape).astype(l.dtype),
            grads, params["params"],
        )
        return {"params": gp}, jnp.zeros_like(obs_flat)

    fused.defvjp(fused_fwd, fused_bwd)

    def apply(params, obs):
        from rl_scheduler_tpu.models.heads import apply_with_optional_batch

        def forward(batched_obs):
            b = batched_obs.shape[0]
            flat = batched_obs.reshape(b, num_nodes * feat).astype(jnp.float32)
            pad = (-b) % block_b
            if pad:
                flat = jnp.concatenate(
                    [flat, jnp.zeros((pad, flat.shape[1]), jnp.float32)],
                    axis=0,
                )
            logits, value = fused(params, flat)
            return logits[:b], value[:b, 0]

        return apply_with_optional_batch(forward, obs)

    return apply


class FusedSetPolicy:
    """Drop-in for ``SetTransformerPolicy`` (num_heads=1) with the fused
    Pallas forward/backward on the HOT path. ``init`` delegates to the
    reference module so parameter trees (and checkpoints) are identical.

    NOT WIRED to the train CLI, deliberately: honestly timed (round 3,
    module docstring) the kernel path LOSES to both the flax module and
    the batch-minor fast path (``models/set_fast.py``) on the bench
    backend — the round-2 in-situ regression (3.7 s vs 0.9 s per update)
    was real, and re-measurement with trustworthy sync shows the isolated
    "win" was a timing artifact. Anyone considering wiring this in must
    re-measure with fetch-based sync first.

    ``apply`` dispatches by batch size: SGD minibatches (>=
    ``min_fused_batch`` rows) run through the kernels; the rollout's
    per-step forwards stay on the reference module. Both paths compute
    the same function (parity-tested), so this is purely a
    compilation-strategy switch.
    """

    num_heads = 1  # the train CLI's resume guard reads this

    def __init__(self, num_nodes: int = 8, feat: int = 6, dim: int = 64,
                 depth: int = 2, block_b: int = DEFAULT_BLOCK_B,
                 interpret: bool | None = None,
                 min_fused_batch: int = 16384):
        from rl_scheduler_tpu.models import SetTransformerPolicy

        self.inner = SetTransformerPolicy(dim=dim, depth=depth, num_heads=1)
        self.dim = dim
        self.depth = depth
        self.min_fused_batch = min_fused_batch
        self._apply = make_fused_set_apply(
            num_nodes, feat, dim, depth, block_b, interpret
        )

    def init(self, key, obs):
        return self.inner.init(key, obs)

    def apply(self, params, obs):
        batched = obs.ndim == 3
        if (batched and obs.shape[0] >= self.min_fused_batch) or not batched:
            if not batched:
                return self.inner.apply(params, obs)
            return self._apply(params, obs)
        return self.inner.apply(params, obs)
