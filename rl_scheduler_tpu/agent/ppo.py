"""PPO with on-device rollout collection: sampler and learner in one program.

Ray-free re-design of the reference's training stack (``train_ppo.py``,
``train_final.py``): where RLlib ships experience from 6 rollout-worker
processes to a driver over the object store, here the vmapped env, the
policy, GAE, and the minibatch SGD epochs are a single jitted function —
one XLA program per training iteration, no host round-trips. The same
function pspec-shards over a device mesh for data parallelism
(``parallel/``).

Hyperparameter semantics mirror RLlib PPO so the reference's named presets
(batch 4000/256/10 @ lr 3e-4 γ 0.99; batch 8000/512/15 @ lr 5e-4 γ 0.995)
behave comparably: GAE(λ=0.95... RLlib default lambda=1.0 — presets set it),
clipped surrogate (0.3), clipped value loss (10.0), advantage normalization
per minibatch, epoch-wise reshuffling.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
import warnings
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

from rl_scheduler_tpu.env import core as env_core
from rl_scheduler_tpu.env.bundle import EnvBundle, multi_cloud_bundle
from rl_scheduler_tpu.models import ActorCritic
from rl_scheduler_tpu.ops import gae as gae_op
from rl_scheduler_tpu.ops.gae import resolve_impl as resolve_gae_impl
from rl_scheduler_tpu.ops.indexing import (
    gather_shuffled_minibatch,
    shuffle_block_perm,
)
from rl_scheduler_tpu.ops.losses import PPOLossConfig, ppo_loss, categorical_log_prob


@dataclasses.dataclass(frozen=True)
class PPOTrainConfig:
    num_envs: int = 64
    rollout_steps: int = 64          # train batch = num_envs * rollout_steps
    minibatch_size: int = 256
    num_epochs: int = 10             # RLlib num_sgd_iter
    lr: float = 3e-4
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.3
    vf_clip: float = 10.0
    vf_coeff: float = 1.0
    entropy_coeff: float = 0.0
    max_grad_norm: float | None = None  # RLlib default: no grad clip
    hidden: tuple = (256, 256)
    gae_impl: str = "auto"           # scan | pallas | auto (pallas on TPU)
    compute_dtype: str = "float32"   # float32 | bfloat16 (torso matmuls)
    # scan: sequential lax.scan rollout (works for every env).
    # open_loop: vectorize the whole horizon — obs + rewards batched over
    #   [T, N], policy applied as ONE forward (only for envs exporting a
    #   bundle horizon_fn; ~2x faster rollout on TPU).
    # auto: open_loop when the bundle supports it, scan otherwise.
    rollout_impl: str = "auto"       # scan | open_loop | auto
    # lax.scan unroll factor for the SGD minibatch loop — an XLA tuning
    # lever: unrolling lets the compiler fuse/lay out minibatch steps like
    # straight-line code instead of a conservative while-loop body. In
    # isolation this recovered a 20x gap for the attention policy, but in
    # the full fused update it measured near-neutral on every config (the
    # layout pathology there is driven by the surrounding program, not the
    # loop structure — see the config-4 note in docs/status.md). Kept as a
    # knob because the effect is context/compiler-version dependent; costs
    # compile time roughly linearly.
    sgd_unroll: int = 1
    # In-training periodic evaluation (reference train_final.py:19:
    # evaluation_interval=5, evaluation_duration=20): every eval_every
    # iterations, run eval_episodes greedy episodes and report
    # eval_episode_reward_mean. 0 disables.
    eval_every: int = 0
    eval_episodes: int = 20
    # Anti-latch interventions (ROADMAP 3b, docs/studies.md), both off by
    # default (byte-identical update when inactive):
    #
    # Sampling-temperature annealing: the rollout's action sampling (and,
    # consistently, the behavior log-probs and the loss's policy) uses
    # softmax(logits / tau), tau annealed linearly from 1.0 at iteration
    # 0 to sample_temp_end at iteration sample_temp_iters (held there
    # after; sample_temp_iters=0 holds sample_temp_end from the start).
    # tau < 1 moves the TRAINING distribution toward the argmax the
    # greedy eval will score — the measured failure mode is a
    # near-uniform sampler earning the spread bonus "for free" while its
    # argmax latched onto one static node premium. The same tau is used
    # everywhere within one iteration, so each iteration is exact PPO on
    # the tempered policy. Active iff sample_temp_end != 1.0.
    sample_temp_end: float = 1.0
    sample_temp_iters: int = 0
    # Argmax-concentration auxiliary penalty: coeff on
    # ops/losses.argmax_concentration (collision probability of the
    # batch-pooled sharpened policy). See PPOLossConfig.
    argmax_penalty_coeff: float = 0.0
    argmax_penalty_sharpness: float = 16.0
    # graftpipe (docs/roofline.md): pipeline collect against learn. The
    # rollout of iteration k+1 is collected with the PRE-update params of
    # iteration k (a 1-iteration-stale behavior policy — PPO's off-policy
    # correction is exact because behavior log-probs are recorded at
    # collect time), so inside a lax.scan-over-updates program the
    # rollout of k+1 has NO data dependency on SGD k and XLA's
    # latency-hiding scheduler can overlap them. Off (the default) leaves
    # the update byte-identical to the unpipelined build; on, the runner
    # carries the in-flight stale-params slot (RunnerState.collect_params,
    # checkpoint-meta-recorded and --resume-guard-pinned).
    overlap_collect: bool = False
    # The fused update prologue (second graftpipe prong): collapse the
    # between-rollout-and-SGD op chain — the epoch-shuffle permutation
    # (argsort over one draw of random bits, ops/indexing.py
    # shuffle_block_perm) fused with the per-minibatch gather
    # (gather_shuffled_minibatch) — into the head of the SGD scan, so the
    # full shuffled [B, K] batch is never materialized (one HBM write +
    # read per epoch gone) and GAE at fleet env counts routes through the
    # one-launch Pallas kernel (ops/pallas_gae.py; interpret-mode
    # fallback keeps the same path correct on CPU). "auto" follows
    # overlap_collect; "on"/"off" pin it for per-prong A/Bs
    # (loadgen/set_scale_bench.py). The permutation VALUES differ from
    # jax.random.permutation's, so this must stay off for the
    # byte-identical default path.
    fused_prologue: str = "auto"     # auto | on | off
    # Epoch-shuffle granularity: permute contiguous blocks of this many
    # samples instead of single rows. Blocks are adjacent envs at one
    # timestep (iid rollouts), so statistics are indistinguishable for
    # minibatches thousands of blocks wide, while the gather moves
    # tile-aligned chunks — profiled ~100x faster than the row-granular
    # gather at 4096x100. Applied only when each minibatch still spans
    # >= 1024 blocks (small configs keep the exact per-sample shuffle:
    # their gathers are cheap anyway and coarse mixing measurably slows
    # small-batch convergence); also falls back to exact when the block
    # does not divide the batch/minibatch sizes. Set 1 to force exact.
    shuffle_block_size: int = 8

    def __post_init__(self):
        # Zero epochs would scan over zero SGD passes: training "completes"
        # while never updating parameters. Guard at construction so every
        # entry point (CLI, tests, notebooks) fails loudly up front.
        if self.num_epochs < 1:
            raise ValueError(
                f"num_epochs={self.num_epochs}: must be >= 1 (each update "
                "needs at least one SGD pass over the rollout)"
            )
        if self.sample_temp_end <= 0:
            raise ValueError(
                f"sample_temp_end={self.sample_temp_end}: the sampling "
                "temperature must stay positive (tau -> 0 is the argmax "
                "limit; reach toward it, never at it)"
            )
        if self.sample_temp_iters < 0:
            raise ValueError(
                f"sample_temp_iters={self.sample_temp_iters}: the anneal "
                "span is an iteration count >= 0 (0 holds the end "
                "temperature from the start)"
            )
        if self.argmax_penalty_coeff < 0:
            raise ValueError(
                f"argmax_penalty_coeff={self.argmax_penalty_coeff}: the "
                "concentration penalty is a loss weight >= 0 (0 disables)"
            )
        if self.argmax_penalty_sharpness <= 0:
            raise ValueError(
                f"argmax_penalty_sharpness={self.argmax_penalty_sharpness}: "
                "the soft-argmax logit multiplier must be positive"
            )
        if self.fused_prologue not in ("auto", "on", "off"):
            raise ValueError(
                f"fused_prologue={self.fused_prologue!r}: choose "
                "auto|on|off (auto follows overlap_collect)"
            )

    @property
    def batch_size(self) -> int:
        return self.num_envs * self.rollout_steps

    @property
    def prologue_enabled(self) -> bool:
        if self.fused_prologue == "auto":
            return self.overlap_collect
        return self.fused_prologue == "on"

    @property
    def num_minibatches(self) -> int:
        return max(1, self.batch_size // self.minibatch_size)

    def loss_config(self) -> PPOLossConfig:
        return PPOLossConfig(
            clip_eps=self.clip_eps,
            vf_clip=self.vf_clip,
            vf_coeff=self.vf_coeff,
            entropy_coeff=self.entropy_coeff,
            argmax_penalty_coeff=self.argmax_penalty_coeff,
            argmax_penalty_sharpness=self.argmax_penalty_sharpness,
        )


def sample_temperature(cfg: PPOTrainConfig, update_idx) -> jnp.ndarray | None:
    """The rollout sampling temperature for the iteration at ``update_idx``
    (a traced scalar), or ``None`` when annealing is inactive
    (``sample_temp_end == 1.0`` — the None path leaves the update
    byte-identical to the un-instrumented build).

    Linear ramp 1.0 -> ``sample_temp_end`` over ``sample_temp_iters``
    iterations, held at the end value after (``sample_temp_iters == 0``
    holds the end value from iteration 0).
    """
    if cfg.sample_temp_end == 1.0:
        return None
    end = jnp.float32(cfg.sample_temp_end)
    if cfg.sample_temp_iters <= 0:
        return end
    frac = jnp.clip(
        jnp.asarray(update_idx, jnp.float32) / cfg.sample_temp_iters,
        0.0, 1.0)
    return 1.0 + (end - 1.0) * frac


def effective_shuffle_block(cfg: PPOTrainConfig) -> int:
    """The epoch-shuffle block size that will actually be used.

    Falls back to 1 (exact per-sample shuffle) unless the block divides the
    batch, the minibatch, AND ``num_envs`` (the flat batch is timestep-major,
    so env-divisibility is what keeps a block inside one timestep — blocks
    straddling timesteps would weld consecutive correlated transitions of
    the same trajectories together), and each minibatch still spans >= 1024
    blocks (see ``PPOTrainConfig.shuffle_block_size``).
    """
    blk = max(1, cfg.shuffle_block_size)
    mb_size = min(cfg.minibatch_size, cfg.batch_size)
    if (
        cfg.batch_size % blk
        or mb_size % blk
        or cfg.num_envs % blk
        or mb_size // blk < 1024
    ):
        return 1
    return blk


# Env count above which the fused prologue routes an "auto" GAE through
# the one-launch Pallas kernel even when the default device is not TPU
# (interpret mode keeps it correct on CPU): at fleet env counts the
# reverse scan's T tiny loop bodies are the term the prologue exists to
# collapse, and the kernel's 512-lane column blocks are full.
PROLOGUE_GAE_MIN_ENVS = 512


def resolve_prologue_gae_impl(cfg: PPOTrainConfig) -> str:
    """GAE impl for the fused-prologue path: an explicit ``cfg.gae_impl``
    is respected; ``"auto"`` routes fleet shapes (``num_envs >=
    PROLOGUE_GAE_MIN_ENVS``) through ``ops/pallas_gae.py`` — on CPU via
    its interpret fallback — and keeps the scan elsewhere (small column
    counts underfill the kernel's blocks)."""
    if cfg.gae_impl != "auto":
        return resolve_gae_impl(cfg.gae_impl)
    if cfg.num_envs >= PROLOGUE_GAE_MIN_ENVS:
        return "pallas"
    return resolve_gae_impl("auto")


class RunnerState(NamedTuple):
    """Everything carried across training iterations (a single pytree).

    ``collect_params`` is graftpipe's in-flight stale-params slot
    (``PPOTrainConfig.overlap_collect``): the params the NEXT rollout will
    sample with — one iteration staler than ``params`` once the pipeline
    is warm. ``None`` when overlap is off, which is an EMPTY pytree node:
    the runner's leaves (and therefore checkpoints, donation, and the
    sharded-path specs) are unchanged from the pre-graftpipe layout.
    """

    params: Any
    opt_state: Any
    env_state: Any            # batched EnvState
    obs: jnp.ndarray          # [N, OBS_DIM]
    key: jnp.ndarray
    ep_return: jnp.ndarray    # [N] running episode return accumulator
    update_idx: jnp.ndarray   # scalar int32
    collect_params: Any = None  # graftpipe 1-iteration-stale behavior slot


def make_optimizer(cfg: PPOTrainConfig) -> optax.GradientTransformation:
    tx = optax.adam(cfg.lr, eps=1e-7)
    if cfg.max_grad_norm is not None:
        tx = optax.chain(optax.clip_by_global_norm(cfg.max_grad_norm), tx)
    return tx


def make_ppo_bundle(
    bundle: EnvBundle,
    cfg: PPOTrainConfig,
    net: Any | None = None,
    axis_name: str | None = None,
    tx: optax.GradientTransformation | None = None,
    scope: Any | None = None,
) -> tuple[Callable, Callable, Any]:
    """Build ``(init_fn, update_fn, net)`` for ANY :class:`EnvBundle`.

    ``scope``: a graftscope :class:`~rl_scheduler_tpu.utils.metrics.
    MetricsSpec`. When set, the update also computes device-resident
    distribution metrics — advantage/reward/value stats + histograms,
    per-minibatch grad norms, the PPO ratio histogram (bucketized inside
    the SGD scan), per-action counts — and returns them under the
    ``"graftscope"`` metrics key as a :data:`MetricsState` pytree. The
    host loop merges those states on device and fetches ONE summary per
    logging window (``utils/metrics.ScopeSession``); nothing here ever
    syncs. ``None`` (the default) leaves the update byte-identical to the
    un-instrumented build.

    ``init_fn(key) -> RunnerState``; ``update_fn(runner) -> (runner, metrics)``
    is pure and jit/shard_map-safe — it performs one full PPO iteration:
    ``rollout_steps`` vmapped env steps, GAE, ``num_epochs`` passes of
    minibatched SGD. With ``axis_name`` set, gradients (and reported metrics)
    are pmean-reduced over that mesh axis — the data-parallel path used by
    ``parallel/sharding.py``; ``cfg.num_envs`` is then the per-device count.

    ``tx`` overrides the optimizer (default :func:`make_optimizer` from the
    config) — the tensor-parallel path passes a tp-aware clip chain whose
    global norm psums sharded leaves over the ``tp`` axis.

    The policy ``net`` must map an observation batch ``[B, *obs_shape]`` to
    ``(logits [B, num_actions], value [B])`` — MLPs over flat obs and
    set-transformer / GNN policies over structured obs all fit.
    """
    if scope is not None:
        from rl_scheduler_tpu.utils.metrics import validate_spec

        # Build-time, so a custom spec naming a stream this trainer does
        # not produce fails with the available names spelled out instead
        # of a KeyError from inside the first traced update.
        validate_spec(
            scope,
            values=("advantage", "reward", "value", "action", "grad_norm"),
            counts=("ratio",), context="make_ppo_bundle(scope=...)")
    compute_dtypes = {"float32": None, "bfloat16": jnp.bfloat16}
    if cfg.compute_dtype not in compute_dtypes:
        raise ValueError(
            f"unknown compute_dtype {cfg.compute_dtype!r}; "
            f"choose from {sorted(compute_dtypes)}"
        )
    if cfg.sgd_unroll < 1:
        raise ValueError(
            f"sgd_unroll={cfg.sgd_unroll}: must be >= 1 (a silently clamped "
            "value would make the knob appear engaged when it is not)"
        )
    if (net is not None and cfg.compute_dtype != "float32"
            and getattr(net, "dtype", None) is None):
        # A custom net owns its own precision (SetTransformerPolicy/
        # GNNPolicy take a dtype field); the config knob only shapes the
        # default ActorCritic — warn when the custom net did NOT get a
        # dtype of its own rather than silently ignore the config.
        import logging

        logging.getLogger(__name__).warning(
            "compute_dtype=%s has no effect on a custom net=%s; set the "
            "net's own dtype field instead", cfg.compute_dtype, type(net).__name__
        )
    net = net or ActorCritic(
        num_actions=bundle.num_actions,
        hidden=cfg.hidden,
        dtype=compute_dtypes[cfg.compute_dtype],
    )
    tx = tx if tx is not None else make_optimizer(cfg)
    obs_shape = tuple(bundle.obs_shape)

    def init_fn(key: jnp.ndarray) -> RunnerState:
        pkey, ekey, rkey = jax.random.split(key, 3)
        dummy = jnp.zeros((1, *obs_shape), jnp.float32)
        params = net.init(pkey, dummy)
        opt_state = tx.init(params)
        env_state, obs = bundle.reset_batch(ekey, cfg.num_envs)
        collect_params = None
        if cfg.overlap_collect:
            # Pipeline warm-up: iteration 0 collects on-policy (slot ==
            # params); staleness starts at iteration 1. Copied leaves so
            # the donated runner never hands XLA the same buffer twice.
            collect_params = jax.tree.map(jnp.copy, params)
        return RunnerState(
            params=params,
            opt_state=opt_state,
            env_state=env_state,
            obs=obs,
            key=rkey,
            ep_return=jnp.zeros(cfg.num_envs, jnp.float32),
            update_idx=jnp.zeros((), jnp.int32),
            collect_params=collect_params,
        )

    def rollout(runner: RunnerState, behavior_params):
        """Collect [T, N] transitions with the behavior policy via lax.scan."""
        temp = sample_temperature(cfg, runner.update_idx)

        def env_step(carry, _):
            env_state, obs, key, ep_ret = carry
            key, akey = jax.random.split(key)
            logits, value = net.apply(behavior_params, obs)
            if temp is not None:
                # Tempered BEHAVIOR policy: sampling and the stored
                # log-probs use the same softmax(logits / tau) the loss
                # recomputes, so the PPO ratio stays exactly on-policy.
                logits = logits / temp
            action = jax.random.categorical(akey, logits)
            log_prob = categorical_log_prob(logits, action)
            env_state, ts = bundle.step_batch(env_state, action)
            new_ep_ret = ep_ret + ts.reward
            done_f = ts.done.astype(jnp.float32)
            transition = {
                "obs": obs,
                "action": action,
                "log_prob": log_prob,
                "value": value,
                "reward": ts.reward,
                "done": done_f,
                # episode return realized at terminal steps (0 elsewhere)
                "final_return": new_ep_ret * done_f,
            }
            ep_ret = new_ep_ret * (1.0 - done_f)
            return (env_state, ts.obs, key, ep_ret), transition

        (env_state, obs, key, ep_ret), traj = jax.lax.scan(
            env_step,
            (runner.env_state, runner.obs, runner.key, runner.ep_return),
            None,
            length=cfg.rollout_steps,
        )
        _, last_value = net.apply(behavior_params, obs)
        return env_state, obs, key, ep_ret, traj, last_value

    def rollout_open_loop(runner: RunnerState, behavior_params):
        """Whole-horizon rollout without a scan (open-loop envs only).

        Obs for all T+1 steps come from one ``horizon_fn`` call; the policy
        runs as ONE ``(T+1)*N`` forward (which also yields the bootstrap
        value for free); actions, log-probs, and rewards are batched over
        ``[T, N]``. Only the O(T·N)-add episode-return bookkeeping scans.
        """
        t = cfg.rollout_steps
        key, hkey, akey = jax.random.split(runner.key, 3)
        obs_all, aux, env_state = bundle.horizon_fn(
            runner.env_state, runner.obs, hkey, t
        )
        n = obs_all.shape[1]
        logits, values = net.apply(
            behavior_params, obs_all.reshape((t + 1) * n, *obs_shape)
        )
        logits = logits.reshape(t + 1, n, -1)
        values = values.reshape(t + 1, n)
        temp = sample_temperature(cfg, runner.update_idx)
        behavior_logits = logits[:t] if temp is None else logits[:t] / temp
        action = jax.random.categorical(akey, behavior_logits)
        log_prob = categorical_log_prob(behavior_logits, action)
        reward = bundle.horizon_reward_fn(aux, action)
        done = aux["dones"]

        def book(ep_ret, xs):
            r, d = xs
            new_ret = ep_ret + r
            return new_ret * (1.0 - d), new_ret * d

        ep_ret, final_return = jax.lax.scan(
            book, runner.ep_return, (reward, done)
        )
        traj = {
            "obs": obs_all[:t],
            "action": action,
            "log_prob": log_prob,
            "value": values[:t],
            "reward": reward,
            "done": done,
            "final_return": final_return,
        }
        return env_state, obs_all[t], key, ep_ret, traj, values[t]

    has_horizon = (
        bundle.horizon_fn is not None and bundle.horizon_reward_fn is not None
    )
    if bundle.horizon_fn is not None and bundle.horizon_reward_fn is None:
        raise ValueError(
            f"bundle {bundle.name!r} sets horizon_fn without "
            "horizon_reward_fn; the open-loop contract needs both"
        )
    if cfg.rollout_impl == "open_loop" and not has_horizon:
        raise ValueError(
            f"rollout_impl='open_loop' needs an env with a horizon_fn; "
            f"bundle {bundle.name!r} has none (use 'scan' or 'auto')"
        )
    if cfg.rollout_impl not in ("scan", "open_loop", "auto"):
        raise ValueError(
            f"unknown rollout_impl {cfg.rollout_impl!r}; "
            "choose scan|open_loop|auto"
        )
    use_open_loop = cfg.rollout_impl == "open_loop" or (
        cfg.rollout_impl == "auto" and has_horizon
    )
    collect = rollout_open_loop if use_open_loop else rollout

    def update_fn(runner: RunnerState):
        # named_scope: zero-cost trace annotations that let
        # tools/traceview attribute profiler events to training phases.
        # graftpipe: the pipelined rollout samples with the 1-iteration-
        # stale collect_params slot instead of the post-SGD params, so
        # inside a scan-over-updates program iteration k+1's rollout has
        # no data dependency on SGD k (its own scope name keeps traceview
        # attribution honest about which path ran).
        if cfg.overlap_collect:
            with jax.named_scope("overlap_collect"):
                env_state, obs, key, ep_ret, traj, last_value = collect(
                    runner, runner.collect_params)
        else:
            with jax.named_scope("rollout"):
                env_state, obs, key, ep_ret, traj, last_value = collect(
                    runner, runner.params)

        # The fused prologue owns the whole between-rollout-and-SGD chain
        # under one trace phase ("prologue": GAE + pack here, permutation
        # + minibatch gather in the scan head below); the classic path
        # keeps its historical scopes (gae around GAE only) so baseline
        # trace attribution is unchanged.
        gae_scope = "prologue" if cfg.prologue_enabled else "gae"
        with jax.named_scope(gae_scope):
            advantages, targets = gae_op(
                traj["reward"], traj["value"], traj["done"], last_value,
                cfg.gamma, cfg.gae_lambda,
                impl=(resolve_prologue_gae_impl(cfg)
                      if cfg.prologue_enabled else cfg.gae_impl),
            )

        # Pack every per-sample field into ONE [B, K] f32 matrix. The epoch
        # shuffle then needs a single 2-D row gather instead of six 1-D
        # gathers — TPUs execute long 1-D random gathers element-wise, and
        # a profile showed them costing ~60% of the whole update at 4096
        # envs (6 fields x ~3 ms per epoch); the packed row gather is
        # tile-efficient. The action column round-trips through f32
        # exactly (action indices are tiny integers).
        flat_obs_dim = math.prod(obs_shape)
        with (jax.named_scope("prologue") if cfg.prologue_enabled
              else contextlib.nullcontext()):
            packed = jnp.concatenate(
                [
                    traj["obs"].reshape(-1, flat_obs_dim).astype(jnp.float32),
                    traj["action"].reshape(-1, 1).astype(jnp.float32),
                    traj["log_prob"].reshape(-1, 1),
                    traj["value"].reshape(-1, 1),
                    advantages.reshape(-1, 1),
                    targets.reshape(-1, 1),
                ],
                axis=1,
            )

        def unpack(rows):
            return {
                "obs": rows[:, :flat_obs_dim].reshape(-1, *obs_shape),
                "action": rows[:, flat_obs_dim].astype(jnp.int32),
                "log_prob": rows[:, flat_obs_dim + 1],
                "value": rows[:, flat_obs_dim + 2],
                "advantage": rows[:, flat_obs_dim + 3],
                "target": rows[:, flat_obs_dim + 4],
            }

        loss_cfg = cfg.loss_config()
        ratio_hist = None
        if scope is not None:
            ratio_hist = next(
                (h for h in scope.hists if h.name == "ratio"), None)
        if ratio_hist is not None:
            # Ratio counts are bucketized inside ppo_loss (static edges
            # from the spec) so the per-sample ratio array reduces in
            # place instead of stacking [epochs, minibatches, B].
            loss_cfg = loss_cfg._replace(ratio_hist_edges=ratio_hist.edges)
        # Minibatches keep the exact configured size (static shapes for XLA);
        # when minibatch_size does not divide the batch, each epoch trains on
        # a fresh random subset of num_minibatches*minibatch_size samples —
        # the per-epoch reshuffle covers the tail in expectation.
        mb_size = min(cfg.minibatch_size, cfg.batch_size)
        # One temperature per ITERATION (computed from the pre-increment
        # update_idx, same value the rollout used): the loss optimizes the
        # identical tempered policy the behavior log-probs came from.
        loss_temp = sample_temperature(cfg, runner.update_idx)

        def loss_fn(params, mb):
            logits, values = net.apply(params, mb["obs"])
            if loss_temp is not None:
                logits = logits / loss_temp
            return ppo_loss(
                logits, values, mb["action"], mb["log_prob"], mb["value"],
                mb["advantage"], mb["target"], loss_cfg,
            )

        def sgd_minibatch(carry, mb_rows):
            params, opt_state = carry
            mb = unpack(mb_rows)
            (_, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, mb)
            if scope is not None:
                # Pre-clip global grad norm, one scalar per minibatch —
                # the flight recorder's spike signal and the scope's
                # grad_norm stream.
                metrics["grad_norm"] = optax.global_norm(grads)
            if axis_name is not None:
                # Data-parallel gradient sync over the mesh axis (ICI
                # all-reduce); identity in the single-device path.
                grads = jax.lax.pmean(grads, axis_name)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), metrics

        blk = effective_shuffle_block(cfg)
        num_blocks = cfg.batch_size // blk
        k_cols = packed.shape[1]
        packed_blocks = packed.reshape(num_blocks, blk * k_cols)
        blocks_per_mb = mb_size // blk

        def sgd_epoch(carry, epoch_key):
            params, opt_state = carry
            perm = jax.random.permutation(epoch_key, num_blocks)
            shuffled = packed_blocks[perm].reshape(cfg.batch_size, k_cols)
            minibatches = shuffled[: cfg.num_minibatches * mb_size].reshape(
                cfg.num_minibatches, mb_size, k_cols
            )
            (params, opt_state), metrics = jax.lax.scan(
                sgd_minibatch, (params, opt_state), minibatches,
                unroll=cfg.sgd_unroll,
            )
            return (params, opt_state), metrics

        def sgd_epoch_fused(carry, epoch_key):
            # Fused-prologue epoch: the permutation is one argsort over
            # random bits, and each minibatch gathers its own rows from
            # the UNSHUFFLED packed batch inside the scan head — the full
            # shuffled [B, K] copy (an HBM write + read per epoch) never
            # materializes. Same minibatch content for the same perm
            # (ops/indexing.py, equivalence-tested); the perm VALUES
            # differ from jax.random.permutation's, hence prologue != the
            # byte-identical default path.
            params, opt_state = carry
            with jax.named_scope("prologue"):
                perm = shuffle_block_perm(epoch_key, num_blocks)

            def sgd_minibatch_fused(carry2, mb_index):
                with jax.named_scope("prologue"):
                    rows = gather_shuffled_minibatch(
                        packed_blocks, perm, mb_index, blocks_per_mb
                    ).reshape(mb_size, k_cols)
                return sgd_minibatch(carry2, rows)

            (params, opt_state), metrics = jax.lax.scan(
                sgd_minibatch_fused, (params, opt_state),
                jnp.arange(cfg.num_minibatches), unroll=cfg.sgd_unroll,
            )
            return (params, opt_state), metrics

        if cfg.prologue_enabled:
            sgd_epoch = sgd_epoch_fused

        key, shuffle_key = jax.random.split(key)
        with jax.named_scope("sgd"):
            epoch_keys = jax.random.split(shuffle_key, cfg.num_epochs)
            (params, opt_state), loss_metrics = jax.lax.scan(
                sgd_epoch, (runner.params, runner.opt_state), epoch_keys
            )

        scope_state = None
        if scope is not None:
            from rl_scheduler_tpu.utils.metrics import scope_observe

            with jax.named_scope("scope_metrics"):
                # hist_ratio arrives [epochs, minibatches, buckets] from
                # the scans; grad_norm [epochs, minibatches]. Reduce both
                # here — still inside the one XLA program.
                counts = {}
                if "hist_ratio" in loss_metrics:
                    counts["ratio"] = jnp.sum(
                        loss_metrics.pop("hist_ratio"), axis=(0, 1))
                scope_state = scope_observe(
                    scope,
                    values={
                        "advantage": advantages,
                        "reward": traj["reward"],
                        "value": traj["value"],
                        "action": traj["action"],
                        "grad_norm": loss_metrics["grad_norm"],
                    },
                    counts=counts,
                )

        num_completed = jnp.sum(traj["done"])
        metrics = {
            "episode_reward_mean": jnp.sum(traj["final_return"])
            / jnp.maximum(num_completed, 1.0),
            "episodes_completed": num_completed,
            "reward_mean": jnp.mean(traj["reward"]),
            **{k: jnp.mean(v) for k, v in loss_metrics.items()},
        }
        if axis_name is not None:
            metrics = jax.lax.pmean(metrics, axis_name)
        if scope_state is not None:
            # Rides out of the jitted update as ordinary pytree leaves;
            # the host loop pops it before logging (TrainObserver).
            metrics["graftscope"] = scope_state
        new_runner = RunnerState(
            params=params,
            opt_state=opt_state,
            env_state=env_state,
            obs=obs,
            key=key,
            ep_return=ep_ret,
            update_idx=runner.update_idx + 1,
            # Pipeline advance: the NEXT rollout samples with THIS
            # iteration's pre-SGD params — available before the SGD above
            # completes, which is exactly the broken dependency that lets
            # XLA overlap rollout k+1 with SGD k in a fused dispatch.
            collect_params=(runner.params if cfg.overlap_collect else None),
        )
        return new_runner, metrics

    # Test seams (tests/test_graftpipe.py): the raw collect closure —
    # deterministic in (runner, behavior_params) — lets the ratio pin
    # recompute the recorded behavior log-probs outside the jitted update.
    update_fn.collect = collect
    update_fn.overlap_collect = cfg.overlap_collect
    update_fn.prologue_enabled = cfg.prologue_enabled
    return init_fn, update_fn, net


def make_ppo(
    env_params: env_core.EnvParams,
    cfg: PPOTrainConfig,
    net: Any | None = None,
    axis_name: str | None = None,
    scope: Any | None = None,
) -> tuple[Callable, Callable, Any]:
    """:func:`make_ppo_bundle` specialized to the flagship multi-cloud env."""
    return make_ppo_bundle(multi_cloud_bundle(env_params), cfg, net,
                           axis_name, scope=scope)


def ppo_train(
    env: env_core.EnvParams | EnvBundle,
    cfg: PPOTrainConfig,
    num_iterations: int,
    seed: int = 0,
    log_fn: Callable[[int, dict], None] | None = None,
    checkpoint_fn: Callable[[int, RunnerState], None] | None = None,
    net: Any | None = None,
    restore: tuple[dict, int] | None = None,
    debug_checks: bool = False,
    sync_every: int = 1,
    eval_log_fn: Callable[[int, dict], None] | None = None,
    updates_per_dispatch: int = 1,
    mesh=None,
    eval_net: Any | None = None,
    scope: Any | None = None,
    observer: Any | None = None,
    preemption: Any | None = None,
    on_preempt: Callable[[int, RunnerState], None] | None = None,
    on_eval: Callable[[int, RunnerState, dict], None] | None = None,
    warm_start_params: Any | None = None,
):
    """Host-side training loop: jitted update per iteration + logging hooks.

    ``scope``/``observer``: graftscope instrumentation (see
    :func:`make_ppo_bundle` and ``utils/metrics.py``). ``scope`` is the
    MetricsSpec compiled into the update; ``observer`` (usually a
    ``TrainObserver`` holding the ScopeSession + flight recorder) is the
    host-side hook the loop drives. Single-device only for now: the
    sharded updates pmean their scalar metrics, which would average the
    Welford counts wrongly.

    ``mesh``: a ``jax.sharding.Mesh`` with a ``dp`` axis runs the update
    data-parallel via ``shard_map`` (``parallel/sharding.py``) — env batch
    sharded, params replicated, gradients pmean'd over ICI. A mesh with a
    ``tp`` axis > 1 runs Megatron-style tensor parallelism
    (``parallel/tensor_parallel.py`` — ``net`` must be None; the path owns
    its TPActorCritic); an ``sp`` axis > 1 runs sequence parallelism over
    the policy's node axis (``net`` must be the structured policy built
    with ``axis_name='sp'``). Everything else (checkpointing, resume,
    in-training eval, metric logging, fused dispatch) is unchanged: the
    sharded runner's leaves are ordinary global arrays. ``cfg.num_envs``
    is the GLOBAL env count.

    ``eval_net``: unsharded twin used by the in-training greedy eval when
    the training ``net`` only works inside ``shard_map`` (sp's collectives,
    tp's psum). Defaults to ``net``; the tp path builds its own twin.

    ``updates_per_dispatch=k`` fuses ``k`` whole PPO iterations into ONE
    dispatched program (``lax.scan`` over the update; metrics stacked and
    unstacked by the loop). This removes the per-iteration Python dispatch
    and device round-trip — the dominant cost for small configs like
    tpu64, where the update's compute is far below the ~10 ms fixed
    dispatch overhead measured through a tunneled TPU. The iteration span
    must divide by ``k``; checkpoint/eval intervals should be multiples
    of ``k``. Incompatible with ``debug_checks``.

    With ``cfg.eval_every > 0``, a greedy ``cfg.eval_episodes``-episode
    evaluation runs every ``cfg.eval_every`` iterations (reference
    ``train_final.py:19`` semantics) and its metrics
    (``eval_episode_reward_mean``, ``eval_episodes_completed``) go to
    ``eval_log_fn(iteration, metrics)`` — or are printed if no sink is
    given.

    ``debug_checks=True`` checkifies the update (``utils/debug.py``): the
    first NaN/zero-division/out-of-bounds index raises with the failing
    op named, instead of silently corrupting training. Forces the scan
    GAE (checkify cannot instrument inside a Pallas kernel). Slower; for
    debugging.

    ``sync_every`` batches device->host metric fetches: updates are
    dispatched asynchronously and metrics for ``sync_every`` iterations are
    fetched with ONE transfer (``log_fn`` then fires for each, in order,
    slightly late). Every host sync costs a full network round-trip when
    the accelerator is remote/tunneled (~100 ms measured), so per-iteration
    syncing can dominate small configs; raise this to keep the device fed.

    ``env`` is either multi-cloud :class:`EnvParams` or any
    :class:`EnvBundle`. Returns ``(runner, history)`` where history is a
    list of metric dicts.

    ``restore=({"params": ..., "opt_state": ...}, completed_iterations)``
    resumes a checkpointed run mid-way (the reference never resumes —
    SURVEY.md §5.4 — this build does): optimizer state and iteration count
    carry over; env state and rollout RNG restart from ``seed`` folded with
    the resume point, so the continued run sees fresh randomness rather
    than replaying the stream the original run already consumed.

    With a ``"loop"`` key in the restored tree (graftguard full-state
    checkpoints: env_state/obs/key/ep_return/update_idx), the ENTIRE
    runner is restored and the RNG is NOT re-folded — the resumed run
    replays exactly the trajectory the uninterrupted run would have
    taken, so interrupt-and-resume is bitwise-identical to never being
    interrupted (the deterministic-resume guarantee,
    ``tests/test_graftguard.py``).

    ``preemption``/``on_preempt``: see ``run_train_loop`` — a
    ``PreemptionGuard`` polled at dispatch boundaries; on a stop the loop
    flushes, force-checkpoints, fires ``on_preempt`` and returns.

    ``warm_start_params`` (graftloop fine-tune-from-trace,
    ``train_ppo --warm-start``): initialize the runner's PARAMS from
    another run's checkpoint while everything else — optimizer state,
    env state, RNG, iteration count — starts fresh at iteration 0. This
    is deliberately NOT ``restore``: a fine-tune is a new run on a new
    workload whose weights happen to start trained, so the optimizer
    must not carry the incumbent's moments and the resume guards must
    not demand the incumbent's scenario. Mutually exclusive with
    ``restore`` (which would overwrite the warm start anyway).
    """
    bundle = env if isinstance(env, EnvBundle) else multi_cloud_bundle(env)
    if mesh is not None and scope is not None:
        raise ValueError(
            "graftscope instruments the single-chip update; the sharded "
            "paths pmean scalar metrics, which would corrupt Welford "
            "counts — drop the mesh or the scope"
        )
    if mesh is not None and debug_checks:
        # Reject before the gae_impl branch below: its "forces scan GAE"
        # warning would describe a run that never happens.
        raise ValueError(
            "debug_checks cannot instrument the shard_map'd update; "
            "run the single-device path for checkified debugging"
        )
    if debug_checks and cfg.gae_impl != "scan":
        if resolve_gae_impl(cfg.gae_impl) == "pallas":
            warnings.warn(
                "debug_checks forces gae_impl='scan': checkify cannot "
                "instrument the Pallas GAE kernel, so it is not the code "
                "under test in this run", stacklevel=2)
        cfg = dataclasses.replace(cfg, gae_impl="scan")
    if mesh is not None:
        if mesh.shape.get("tp", 1) > 1:
            from rl_scheduler_tpu.parallel.tensor_parallel import (
                make_tensor_parallel_ppo,
            )

            if cfg.overlap_collect or cfg.prologue_enabled:
                raise ValueError(
                    "overlap_collect/fused_prologue instrument the shared "
                    "PPO update (make_ppo_bundle); the tensor-parallel "
                    "trainer builds its own — drop the tp axis or the "
                    "graftpipe knobs"
                )
            if net is not None:
                raise ValueError(
                    "the tensor-parallel path builds its own TPActorCritic "
                    "from cfg.hidden; a custom net cannot be tp-sharded"
                )
            init_fn, update_fn, net = make_tensor_parallel_ppo(
                bundle, cfg, mesh
            )
            if eval_net is None and cfg.eval_every > 0:
                from rl_scheduler_tpu.parallel.tensor_parallel import (
                    TPActorCritic,
                )

                # Checkpoint/runner params are the full global matrices;
                # the unsharded twin computes the identical function.
                eval_net = TPActorCritic(
                    num_actions=bundle.num_actions, hidden=cfg.hidden,
                    tp_axis=None, tp_size=1,
                )
        elif mesh.shape.get("sp", 1) > 1:
            from rl_scheduler_tpu.parallel.sharding import make_seq_parallel_ppo

            if net is None or getattr(net, "axis_name", None) != "sp":
                raise ValueError(
                    "the sequence-parallel path needs a structured policy "
                    "built with axis_name='sp' (e.g. SetTransformerPolicy)"
                )
            if eval_net is None and cfg.eval_every > 0:
                # The sp net's collectives cannot trace outside shard_map;
                # the unsharded clone computes the identical function
                # (ring attention is exact and parameter-shape-preserving).
                eval_net = net.clone(axis_name=None)
            init_fn, update_fn, net = make_seq_parallel_ppo(
                bundle, cfg, net, mesh
            )
        else:
            from rl_scheduler_tpu.parallel.sharding import (
                make_data_parallel_ppo_bundle,
            )

            init_fn, update_fn, net = make_data_parallel_ppo_bundle(
                bundle, cfg, mesh, net=net
            )
    else:
        init_fn, update_fn, net = make_ppo_bundle(bundle, cfg, net=net,
                                                  scope=scope)
    start_iteration = 0
    full_state = restore is not None and "loop" in restore[0]
    key = jax.random.PRNGKey(seed)
    if restore is not None and not full_state:
        key = jax.random.fold_in(key, restore[1])
    runner = init_fn(key)
    if warm_start_params is not None:
        if restore is not None:
            raise ValueError(
                "warm_start_params with restore: a resume already has "
                "weights — pick one initialization source")
        # Copy like the restore path: the jitted update donates buffers.
        params = jax.tree.map(lambda x: jnp.array(x, copy=True),
                              warm_start_params)
        want = jax.tree_util.tree_structure(runner.params)
        got = jax.tree_util.tree_structure(params)
        if want != got:
            raise ValueError(
                "warm_start_params tree structure does not match this "
                "run's network (different env family / policy "
                f"architecture?): checkpoint {got} vs configured {want}")
        mismatched = [
            f"{jax.tree_util.keystr(path)}: {jnp.shape(w)} vs {v.shape}"
            for (path, w), v in zip(
                jax.tree_util.tree_leaves_with_path(params),
                jax.tree_util.tree_leaves(runner.params))
            if tuple(jnp.shape(w)) != tuple(v.shape)]
        if mismatched:
            raise ValueError(
                "warm_start_params leaf shapes do not match this run's "
                "network (different width/heads?): "
                + "; ".join(mismatched[:4]))
        runner = runner._replace(params=params)
        if cfg.overlap_collect:
            # The pipeline's behavior slot must start from the warm
            # weights too, exactly like a warm restart on resume.
            runner = runner._replace(
                collect_params=jax.tree.map(jnp.copy, params))
    if restore is not None:
        tree, start_iteration = restore
        # Copy the restored leaves: the jitted update donates the runner's
        # buffers, which would otherwise delete the caller's checkpoint
        # tree out from under it on accelerator backends.
        tree = jax.tree.map(lambda x: jnp.array(x, copy=True), tree)
        if full_state:
            # Deterministic resume: every carried leaf (env state, obs,
            # RNG key, episode returns) comes from the checkpoint, so the
            # continuation replays the uninterrupted run's exact stream.
            loop_state = tree["loop"]
            runner = runner._replace(
                params=tree["params"],
                opt_state=tree["opt_state"],
                env_state=loop_state["env_state"],
                obs=loop_state["obs"],
                key=loop_state["key"],
                ep_return=loop_state["ep_return"],
                update_idx=loop_state["update_idx"],
            )
            if "collect_params" in loop_state and cfg.overlap_collect:
                # graftpipe pipelined runner: the in-flight stale-params
                # slot rides the full-state checkpoint so a resumed
                # overlap run replays the uninterrupted stream bitwise
                # (the CLI's resume guard pins the overlap flag to the
                # recorded one; an API caller restoring an overlap tree
                # with overlap OFF falls through and the slot is simply
                # dropped — installing it would hand the unpipelined
                # update a carry whose structure it cannot return).
                runner = runner._replace(
                    collect_params=loop_state["collect_params"])
            elif cfg.overlap_collect:
                # Full-state tree without a slot (API caller resuming a
                # pre-graftpipe checkpoint with overlap newly on): warm
                # restart — collect with the restored params, exactly
                # like iteration 0 of a fresh pipelined run.
                runner = runner._replace(
                    collect_params=jax.tree.map(jnp.copy, tree["params"]))
        else:
            runner = runner._replace(
                params=tree["params"],
                opt_state=tree["opt_state"],
                update_idx=jnp.asarray(start_iteration, jnp.int32),
            )
            if cfg.overlap_collect:
                # Learning-state-only resume (sharded paths, changed env
                # shape): the pipeline restarts warm from the RESTORED
                # params — leaving the fresh init's random weights in the
                # slot would collect one rollout with an untrained
                # policy.
                runner = runner._replace(
                    collect_params=jax.tree.map(jnp.copy, tree["params"]))
    from rl_scheduler_tpu.agent.loop import make_update, run_train_loop

    update = make_update(update_fn, debug_checks, updates_per_dispatch)
    eval_hook = make_greedy_eval_hook(
        bundle, eval_net if eval_net is not None else net,
        cfg.eval_every, cfg.eval_episodes, seed, eval_log_fn,
        on_eval=on_eval,
    )

    return run_train_loop(
        update, runner, start_iteration, num_iterations,
        sync_every=sync_every, log_fn=log_fn, checkpoint_fn=checkpoint_fn,
        eval_every=cfg.eval_every, eval_hook=eval_hook,
        updates_per_dispatch=updates_per_dispatch, observer=observer,
        preemption=preemption, on_preempt=on_preempt,
    )


def make_greedy_eval_hook(
    bundle: EnvBundle,
    net: Any,
    eval_every: int,
    eval_episodes: int,
    seed: int,
    eval_log_fn: Callable[[int, dict], None] | None,
    on_eval: Callable[[int, Any, dict], None] | None = None,
) -> Callable[[int, Any], None] | None:
    """Shared PPO/DQN in-training eval hook: ``hook(i, runner)`` runs the
    jitted greedy evaluation on ``runner.params`` (distinct key per firing)
    and hands the fetched metrics to ``eval_log_fn`` — or prints them.
    Returns ``None`` when disabled.

    ``on_eval(i, runner, metrics)`` fires AFTER logging (and after any
    stall guard wrapped into ``eval_log_fn`` has accepted the value, so a
    raising guard skips it): the one place per firing that sees both the
    fetched metrics and the live runner — the best-eval checkpoint
    tracker's seam (``agent/loop.make_best_checkpoint_hook``)."""
    if eval_every <= 0:
        return None
    from rl_scheduler_tpu.agent.evaluate import make_greedy_eval_fn

    eval_metrics_fn = make_greedy_eval_fn(bundle, net, eval_episodes)
    # A dedicated key stream, decorrelated from the training stream.
    eval_key = jax.random.fold_in(jax.random.PRNGKey(seed), 0x0E7A1)

    def eval_hook(i: int, runner: Any) -> None:
        metrics = jax.device_get(
            eval_metrics_fn(runner.params, jax.random.fold_in(eval_key, i))
        )
        metrics = {k: float(v) for k, v in metrics.items()}
        if eval_log_fn is not None:
            eval_log_fn(i, metrics)
        else:
            from rl_scheduler_tpu.agent.loop import print_eval_line

            print_eval_line(i, metrics)
        if on_eval is not None:
            on_eval(i, runner, metrics)

    return eval_hook
