"""Shared host-side training loop with batched device->host metric syncs.

Every host sync costs a full network round-trip when the accelerator is
remote/tunneled (~100 ms measured through this repo's TPU tunnel), so the
loop dispatches ``sync_every`` jitted updates asynchronously and fetches
all their metrics with ONE ``jax.device_get``. ``log_fn`` still fires once
per iteration, in order — just in bursts at flush time.

Because completion times are only observed at flush granularity, each
metrics dict gets a ``wall_time`` key (seconds since loop start) linearly
interpolated across its burst — rate calculations built on it stay accurate
at every ``sync_every``, unlike rates computed from the caller's own clock
at ``log_fn`` call time (which would lump a whole burst into one instant).

A ``finally`` flush writes any pending metrics out even when the loop dies
mid-burst (Ctrl-C, OOM, a checkify error), so crash-truncated runs keep
every completed iteration's metrics.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Any, Callable

import jax


def run_train_loop(
    update: Callable[[Any], tuple[Any, dict]],
    runner: Any,
    start_iteration: int,
    num_iterations: int,
    *,
    sync_every: int = 1,
    log_fn: Callable[[int, dict], None] | None = None,
    checkpoint_fn: Callable[[int, Any], None] | None = None,
    eval_every: int = 0,
    eval_hook: Callable[[int, Any], None] | None = None,
    updates_per_dispatch: int = 1,
    observer: Any | None = None,
    preemption: Any | None = None,
    on_preempt: Callable[[int, Any], None] | None = None,
) -> tuple[Any, list[dict]]:
    """Run ``update`` for iterations ``[start_iteration, num_iterations)``.

    ``preemption`` (a ``utils/preemption.PreemptionGuard``) is polled at
    each dispatch boundary — the one point where the runner is a
    consistent pytree. When it reports a stop: pending metrics flush, a
    FINAL checkpoint is written through ``checkpoint_fn.force`` (saving
    even mid-interval; falls back to a plain ``checkpoint_fn`` call),
    ``on_preempt(last_iteration, runner)`` fires (the CLIs dump a
    flight-recorder manifest there), and the loop returns normally with
    ``preemption.stopped_at`` set. The in-flight dispatch always
    completes first: stopping is checked BEFORE dispatching, never by
    abandoning dispatched work.

    ``observer`` (graftscope, ``utils/metrics.TrainObserver``) gets three
    hooks: ``observe(i0, metrics, k) -> metrics`` right after each
    dispatch (device-side bookkeeping; it pops the non-scalar
    ``"graftscope"`` state out of the metrics dict before the loop
    fetches/logs), ``after_log(i, row)`` per fetched row (host-side
    anomaly checks), and ``close()`` in the loop's ``finally`` (final
    partial-window flush). Without an observer, a stray ``"graftscope"``
    key is dropped so the scalar flush below stays well-typed.

    With ``eval_every > 0`` and an ``eval_hook``, the hook fires after
    every ``eval_every``-th iteration (reference semantics:
    ``evaluation_interval``, ``train_final.py:19``). Pending training
    metrics are flushed first so the hook's own log records land after the
    iterations they evaluate.

    ``updates_per_dispatch=k > 1`` declares that ``update`` fuses ``k``
    training iterations into ONE dispatched program (``lax.scan`` inside
    jit, see ``dqn_train``) and returns metrics with a leading ``[k]``
    stack axis; the loop advances ``k`` iterations per call and unstacks
    per-iteration metrics. This amortizes the per-dispatch host/tunnel
    round-trip that dominates tiny updates. The iteration span must
    divide by ``k``; checkpoint/eval hooks fire at dispatch boundaries
    (pass every-values that are multiples of ``k``).

    Returns ``(final_runner, history)`` where history holds one float dict
    per iteration (plus the synthetic ``wall_time`` key described above).
    """
    history: list[dict] = []
    # Each pending entry is (first_iteration, metrics, k): with k > 1 the
    # metrics leaves carry a leading [k] stack axis covering iterations
    # [first, first + k). Unstacking happens AFTER device_get, in numpy —
    # slicing device arrays per iteration would issue thousands of tiny
    # device ops and eat the fused dispatch's win.
    pending: list[tuple[int, dict, int]] = []
    t0 = time.perf_counter()
    last_flush_elapsed = 0.0

    def flush() -> None:
        nonlocal last_flush_elapsed
        if not pending:
            return
        # Take the burst off the queue BEFORE running callbacks: if log_fn
        # (or device_get) raises mid-burst, the finally-flush must not
        # re-fetch and re-emit iterations that were already logged.
        burst_items, pending[:] = list(pending), []
        fetched = jax.device_get([m for _, m, _ in burst_items])
        now = time.perf_counter() - t0
        prev = last_flush_elapsed
        last_flush_elapsed = now
        total = sum(kk for _, _, kk in burst_items)
        n = 0
        for (j0, _, kk), vals in zip(burst_items, fetched):
            for j in range(kk):
                n += 1
                row = {
                    k: float(v[j] if kk > 1 else v) for k, v in vals.items()
                }
                row["wall_time"] = prev + (now - prev) * n / total
                history.append(row)
                if log_fn is not None:
                    log_fn(j0 + j, row)
                if observer is not None:
                    observer.after_log(j0 + j, row)

    k = max(1, updates_per_dispatch)
    if (num_iterations - start_iteration) % k:
        raise ValueError(
            f"iteration span {num_iterations - start_iteration} not "
            f"divisible by updates_per_dispatch={k}"
        )
    if start_iteration % k:
        # Observed iteration boundaries are start + n*k; a misaligned
        # resume point would shift every boundary off the eval/checkpoint
        # intervals, silently skipping both even when the intervals
        # themselves divide by k.
        raise ValueError(
            f"start_iteration={start_iteration} not divisible by "
            f"updates_per_dispatch={k}; resume at a multiple of the "
            "dispatch factor (or train the stub iterations with k=1)"
        )
    if eval_every > 0 and eval_hook is not None and eval_every % k:
        # The loop only observes iteration boundaries at dispatch ends;
        # a non-multiple interval would silently skip evals.
        raise ValueError(
            f"eval_every={eval_every} not divisible by "
            f"updates_per_dispatch={k}; evals would be silently dropped"
        )
    ckpt_every = getattr(checkpoint_fn, "every", None)
    if ckpt_every is not None and ckpt_every > 0 and ckpt_every % k:
        # Same failure mode as eval_every: with k > 1 checkpoint_fn only
        # ever sees i = i0 + k - 1, so a non-multiple interval silently
        # skips periodic checkpoints (make_periodic_checkpoint_fn tags
        # its interval precisely so this check can see it).
        raise ValueError(
            f"checkpoint interval {ckpt_every} not divisible by "
            f"updates_per_dispatch={k}; periodic checkpoints would be "
            "silently dropped"
        )
    try:
        for i0 in range(start_iteration, num_iterations, k):
            if preemption is not None and preemption.should_stop():
                # Acting here (before the next dispatch) means the last
                # dispatched update has already been folded into runner:
                # the final checkpoint covers everything trained.
                last = i0 - 1
                preemption.stopped_at = last
                # Checkpoint FIRST: the final save is the artifact this
                # path exists to write; the metrics flush is a device
                # fetch that can itself fail on a dying VM and must not
                # forfeit it.
                if checkpoint_fn is not None and last >= start_iteration:
                    force = getattr(checkpoint_fn, "force", checkpoint_fn)
                    force(last, runner)
                try:
                    flush()
                except Exception:  # noqa: BLE001 — shutdown path
                    import logging

                    logging.getLogger(__name__).exception(
                        "metrics flush failed during preemption shutdown; "
                        "final checkpoint was already written")
                if on_preempt is not None:
                    on_preempt(last, runner)
                print(
                    f"preemption: stopped cleanly after iteration "
                    f"{last + 1} (resume with --resume to continue)",
                    flush=True,
                )
                break
            runner, metrics = update(runner)
            if observer is not None:
                metrics = observer.observe(i0, metrics, k)
            elif isinstance(metrics, dict) and "graftscope" in metrics:
                # Scope-instrumented update without an observer (direct
                # ppo_train(scope=...) callers): drop the non-scalar
                # state so the flush below stays well-typed.
                metrics = {k2: v for k2, v in metrics.items()
                           if k2 != "graftscope"}
            pending.append((i0, metrics, k))
            i = i0 + k - 1
            covered = sum(kk for _, _, kk in pending)
            if covered >= max(1, sync_every) or i + 1 == num_iterations:
                flush()
            if checkpoint_fn is not None:
                checkpoint_fn(i, runner)
            if (eval_hook is not None and eval_every > 0
                    and (i + 1) % eval_every == 0):
                flush()
                eval_hook(i, runner)
    finally:
        try:
            flush()
        finally:
            if observer is not None:
                observer.close()
    return runner, history


class TensorBoardLogger:
    """Optional TensorBoard sink for training metrics (SURVEY.md §5.5).

    Uses torch's ``SummaryWriter`` (CPU torch ships with this framework's
    environment); raises ImportError with a clear message if the
    ``tensorboard`` package is absent. Scalars land under ``<run_dir>/tb``
    — point ``tensorboard --logdir`` at the run root.
    """

    def __init__(self, run_dir: Any):
        try:
            from torch.utils.tensorboard import SummaryWriter
        except ImportError as e:
            raise ImportError(
                "tensorboard logging needs BOTH torch and the tensorboard "
                f"package (torch.utils.tensorboard import failed: {e})"
            ) from e
        self._writer = SummaryWriter(str(run_dir) + "/tb")

    def add(self, step: int, metrics: dict) -> None:
        for k, v in metrics.items():
            self._writer.add_scalar(k, v, step)
        # Flush per burst so a killed run's event file matches the JSONL
        # sink's durability (SummaryWriter otherwise buffers ~120 s).
        self._writer.flush()

    def add_text(self, tag: str, text: str, step: int = 0) -> None:
        """Event-style marker (e.g. a reseed boundary) so the scalar
        streams' repeated step numbers are attributable in the TB UI."""
        self._writer.add_text(tag, text, step)
        self._writer.flush()

    def close(self) -> None:
        self._writer.close()


def make_jsonl_log_fn(
    metrics_file: Any,
    steps_per_iter: int,
    start_iteration: int = 0,
    print_line: Callable[[int, float, dict], None] | None = None,
    tb: TensorBoardLogger | None = None,
) -> Callable[[int, dict], None]:
    """Standard CLI ``log_fn``: one JSONL line per iteration with a
    cumulative ``env_steps_per_sec`` computed from the loop's ``wall_time``
    (the local clock would lump a sync burst into one instant), then an
    optional ``print_line(i, sps, metrics)`` for console output and an
    optional TensorBoard sink.
    """

    def log_fn(i: int, metrics: dict) -> None:
        sps = steps_per_iter * (i + 1 - start_iteration) / metrics["wall_time"]
        line = {"iteration": i + 1, "env_steps_per_sec": round(sps, 1), **metrics}
        metrics_file.write(json.dumps(line) + "\n")
        metrics_file.flush()
        if tb is not None:
            tb.add(i + 1, {"env_steps_per_sec": sps, **metrics})
        if print_line is not None:
            print_line(i, sps, metrics)

    return log_fn


def make_scope_log_fn(
    metrics_file: Any,
    tb: TensorBoardLogger | None = None,
) -> Callable[[int, dict], None]:
    """Standard CLI sink for graftscope window summaries: one JSONL line
    tagged ``"graftscope": true`` (so analysis can split the stream, same
    convention as the eval sink), scalar entries mirrored to TensorBoard
    (histogram dicts stay JSONL-only)."""

    def scope_log_fn(i: int, summary: dict) -> None:
        line = {"iteration": i + 1, "graftscope": True, **summary}
        metrics_file.write(json.dumps(line) + "\n")
        metrics_file.flush()
        if tb is not None:
            tb.add(i + 1, {k: v for k, v in summary.items()
                           if isinstance(v, (int, float))})

    return scope_log_fn


def validate_metrics_window(window: int, updates_per_dispatch: int) -> None:
    """The train CLIs' shared ``--metrics-window`` validation; SystemExit
    with the flag-level message on misuse so both CLIs reject identically."""
    if window < 0:
        raise SystemExit(
            f"--metrics-window {window}: pass a positive "
            "iteration count (0 disables)"
        )
    if window and window % max(1, updates_per_dispatch):
        raise SystemExit(
            f"--metrics-window {window} is not a multiple of "
            f"--updates-per-dispatch {updates_per_dispatch}: windows "
            "are observed at dispatch boundaries, so the flush cadence "
            "would silently drift (pick a multiple)"
        )


def make_graftscope(spec, window: int, run_dir, metrics_file,
                    tb: TensorBoardLogger | None, config: dict):
    """One-stop graftscope construction for the train CLIs: a ScopeSession
    flushing window summaries through :func:`make_scope_log_fn`, a flight
    recorder with a run manifest under ``run_dir``, and the TrainObserver
    tying both into ``run_train_loop``. Returns ``(observer, recorder)`` —
    one shared builder so the manifest fields and artifact layout cannot
    drift between the PPO and DQN CLIs."""
    from pathlib import Path

    from rl_scheduler_tpu.utils.flight_recorder import (
        FlightRecorder,
        build_manifest,
    )
    from rl_scheduler_tpu.utils.metrics import ScopeSession, TrainObserver

    session = ScopeSession(spec, window, make_scope_log_fn(metrics_file, tb))
    recorder = FlightRecorder(
        path=Path(run_dir) / "flight_recorder.jsonl",
        manifest=build_manifest(config=config),
    )
    observer = TrainObserver(session, recorder)
    print(f"graftscope: metrics window {window}, flight "
          f"recorder ring {recorder.capacity} -> {recorder.path}")
    return observer, recorder


def make_update(
    update_fn: Callable[[Any], tuple[Any, dict]],
    debug_checks: bool = False,
    updates_per_dispatch: int = 1,
) -> Callable[[Any], tuple[Any, dict]]:
    """Compile a trainer's pure ``update_fn`` for the host loop — shared by
    PPO and DQN so the checkify/fusion rules live once.

    ``debug_checks`` checkifies (``utils/debug.py``); ``updates_per_dispatch
    = k > 1`` wraps ``k`` iterations in ``lax.scan`` inside one jit (metrics
    stacked, see ``run_train_loop``). The two are incompatible: checkify
    raises per dispatch, so fused iterations would report a stale/merged
    error state.
    """
    if debug_checks and updates_per_dispatch > 1:
        raise ValueError(
            "debug_checks is incompatible with updates_per_dispatch > 1: "
            "checkify raises per dispatch, so fused iterations would "
            "report a stale/merged error state"
        )
    if debug_checks:
        from rl_scheduler_tpu.utils.debug import checkified_update

        return checkified_update(update_fn)
    if updates_per_dispatch > 1:
        def fused(runner):
            return jax.lax.scan(
                lambda r, _: update_fn(r), runner, None,
                length=updates_per_dispatch,
            )

        return jax.jit(fused, donate_argnums=0)
    return jax.jit(update_fn, donate_argnums=0)


def print_eval_line(i: int, metrics: dict) -> None:
    """The one console format for in-training eval metrics (shared by the
    CLI sink below and the no-sink fallback in ``agent.ppo``)."""
    print(
        f"  eval@{i + 1}: "
        f"reward_mean={metrics['eval_episode_reward_mean']:.2f} "
        f"({metrics['eval_episodes_completed']:.0f} episodes)",
        flush=True,
    )


def make_eval_log_fn(
    metrics_file: Any,
    tb: TensorBoardLogger | None = None,
) -> Callable[[int, dict], None]:
    """Standard CLI sink for in-training evaluation metrics: one JSONL line
    (tagged ``"eval": true`` so analysis can split the streams), the same
    scalars to TensorBoard, and a console line."""

    def eval_log_fn(i: int, metrics: dict) -> None:
        line = {"iteration": i + 1, "eval": True, **metrics}
        metrics_file.write(json.dumps(line) + "\n")
        metrics_file.flush()
        if tb is not None:
            tb.add(i + 1, metrics)
        print_eval_line(i, metrics)

    return eval_log_fn


def align_checkpoint_interval(requested: int | None, default: int,
                              updates_per_dispatch: int) -> int:
    """Resolve a CLI checkpoint cadence against the fused-dispatch factor.

    ``requested is None`` (the user never chose a cadence): the default is
    rounded UP to the next multiple of ``updates_per_dispatch``, with a
    printed notice when that changes it. An EXPLICIT misaligned request
    exits with the actionable message instead — silently rewriting a
    value the user chose would hide skipped checkpoints behind one log
    line (``run_train_loop`` would reject it later anyway, less helpfully).
    """
    k = max(1, updates_per_dispatch)
    if requested is None:
        aligned = (max(1, default) + k - 1) // k * k
        if aligned != default:
            print(f"--checkpoint-every default {default} rounded up to "
                  f"{aligned} to align with --updates-per-dispatch {k}")
        return aligned
    if requested <= 0:
        # A zero/negative cadence would pass this gate and then divide by
        # zero at the first iteration boundary — AFTER the run dir and
        # metadata exist, defeating the validate-before-side-effects goal.
        raise SystemExit(
            f"--checkpoint-every {requested}: must be a positive iteration "
            "count"
        )
    if requested % k:
        raise SystemExit(
            f"--checkpoint-every {requested} is not a multiple of "
            f"--updates-per-dispatch {k}: fused dispatches only observe "
            f"every {k}-th iteration boundary, so those checkpoints would "
            "silently be skipped (pick a multiple)"
        )
    return requested


BEST_DIR = "best"


def make_best_checkpoint_hook(
    best_ckpt: Any,
    tree_fn: Callable[[Any], dict],
    extras: dict,
    metric: str = "eval_episode_reward_mean",
    initial_best: float | None = None,
) -> Callable[[int, Any, dict], None]:
    """Best-in-training-eval checkpoint keeper (ROADMAP item 3a).

    An ``on_eval(i, runner, metrics)`` hook for the trainers' greedy-eval
    seam: whenever this firing's ``metric`` beats every previous one, the
    runner is saved through ``best_ckpt`` (a ``CheckpointManager`` over
    ``<run>/best``, keep=1 — graftguard's async manifested saves make the
    write nearly free: dispatch + return, finalized at the next save/
    close). The measured fleet late-degrade mode — healthy at the stall
    deadline, below baseline at the final eval (seeds 5/8 of the 9-seed
    study, docs/scaling.md §1b) — is salvaged outright: the peak-eval
    weights survive in ``best/`` while ``checkpoints/`` holds the
    degraded tail, and ``--resume-best`` / ``evaluate --best`` select
    them (chaos-suite proof: ``tests/test_graftguard.py``).

    Save failures follow the periodic-checkpoint contract: logged and
    counted on ``hook.failures``, never fatal. ``hook.best`` exposes the
    running maximum (``initial_best`` seeds it on resume so a restored
    run does not clobber a better earlier save).
    """
    state = {"best": float("-inf") if initial_best is None else initial_best}
    log = logging.getLogger(__name__)

    def hook(i: int, runner: Any, metrics: dict) -> None:
        value = metrics.get(metric)
        if value is None or value <= state["best"]:
            return
        state["best"] = value
        try:
            best_ckpt.save(i + 1, tree_fn(runner),
                           extras={**extras, "best_eval": value,
                                   "best_metric": metric})
            print(f"  best-eval checkpoint updated at iteration {i + 1} "
                  f"({metric}={value:.2f})", flush=True)
        except Exception as e:  # noqa: BLE001 — same non-fatal contract
            # as periodic saves: losing a best-save must not kill training
            hook.failures.append((i + 1, repr(e)))
            log.error("best-eval checkpoint save at iteration %d failed "
                      "(%s); training continues", i + 1, e)

    hook.failures = []
    hook.best_value = lambda: state["best"]
    return hook


def make_periodic_checkpoint_fn(
    ckpt: Any,
    every: int,
    total_iterations: int,
    tree_fn: Callable[[Any], dict],
    extras: dict,
) -> Callable[[int, Any], None]:
    """Standard CLI ``checkpoint_fn``: save every ``every`` iterations and
    at the end (the reference's Ray lifecycle, ``train_final.py:27-31``).

    graftguard semantics (docs/robustness.md): a FAILED save is logged
    and counted (``checkpoint_fn.failures``) but never unwinds training —
    the data-loss bound is "everything since the last verified
    checkpoint", and killing the run on a transient disk error would
    forfeit the training still to come. ``checkpoint_fn.force(i, runner)``
    saves regardless of the cadence (skipping only a step already saved)
    — the preemption path's final checkpoint.
    """
    import logging

    log = logging.getLogger(__name__)
    state = {"last_saved": None}

    def _save(step: int, runner: Any) -> None:
        try:
            ckpt.save(step, tree_fn(runner), extras=extras)
            state["last_saved"] = step
        except Exception as e:  # noqa: BLE001 — a checkpoint write
            # failure must not kill training (graftguard contract)
            checkpoint_fn.failures.append((step, repr(e)))
            log.error(
                "checkpoint save at step %d failed (%s); training "
                "continues — data-loss bound is the last verified "
                "checkpoint", step, e)

    def checkpoint_fn(i: int, runner: Any) -> None:
        if (i + 1) % every == 0 or (i + 1) == total_iterations:
            _save(i + 1, runner)

    def force(i: int, runner: Any) -> None:
        if state["last_saved"] != i + 1:
            _save(i + 1, runner)

    # run_train_loop validates this against updates_per_dispatch (fused
    # dispatches only observe every k-th iteration boundary).
    checkpoint_fn.every = every
    checkpoint_fn.force = force
    checkpoint_fn.failures = []
    return checkpoint_fn
