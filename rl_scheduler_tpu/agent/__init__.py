"""Agents: PPO and DQN trainers, hyperparameter presets, evaluation."""

from rl_scheduler_tpu.agent.ppo import PPOTrainConfig, make_ppo, ppo_train
from rl_scheduler_tpu.agent.dqn import DQNConfig, make_dqn, dqn_train
from rl_scheduler_tpu.agent.presets import DQN_PRESETS, PPO_PRESETS

__all__ = [
    "PPOTrainConfig",
    "make_ppo",
    "ppo_train",
    "DQNConfig",
    "make_dqn",
    "dqn_train",
    "PPO_PRESETS",
    "DQN_PRESETS",
]
