"""Policy evaluation + final report (reference parity, vectorized).

Re-designs the reference's evaluation stack for TPU:

- ``final_evaluation.py:13-27`` walks ``~/ray_results`` for the newest
  checkpoint; here :func:`rl_scheduler_tpu.utils.checkpoint.find_latest_run`
  does the same over our run root.
- ``final_evaluation.py:42-55`` runs 100 greedy episodes one
  ``compute_single_action`` at a time (~10k sequential host round-trips);
  here the 100 episodes are a vmapped batch — one ``lax.scan`` over 99 steps
  evaluates all episodes in a single XLA program.
- ``final_evaluation.py:60-84`` aggregates cost (= |reward|), AWS/Azure
  choice percentages, and improvement vs the cost-greedy baseline, writing
  ``results/final_evaluation_summary.txt``. Same artifacts here, except the
  baseline cost is *computed* from the table rather than hardcoded ($4.765,
  ``final_evaluation.py:73``) — the constant is kept for cross-checking.
- ``eval_ppo.py:17-31`` (20-step per-step printout) is :func:`quick_eval`.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
from pathlib import Path
from typing import Callable

import jax
import jax.numpy as jnp

from rl_scheduler_tpu.config import EnvConfig, RuntimeConfig
from rl_scheduler_tpu.env import core as env_core
from rl_scheduler_tpu.env.baselines import (
    cost_greedy_policy,
    random_policy,
    round_robin_policy,
)
from rl_scheduler_tpu.env.vector import reset_batch, rollout_from
from rl_scheduler_tpu.utils.fsio import atomic_write_json

# The reference's hardcoded eval anchor (final_evaluation.py:73), kept only
# to report alongside the computed baseline.
REFERENCE_BASELINE_COST = 4.765

CLOUD_NAMES = ("AWS", "Azure")


@dataclasses.dataclass(frozen=True)
class EvalReport:
    """Aggregate results of a greedy evaluation run."""

    num_episodes: int
    avg_episode_reward: float
    avg_episode_cost: float        # |weighted cost+latency| per episode, >= 0
    choice_fractions: tuple        # fraction of decisions per cloud
    avg_episode_length: float
    baseline_cost: float           # cost-greedy baseline on the same table
    improvement_pct: float         # vs computed baseline (positive = better)

    def summary(self) -> str:
        lines = [
            "=" * 60,
            "FINAL EVALUATION SUMMARY",
            "=" * 60,
            f"Episodes evaluated:       {self.num_episodes}",
            f"Average episode reward:   {self.avg_episode_reward:.3f}",
            f"Average episode cost:     ${self.avg_episode_cost:.3f}",
            f"Cost-greedy baseline:     ${self.baseline_cost:.3f}"
            f" (reference constant: ${REFERENCE_BASELINE_COST})",
            f"Improvement vs baseline:  {self.improvement_pct:+.2f}%",
            "Cloud choice split:       "
            + ", ".join(
                f"{name} {frac * 100:.1f}%"
                for name, frac in zip(CLOUD_NAMES, self.choice_fractions)
            ),
            f"Average episode length:   {self.avg_episode_length:.1f}",
            "=" * 60,
        ]
        return "\n".join(lines)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def greedy_policy_fn(net, params) -> Callable:
    """Deterministic (explore=False) policy: argmax over action scores.

    Works for both policy families: actor-critic nets returning
    ``(logits, value)`` and Q-networks returning plain ``q`` values —
    greedy argmax is the same operation either way.
    """

    def policy(obs, key):
        out = net.apply(params, obs)
        scores = out[0] if isinstance(out, tuple) else out
        return jnp.argmax(scores, axis=-1).astype(jnp.int32)

    return policy


def make_greedy_eval_fn(
    bundle,
    net,
    num_episodes: int = 20,
    num_steps: int | None = None,
) -> Callable:
    """Jitted in-training evaluation over ANY :class:`EnvBundle`.

    Returns ``eval_fn(params, key) -> metrics`` running ``num_episodes``
    batch lanes of greedy (explore=False) rollout for one episode each —
    the TPU-shaped counterpart of the reference's periodic evaluation
    (``train_final.py:19``, ``evaluation_interval=5,
    evaluation_duration=20``, which steps 20 sequential episodes through
    RLlib eval workers). Every env family here has fixed-length episodes
    (``bundle.episode_steps``), so one scan of that length completes
    exactly one episode per lane.

    Metrics (device scalars; ``jax.device_get`` to read):
    ``eval_episode_reward_mean`` and ``eval_episodes_completed``.
    """
    steps = num_steps if num_steps is not None else bundle.episode_steps
    if steps is None:
        raise ValueError(
            f"bundle {bundle.name!r} does not declare episode_steps; pass "
            "num_steps explicitly"
        )

    @jax.jit
    def eval_fn(params, key):
        state, obs = bundle.reset_batch(key, num_episodes)

        def step(carry, _):
            state, obs, ep_ret = carry
            out = net.apply(params, obs)
            scores = out[0] if isinstance(out, tuple) else out
            action = jnp.argmax(scores, axis=-1).astype(jnp.int32)
            state, ts = bundle.step_batch(state, action)
            done_f = ts.done.astype(jnp.float32)
            new_ret = ep_ret + ts.reward
            final = new_ret * done_f
            return (state, ts.obs, new_ret * (1.0 - done_f)), (final, done_f)

        init = (state, obs, jnp.zeros(num_episodes, jnp.float32))
        _, (finals, dones) = jax.lax.scan(step, init, None, length=steps)
        completed = dones.sum()
        return {
            "eval_episode_reward_mean": finals.sum() / jnp.maximum(completed, 1.0),
            "eval_episodes_completed": completed,
        }

    return eval_fn


def _episode_cost(params: env_core.EnvParams, ep_reward: jnp.ndarray) -> jnp.ndarray:
    """Positive weighted cost+latency total, independent of the reward sign
    convention (the reference conflates the two: ``cost = -reward`` at
    ``final_evaluation.py:60`` on a *positive* reward)."""
    return ep_reward * params.reward_sign


def run_episodes(
    env_params: env_core.EnvParams,
    policy_fn: Callable,
    num_episodes: int,
    seed: int = 0,
):
    """Run ``num_episodes`` full episodes in parallel (one scan, no resets).

    Returns ``(episode_rewards [E], action_counts [E, C], lengths [E])``.
    Episodes are fixed-length (``max_steps``), matching the reference's CSV
    replay semantics, so a single scan of ``max_steps`` covers exactly one
    episode per batch lane.
    """
    max_steps = int(env_params.max_steps)

    @jax.jit
    def _run(key):
        reset_key, rollout_key = jax.random.split(key)
        state, obs = reset_batch(env_params, reset_key, num_episodes)
        _, _, _, traj = rollout_from(
            env_params, state, obs, rollout_key, policy_fn, max_steps
        )
        ep_rewards = traj["reward"].sum(axis=0)          # [E]
        actions = traj["action"]                          # [T, E]
        counts = jnp.stack(
            [(actions == c).sum(axis=0) for c in range(env_core.NUM_ACTIONS)],
            axis=-1,
        )                                                 # [E, C]
        lengths = jnp.full((num_episodes,), max_steps, jnp.int32)
        return ep_rewards, counts, lengths

    return _run(jax.random.PRNGKey(seed))


def baseline_episode_cost(env_params: env_core.EnvParams, policy: str = "greedy") -> float:
    """Exact episode cost of a deterministic baseline on the table (no RNG
    needed: cost-greedy and round-robin depend only on the table rows)."""
    steps = jnp.arange(int(env_params.max_steps))
    costs = env_params.costs[steps]
    lats = env_params.latencies[steps]
    if policy == "greedy":
        acts = cost_greedy_policy(costs)
    elif policy == "round_robin":
        acts = round_robin_policy(steps)
    else:
        raise ValueError(policy)
    chosen_cost = jnp.take_along_axis(costs, acts[:, None], axis=1)[:, 0]
    chosen_lat = jnp.take_along_axis(lats, acts[:, None], axis=1)[:, 0]
    per_step = env_params.reward_scale * (
        env_params.cost_weight * chosen_cost + env_params.latency_weight * chosen_lat
    )
    return float(per_step.sum())


def evaluate(
    env_params: env_core.EnvParams,
    policy_fn: Callable,
    num_episodes: int = 100,
    seed: int = 0,
) -> EvalReport:
    """Greedy evaluation + aggregate report (final_evaluation.py parity)."""
    ep_rewards, counts, lengths = run_episodes(
        env_params, policy_fn, num_episodes, seed
    )
    avg_reward = float(ep_rewards.mean())
    avg_cost = float(_episode_cost(env_params, ep_rewards).mean())
    total = counts.sum()
    fractions = tuple(float(c) for c in counts.sum(axis=0) / jnp.maximum(total, 1))
    if float(env_params.fault_prob) > 0.0:
        # Fault injection perturbs rewards stochastically; the closed-form
        # table baseline would not be comparable. Run the greedy baseline
        # through the same faulted env instead (different key stream).
        base_rewards, _, _ = run_episodes(
            env_params, BASELINE_POLICIES["greedy"], num_episodes, seed + 1
        )
        baseline = float(_episode_cost(env_params, base_rewards).mean())
    else:
        baseline = baseline_episode_cost(env_params, "greedy")
    improvement = (baseline - avg_cost) / baseline * 100.0 if baseline else 0.0
    return EvalReport(
        num_episodes=num_episodes,
        avg_episode_reward=avg_reward,
        avg_episode_cost=avg_cost,
        choice_fractions=fractions,
        avg_episode_length=float(lengths.mean()),
        baseline_cost=baseline,
        improvement_pct=improvement,
    )


def quick_eval(
    env_params: env_core.EnvParams,
    net,
    params,
    num_steps: int = 20,
    seed: int = 0,
    print_fn: Callable = print,
) -> float:
    """Per-step sanity rollout (reference ``eval_ppo.py:17-31``): greedy
    actions, printed cloud choice / reward / CPU observation per step."""
    policy = greedy_policy_fn(net, params)
    key = jax.random.PRNGKey(seed)
    state, obs = env_core.reset(env_params, key)
    obs = jax.device_get(obs)
    total = 0.0
    t = -1  # num_steps=0: report "0 steps" instead of NameError below
    for t in range(num_steps):
        action = int(policy(obs[None, :], None)[0])
        state, ts = env_core.step(env_params, state, jnp.asarray(action))
        # One device sync for the whole timestep (GL008): the previous
        # float(ts.reward) (twice!) + bool(ts.done) + obs formatting cost
        # four separate round-trips per printed step.
        # graftlint: disable=GL009 -- quick_eval IS a per-step interactive walkthrough: printing each step is the product, and this single batched fetch per printed step is already the minimum (GL008)
        next_obs, reward, done = jax.device_get((ts.obs, ts.reward, ts.done))
        total += float(reward)
        print_fn(
            f"Step {t + 1:2d}: cloud={CLOUD_NAMES[action]:5s} "
            f"reward={float(reward):8.3f} cpu={obs[4]:.2f}/{obs[5]:.2f}"
        )
        obs = next_obs
        if done:
            break
    print_fn(f"Total reward over {t + 1} steps: {total:.3f}")
    return total


BASELINE_POLICIES = {
    "greedy": lambda obs, key: cost_greedy_policy(obs),
    "random": lambda obs, key: random_policy(key, obs.shape[:-1]),
}


# ------------------------------------------- structured envs (configs 4-5)


@dataclasses.dataclass(frozen=True)
class StructuredEvalReport:
    """Greedy evaluation of a structured (per-node) policy vs the
    hand-coded node baselines — the reproducible form of the
    status-table convergence comparisons (docs/status.md rows 4-5)."""

    env: str
    num_episodes: int
    avg_episode_reward: float
    baseline_rewards: dict          # name -> mean episode reward
    improvement_vs_best_baseline_pct: float
    cloud_fractions: tuple          # decision split over clouds

    def summary(self) -> str:
        best_name = max(self.baseline_rewards,
                        key=lambda k: self.baseline_rewards[k])
        lines = [
            "=" * 60,
            f"STRUCTURED EVALUATION SUMMARY ({self.env})",
            "=" * 60,
            f"Episodes evaluated:       {self.num_episodes}",
            f"Policy episode reward:    {self.avg_episode_reward:.1f}",
        ]
        for name, r in sorted(self.baseline_rewards.items()):
            lines.append(f"Baseline {name:<15s} {r:.1f}")
        lines += [
            f"Improvement vs best baseline ({best_name}): "
            f"{self.improvement_vs_best_baseline_pct:+.1f}%",
            "Cloud choice split:       "
            + ", ".join(
                f"{name} {frac * 100:.1f}%"
                for name, frac in zip(CLOUD_NAMES, self.cloud_fractions)
            ),
            "=" * 60,
        ]
        return "\n".join(lines)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def run_bundle_episodes(bundle, policy_fn, num_episodes: int, seed: int = 0):
    """``(episode_rewards [E], chosen_clouds [T, E])`` for one full episode
    per batch lane on ANY structured bundle (fixed-length episodes, like
    :func:`run_episodes` for the flat env)."""
    steps = bundle.episode_steps

    @jax.jit
    def _run(key):
        reset_key, policy_key = jax.random.split(key)
        state, obs = bundle.reset_batch(reset_key, num_episodes)

        def step_fn(carry, k):
            state, obs = carry
            action = policy_fn(obs, k)
            state, ts = bundle.step_batch(state, action)
            return (state, ts.obs), (ts.reward, ts.chosen_cloud)

        keys = jax.random.split(policy_key, steps)
        _, (rewards, clouds) = jax.lax.scan(step_fn, (state, obs), keys)
        return rewards.sum(axis=0), clouds

    return _run(jax.random.PRNGKey(seed))


def best_node_baseline_reward(env_name: str, bundle,
                              num_episodes: int = 64,
                              seed: int = 0) -> float:
    """Mean episode reward of the BEST hand-coded node baseline on this
    bundle — the stall-guard threshold for ``train_ppo
    --reseed-on-stall``: a healthy seed's in-training greedy eval crosses
    this within ~16 iterations at fleet N, a fragile seed never does
    (measured, docs/scaling.md §1b)."""
    from rl_scheduler_tpu.env.baselines import structured_baselines

    return max(
        float(run_bundle_episodes(bundle, fn, num_episodes, seed)[0].mean())
        for fn in structured_baselines(env_name).values()
    )


def structured_evaluate(env_name: str, bundle, net, params,
                        num_episodes: int = 100,
                        seed: int = 0) -> StructuredEvalReport:
    """Evaluate a cluster_set/cluster_graph checkpoint greedily against
    the hand-coded node baselines (random / cheapest-node / load-spread,
    ``env/baselines.py``) on the same episode batch sizes."""
    from rl_scheduler_tpu.env.baselines import structured_baselines

    policy = greedy_policy_fn(net, params)
    ep_rewards, clouds = run_bundle_episodes(bundle, policy,
                                             num_episodes, seed)
    base_rewards = {}
    for name, fn in structured_baselines(env_name).items():
        # All baselines share ONE seed stream (seed+1, distinct from the
        # policy's): a paired comparison on identical episode draws, not
        # independent samples per baseline.
        r, _ = run_bundle_episodes(bundle, fn, num_episodes, seed + 1)
        base_rewards[name] = float(r.mean())
    avg_reward = float(ep_rewards.mean())
    best = max(base_rewards.values())
    improvement = ((avg_reward - best) / abs(best) * 100.0) if best else 0.0
    counts = jnp.stack([(clouds == c).sum() for c in range(len(CLOUD_NAMES))])
    total = jnp.maximum(counts.sum(), 1)
    return StructuredEvalReport(
        env=env_name,
        num_episodes=num_episodes,
        avg_episode_reward=avg_reward,
        baseline_rewards=base_rewards,
        improvement_vs_best_baseline_pct=float(improvement),
        cloud_fractions=tuple(float(c) / float(total) for c in counts),
    )


# ------------------------------------------ scenario × policy eval matrix

MATRIX_SCHEMA_VERSION = 1


def _load_set_checkpoint(run_dir: Path, best: bool = False) -> tuple:
    """``((net, params, node_feat), meta)`` for a cluster_set checkpoint
    run dir — the shared loader for the matrix's checkpoint column, the
    transfer grid's generalist, and its per-family specialists."""
    from rl_scheduler_tpu.utils.checkpoint import load_policy_params

    if best:
        from rl_scheduler_tpu.agent.loop import BEST_DIR

        best_dir = run_dir / BEST_DIR
        if not (best_dir / "checkpoints").is_dir():
            # Same friendly refusal as the non-matrix --best path.
            raise SystemExit(
                f"--best: no best-eval checkpoint under {run_dir} "
                "(the keeper runs whenever training has --eval-every "
                "active)")
        run_dir = best_dir
    params, meta = load_policy_params(run_dir)
    if meta.get("env") != "cluster_set":
        raise SystemExit(
            f"the scenario matrix/transfer grid sweeps the set family; "
            f"checkpoint {run_dir} trained env {meta.get('env')!r}")
    from rl_scheduler_tpu.models import SetTransformerPolicy

    num_heads = meta.get("num_heads")
    if num_heads is None:
        # Checkpoints from before num_heads was recorded were always
        # 4-head (the same mandatory fallback as the --run eval path).
        num_heads = 4
    net = SetTransformerPolicy(dim=64, depth=2, num_heads=num_heads)
    return (net, params, meta.get("node_feat") or 6), meta


def _trained_families(meta: dict) -> tuple:
    """The families a checkpoint's training distribution covered — a
    mixture's component families (graftmix meta), a single scenario's
    family, or the bare CSV replay (domain_random-shaped)."""
    if meta.get("mixture_families"):
        return tuple(meta["mixture_families"])
    if meta.get("scenario_family"):
        return (meta["scenario_family"],)
    return ()


def _matrix_cell_policies(scenario_name: str, columns: dict,
                          node_feat: int, checkpoint: tuple | None) -> dict:
    """``{policy_name: policy_fn}`` for one matrix row: the hand-coded
    node baselines read THIS scenario's column layout (satellite fix —
    a widened observation must not silently score the wrong column), and
    a checkpoint policy joins only when its trained observation width
    matches the scenario's (an incompatible cell is reported, not
    silently scored on garbage features)."""
    from rl_scheduler_tpu.env.baselines import structured_baselines

    policies = dict(structured_baselines("cluster_set", columns=columns))
    if checkpoint is not None:
        net, params, ckpt_feat = checkpoint
        if ckpt_feat == node_feat:
            policies["checkpoint"] = greedy_policy_fn(net, params)
        else:
            policies["checkpoint"] = None  # incompatible: reported below
    return policies


def scenario_policy_matrix(
    scenario_names: list,
    num_nodes: int = 8,
    episodes: int = 32,
    seed: int = 0,
    checkpoint: tuple | None = None,
    trained_families: tuple = (),
    emit: Callable[[dict], None] | None = None,
) -> list[dict]:
    """The scenario × policy-family eval matrix (ROADMAP item 5).

    One cell per (scenario, policy): ``episodes`` full fixed-length
    episodes through the scenario's vmapped bundle, every policy in a row
    evaluated on the SAME seeded episode draws (paired comparison — one
    ``PRNGKey(seed)`` per scenario, like ``structured_evaluate``'s
    baseline convention). ``"csv"`` names the un-scenarioed CSV-replay
    env, the baseline row every scenario is read against.

    ``checkpoint`` is ``(net, params, node_feat)`` from a trained run;
    cells whose scenario trains a different observation width record
    ``"incompatible": true`` plus the structured ``reason`` field
    (graftmix ``incompatible_reason`` — obs-width vs family vs
    scenario-meta) instead of a reward (the embed kernel bakes the
    width — docs/scenarios.md). ``trained_families`` (graftmix: the
    checkpoint's training-distribution families, from meta) flags each
    checkpoint cell ``held_out`` when its scenario's family was never
    trained — the zero-shot columns.

    Emits one bench-style ``schema_version``-tagged dict per cell through
    ``emit`` (the CLI writes them as JSON lines) and returns them all.
    """
    import numpy as np

    from rl_scheduler_tpu.scenarios import (
        baseline_columns,
        csv_reference_row,
        get_scenario,
        node_feat_for,
        scenario_bundle,
    )

    rows = []
    for sname in scenario_names:
        if sname == "csv":
            bundle_fn, columns, feat, sfamily = csv_reference_row()
            bundle = bundle_fn(num_nodes)
        else:
            scn = get_scenario(sname)
            bundle = scenario_bundle(scn, num_nodes)
            columns, feat = baseline_columns(scn), node_feat_for(scn)
            sfamily = scn.family
        for pname, fn in _matrix_cell_policies(
                sname, columns, feat, checkpoint).items():
            cell = {
                "schema_version": MATRIX_SCHEMA_VERSION,
                "metric": "scenario_matrix_cell",
                "scenario": sname,
                "policy": pname,
                "episodes": episodes,
                "num_nodes": num_nodes,
                "node_feat": feat,
                "seed": seed,
            }
            if pname == "checkpoint" and trained_families:
                cell["held_out"] = sfamily not in trained_families
            if fn is None:
                from rl_scheduler_tpu.mixtures.grid import (
                    incompatible_reason,
                )

                cell["incompatible"] = True
                cell.update(incompatible_reason(checkpoint[2], feat))
            else:
                ep_rewards, _ = run_bundle_episodes(bundle, fn, episodes,
                                                    seed)
                ep = np.asarray(ep_rewards)
                cell["reward_mean"] = round(float(ep.mean()), 3)
                cell["reward_std"] = round(float(ep.std()), 3)
            rows.append(cell)
            if emit is not None:
                emit(cell)
    return rows


def matrix_summary(rows: list) -> str:
    """Human-readable grid of the matrix cells (policies × scenarios).
    Scenarios whose family the checkpoint never trained on (graftmix
    ``held_out`` cells) are starred — the zero-shot columns."""
    scenarios = list(dict.fromkeys(r["scenario"] for r in rows))
    policies = list(dict.fromkeys(r["policy"] for r in rows))
    cell = {(r["scenario"], r["policy"]): r for r in rows}
    held = {r["scenario"] for r in rows if r.get("held_out")}
    labels = {s: s + ("*" if s in held else "") for s in scenarios}
    width = max(12, *(len(labels[s]) + 2 for s in scenarios))
    lines = [
        "=" * (16 + width * len(scenarios)),
        "SCENARIO x POLICY EVAL MATRIX (mean episode reward)"
        + ("   [* = held-out family]" if held else ""),
        "=" * (16 + width * len(scenarios)),
        " " * 16 + "".join(f"{labels[s]:>{width}}" for s in scenarios),
    ]
    for p in policies:
        vals = []
        for s in scenarios:
            r = cell.get((s, p))
            if r is None:
                vals.append(f"{'-':>{width}}")
            elif r.get("incompatible"):
                vals.append(f"{'incompat.':>{width}}")
            else:
                vals.append(f"{r['reward_mean']:>{width}.1f}")
        lines.append(f"{p:<16}" + "".join(vals))
    lines.append("=" * (16 + width * len(scenarios)))
    return "\n".join(lines)


def _write_report(results_dir: Path, stem: str, report) -> None:
    """Write the ``<stem>.txt`` + ``<stem>.json`` artifact pair (shared by
    the flat and structured evaluation families)."""
    results_dir.mkdir(parents=True, exist_ok=True)
    (results_dir / f"{stem}.txt").write_text(report.summary() + "\n")
    # Atomic: the report is re-read by studies/loop tooling mid-run.
    atomic_write_json(results_dir / f"{stem}.json", report.to_json(),
                      indent=2)
    print(f"Report written to {results_dir}/{stem}.txt")


def _run_matrix(args) -> list:
    """``--matrix`` mode: sweep scenarios × policy families, one JSON
    line per cell to stdout AND <results-dir>/scenario_matrix.jsonl, then
    the summary grid (``make eval-matrix``)."""
    from rl_scheduler_tpu.scenarios import list_scenarios

    names = (["csv"] + list_scenarios() if args.scenarios == "all"
             else [s.strip() for s in args.scenarios.split(",") if s.strip()])
    checkpoint, trained = None, ()
    if args.run is not None or args.best:
        from rl_scheduler_tpu.utils.checkpoint import find_latest_run

        run_dir = Path(args.run) if args.run else find_latest_run(args.run_root)
        checkpoint, meta = _load_set_checkpoint(run_dir, best=args.best)
        trained = _trained_families(meta)
        print(f"Matrix checkpoint column: {run_dir} "
              f"(node_feat={checkpoint[2]}"
              + (f", trained families: {', '.join(trained)}" if trained
                 else "") + ")")

    results_dir = Path(args.results_dir)
    results_dir.mkdir(parents=True, exist_ok=True)
    out_path = results_dir / "scenario_matrix.jsonl"
    with out_path.open("w") as fh:
        def emit(cell: dict) -> None:
            line = json.dumps(cell)
            print(line)
            fh.write(line + "\n")

        rows = scenario_policy_matrix(
            names, num_nodes=args.matrix_nodes, episodes=args.episodes,
            seed=args.seed, checkpoint=checkpoint, trained_families=trained,
            emit=emit)
    summary = matrix_summary(rows)
    print(summary)
    (results_dir / "scenario_matrix.txt").write_text(summary + "\n")
    print(f"Matrix written to {out_path}")
    return rows


def _run_transfer_grid(args) -> dict:
    """``--transfer-grid`` mode (graftmix, docs/scenarios.md): the
    zero-shot transfer grid — the generalist checkpoint vs each
    per-family specialist (or the best paired baseline) across
    scenarios × node counts, one graftstudy verdict per cell, one
    ``transfer_grid`` JSON line + the human grid (``make
    transfer-grid``)."""
    from rl_scheduler_tpu.mixtures.grid import (
        render_transfer_grid,
        transfer_cells,
        transfer_grid_summary,
    )
    from rl_scheduler_tpu.scenarios import list_scenarios
    from rl_scheduler_tpu.utils.checkpoint import find_latest_run

    run_dir = Path(args.run) if args.run else find_latest_run(args.run_root)
    checkpoint, meta = _load_set_checkpoint(run_dir, best=args.best)
    trained = _trained_families(meta)
    specialists = {}
    for item in args.specialist or ():
        sname, sep, sdir = item.partition("=")
        if not sep:
            raise SystemExit(
                f"--specialist {item!r}: pass <scenario>=<run_dir>")
        spec_ckpt, spec_meta = _load_set_checkpoint(Path(sdir))
        if spec_meta.get("mixture"):
            raise SystemExit(
                f"--specialist {sname}={sdir}: that run trained mixture "
                f"{spec_meta['mixture']!r} — a generalist is not a "
                "per-family specialist (the margin row would compare "
                "the generalist against itself)")
        if spec_meta.get("scenario") not in (None, sname):
            raise SystemExit(
                f"--specialist {sname}={sdir}: that run trained scenario "
                f"{spec_meta.get('scenario')!r}, not {sname!r} — the "
                "margin row must compare against the real specialist")
        specialists[sname] = spec_ckpt
    names = (["csv"] + list_scenarios() if args.scenarios == "all"
             else [s.strip() for s in args.scenarios.split(",") if s.strip()])
    node_counts = tuple(int(n) for n in args.grid_nodes.split(","))
    seeds = tuple(range(args.seed, args.seed + args.grid_seeds))
    print(f"Transfer grid: {run_dir} "
          f"(mixture {meta.get('mixture')!r}, trained families "
          f"{', '.join(trained) or '-'}; {len(names)} scenarios x "
          f"{len(node_counts)} node counts, {len(seeds)} paired seeds x "
          f"{args.grid_episodes} episodes"
          + (f", specialists: {', '.join(sorted(specialists))}"
             if specialists else "") + ")")

    results_dir = Path(args.results_dir)
    results_dir.mkdir(parents=True, exist_ok=True)
    cells_path = results_dir / "transfer_grid.jsonl"
    with cells_path.open("w") as fh:
        def emit(cell: dict) -> None:
            fh.write(json.dumps(cell) + "\n")

        cells = transfer_cells(
            checkpoint, names, node_counts=node_counts, seeds=seeds,
            episodes=args.grid_episodes, specialists=specialists,
            trained_families=trained,
            scenario_seed=meta.get("scenario_seed", 0) or 0, emit=emit)
    summary = transfer_grid_summary(cells, run=str(run_dir),
                                    mixture=meta.get("mixture"),
                                    trained_families=trained)
    print(json.dumps(summary, sort_keys=True))
    grid = render_transfer_grid(summary)
    print(grid)
    # Atomic: graftmix's grid consumers poll this file between cells.
    atomic_write_json(results_dir / "transfer_grid.json", summary, indent=2)
    (results_dir / "transfer_grid.txt").write_text(grid + "\n")
    print(f"Transfer grid written to {cells_path}")
    return summary


def main(argv: list[str] | None = None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--run", default=None,
                   help="run directory (default: auto-discover newest)")
    p.add_argument("--run-root", default=RuntimeConfig().checkpoint_dir)
    p.add_argument("--best", action="store_true",
                   help="evaluate the run's BEST-in-training-eval "
                        "checkpoint (<run>/best, kept whenever training "
                        "ran with --eval-every) instead of the latest — "
                        "the salvage path for late-degrade seeds "
                        "(docs/scaling.md §1b)")
    p.add_argument("--episodes", type=int, default=100)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--quick", action="store_true",
                   help="20-step per-step printout (eval_ppo.py parity)")
    p.add_argument("--baseline", choices=sorted(BASELINE_POLICIES), default=None,
                   help="evaluate a built-in baseline instead of a checkpoint")
    p.add_argument("--matrix", action="store_true",
                   help="emit the scenario x policy-family eval matrix "
                        "(one schema_version-tagged JSON line per cell to "
                        "<results-dir>/scenario_matrix.jsonl + a summary "
                        "grid; docs/scenarios.md). --run adds the "
                        "checkpoint as a policy column; --baseline/"
                        "--quick do not apply")
    p.add_argument("--scenarios", default="all",
                   help="--matrix: comma-separated scenario names, or "
                        "'all' (the registry + the csv baseline row)")
    p.add_argument("--matrix-nodes", type=int, default=8,
                   help="--matrix: node-set size each scenario builds")
    p.add_argument("--transfer-grid", action="store_true",
                   help="graftmix (docs/scenarios.md): the zero-shot "
                        "transfer grid — the --run checkpoint (a "
                        "mixture-trained generalist) vs each per-family "
                        "specialist (--specialist) or the best paired "
                        "baseline, across --scenarios x --grid-nodes, "
                        "paired seeded episodes with a graftstudy "
                        "Wilson/sign-test verdict per cell; one "
                        "transfer_grid JSON line + the human grid "
                        "(`make transfer-grid`)")
    p.add_argument("--specialist", action="append", metavar="NAME=DIR",
                   help="--transfer-grid: a per-family specialist run "
                        "for the margin row, e.g. --specialist "
                        "churn=runs/CHURN (repeatable; scenarios "
                        "without one compare against the best "
                        "hand-coded baseline on the same paired seeds)")
    p.add_argument("--grid-nodes", default="8,16",
                   help="--transfer-grid: comma-separated node counts "
                        "(the grid's second axis; >= 2 for the "
                        "acceptance protocol)")
    p.add_argument("--grid-seeds", type=int, default=5,
                   help="--transfer-grid: paired seeds per cell (the "
                        "sign test's n; 5 means only 5/5 confirms)")
    p.add_argument("--grid-episodes", type=int, default=8,
                   help="--transfer-grid: episodes per (cell, seed)")
    p.add_argument("--results-dir", default="results")
    args = p.parse_args(argv)

    if args.matrix and args.transfer_grid:
        raise SystemExit("--matrix and --transfer-grid are different "
                         "sweeps; pick one")
    if args.transfer_grid:
        return _run_transfer_grid(args)
    if args.matrix:
        return _run_matrix(args)

    if args.baseline is not None:
        env_params = env_core.make_params(EnvConfig())
        policy = BASELINE_POLICIES[args.baseline]
    else:
        from rl_scheduler_tpu.utils.checkpoint import find_latest_run, load_policy_params

        run_dir = Path(args.run) if args.run else find_latest_run(args.run_root)
        if args.best:
            from rl_scheduler_tpu.agent.loop import BEST_DIR

            best_dir = run_dir / BEST_DIR
            if not (best_dir / "checkpoints").is_dir():
                raise SystemExit(
                    f"--best: no best-eval checkpoint under {run_dir} "
                    "(the keeper runs whenever training has --eval-every "
                    "active)")
            run_dir = best_dir
        print(f"Using checkpoint run: {run_dir}")
        params, meta = load_policy_params(run_dir)
        if args.best and meta.get("best_eval") is not None:
            print(f"Best-eval checkpoint: in-training eval "
                  f"{meta['best_eval']:.2f} at its save point")
        ckpt_env = meta.get("env", "multi_cloud")
        if ckpt_env in ("cluster_set", "cluster_graph"):
            # Structured checkpoints: greedy episodes vs the hand-coded
            # node baselines (the reproducible form of the status-table
            # convergence comparisons).
            from rl_scheduler_tpu.agent.ppo import PPOTrainConfig
            from rl_scheduler_tpu.agent.train_ppo import make_bundle_and_net

            num_heads = meta.get("num_heads")
            if num_heads is None and ckpt_env == "cluster_set":
                # Checkpoints from before num_heads was recorded were
                # always 4-head (same fallback as the resume guard,
                # train_ppo.py).
                num_heads = 4
            scenario = None
            mixture = None
            if meta.get("scenario"):
                # Scenario-trained run: rebuild the SAME compiled
                # workload (name + table seed from meta) so the policy is
                # measured on the distribution it trained for — and the
                # node baselines inside structured_evaluate run on the
                # same scenario episodes (the per-scenario baseline).
                from rl_scheduler_tpu.scenarios import get_scenario

                scenario = get_scenario(meta["scenario"],
                                        seed=meta.get("scenario_seed", 0))
                print(f"Rebuilding scenario {scenario.name!r} "
                      f"(seed {scenario.seed}) from checkpoint meta")
            elif meta.get("mixture"):
                # graftmix generalist: rebuild the training MIXTURE so
                # the report measures the distribution it trained for
                # (the per-family columns live in the transfer grid,
                # evaluate --transfer-grid).
                from rl_scheduler_tpu.mixtures import get_mixture

                mixture = get_mixture(meta["mixture"])
                print(f"Rebuilding mixture {meta['mixture']!r} "
                      f"(seed {meta.get('scenario_seed', 0)}) from "
                      "checkpoint meta")
            bundle, net = make_bundle_and_net(
                ckpt_env, PPOTrainConfig(), num_heads=num_heads,
                scenario=scenario, mixture=mixture,
                mixture_seed=meta.get("scenario_seed", 0) or 0,
                # Rebuild the env at the trained node count (fleet
                # checkpoints; pre-fleet meta lacks the key -> default 8)
                # and keep flash attention for flash-trained runs — at
                # fleet-giant N the dense [B, N, N] scores cannot
                # materialize (docs/scaling.md §3).
                num_nodes=meta.get("num_nodes"),
                flash_attn=bool(meta.get("flash_attn")),
            )
            if args.quick:
                print("--quick is the flat-env per-step printout; the "
                      "structured report follows instead")
            report = structured_evaluate(
                ckpt_env, bundle, net, params,
                num_episodes=args.episodes, seed=args.seed,
            )
            print(report.summary())
            _write_report(Path(args.results_dir),
                          f"structured_evaluation_{ckpt_env}", report)
            return report
        if ckpt_env != "multi_cloud":
            raise SystemExit(
                f"checkpoint {run_dir} is for env {ckpt_env!r}; this "
                "evaluation harness covers the multi-cloud and structured "
                "(cluster_set/cluster_graph) envs — single_cluster runs "
                "are evaluated by their convergence tests"
            )
        flat_table = None
        if meta.get("scenario"):
            # Flat scenario run (bursty/price_spike tables): evaluate on
            # the same compiled table — and WITHOUT random episode
            # phases, so the closed-form cost-greedy baseline (computed
            # from this scenario's table, not the CSV's) stays exact.
            from rl_scheduler_tpu.scenarios import cloud_table, get_scenario

            flat_table = cloud_table(get_scenario(
                meta["scenario"], seed=meta.get("scenario_seed", 0)))
            print(f"Rebuilding scenario {meta['scenario']!r} tables from "
                  "checkpoint meta")
        env_params = env_core.make_params(
            EnvConfig(legacy_reward_sign=bool(meta.get("legacy_reward_sign", False))),
            table=flat_table,
        )
        from rl_scheduler_tpu.models import build_flat_policy_net

        algo = meta.get("algo", "ppo")
        hidden = tuple(meta.get("hidden") or (256, 256))
        # tp-trained checkpoints store the full global matrices in
        # TPActorCritic layout; convert once to the ActorCritic tree
        # (identical function) so evaluation needs no mesh.
        from rl_scheduler_tpu.parallel.tensor_parallel import (
            untp_checkpoint_tree,
        )

        params = untp_checkpoint_tree(meta, params)
        net = build_flat_policy_net(algo, env_core.NUM_ACTIONS, hidden)
        if args.quick:
            quick_eval(env_params, net, params)
        policy = greedy_policy_fn(net, params)

    report = evaluate(env_params, policy, args.episodes, args.seed)
    print(report.summary())
    _write_report(Path(args.results_dir), "final_evaluation_summary", report)
    return report


if __name__ == "__main__":
    main()
