"""DQN training entry point (BASELINE config 1).

The reference has no DQN; BASELINE.json's first config asks for a 2-layer
MLP DQN on the single-cluster env, 1 env, CPU. This CLI mirrors
``train_ppo``'s conventions — presets, run directory with JSONL metrics,
periodic keep-N checkpoints — on top of :func:`rl_scheduler_tpu.agent.dqn.dqn_train`.

Usage::

    python -m rl_scheduler_tpu.agent.train_dqn --preset config1 --iterations 2000
    python -m rl_scheduler_tpu.agent.train_dqn --env multi_cloud \
        --preset vector256 --iterations 500
"""

from __future__ import annotations

import argparse
import dataclasses
import time
from pathlib import Path

import jax

from rl_scheduler_tpu.agent.dqn import dqn_train
from rl_scheduler_tpu.agent.presets import DQN_PRESETS
from rl_scheduler_tpu.config import EnvConfig, RuntimeConfig
from rl_scheduler_tpu.env import core as env_core

# DQN pairs with the flat-obs envs; the set/graph envs use actor-critic
# policies trained by train_ppo (BASELINE configs 4-5).
ENVS = ("single_cluster", "multi_cloud")


def make_bundle(env_name: str):
    if env_name == "single_cluster":
        from rl_scheduler_tpu.env.bundle import single_cluster_bundle

        return single_cluster_bundle()
    if env_name == "multi_cloud":
        from rl_scheduler_tpu.env.bundle import multi_cloud_bundle

        return multi_cloud_bundle(env_core.make_params(EnvConfig()))
    raise ValueError(f"unknown env {env_name!r}; choose from {ENVS}")


def main(argv: list[str] | None = None) -> Path:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--preset", default="config1", choices=sorted(DQN_PRESETS))
    p.add_argument("--env", default="single_cluster", choices=ENVS,
                   help="env family: single_cluster (BASELINE config 1) or "
                        "multi_cloud")
    p.add_argument("--iterations", type=int, default=2000,
                   help="learner iterations (each = collect_steps x num_envs "
                        "env steps + one learner step)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--run-name", default=None)
    p.add_argument("--run-root", default=RuntimeConfig().checkpoint_dir)
    p.add_argument("--checkpoint-every", type=int, default=None,
                   help="checkpoint cadence in iterations (default 500, "
                        "auto-aligned up to --updates-per-dispatch; an "
                        "explicit misaligned value errors)")
    p.add_argument("--keep", type=int, default=5)
    p.add_argument("--eval-every", type=int, default=None,
                   help="run a greedy (epsilon=0) evaluation every N "
                        "iterations during training (reference "
                        "train_final.py:19 semantics); 0 disables")
    p.add_argument("--eval-episodes", type=int, default=None,
                   help="episodes per in-training evaluation (default 20)")
    p.add_argument("--num-envs", type=int, default=None,
                   help="override the preset's parallel env count")
    p.add_argument("--hidden", default=None,
                   help="comma-separated Q-network widths, e.g. 64,64")
    p.add_argument("--log-every", type=int, default=100,
                   help="print one progress line every N iterations (all "
                        "iterations always go to metrics.jsonl)")
    p.add_argument("--tensorboard", action="store_true",
                   help="also log metrics to TensorBoard under <run>/tb")
    p.add_argument("--sync-every", type=int, default=100,
                   help="fetch metrics for N iterations in one device->host "
                        "transfer; a DQN iteration is tiny, so per-iteration "
                        "syncing (~100 ms round-trip on a tunneled "
                        "accelerator) would dominate the run")
    p.add_argument("--updates-per-dispatch", type=int, default=1,
                   help="fuse K whole iterations into one jitted dispatch "
                        "(lax.scan over the update). sync-every only batches "
                        "metric FETCHES; this also removes the per-iteration "
                        "Python dispatch, the config-1 bottleneck. iterations "
                        "and checkpoint/eval intervals should be multiples "
                        "of K")
    p.add_argument("--debug-checks", action="store_true",
                   help="checkify the update: raise on the first NaN/"
                        "zero-division/out-of-bounds index instead of "
                        "silently corrupting training (slower; for "
                        "debugging; incompatible with "
                        "--updates-per-dispatch > 1)")
    p.add_argument("--metrics-window", type=int, default=0, metavar="N",
                   help="graftscope (docs/observability.md): device-"
                        "resident replay/grad distribution metrics "
                        "accumulated inside the jitted update, ONE host "
                        "fetch per N iterations, plus the anomaly flight "
                        "recorder (<run>/flight_recorder.jsonl). 0 "
                        "disables (the default)")
    args = p.parse_args(argv)

    from rl_scheduler_tpu.agent.loop import validate_metrics_window

    validate_metrics_window(args.metrics_window, args.updates_per_dispatch)

    cfg = DQN_PRESETS[args.preset]
    overrides = {}
    if args.num_envs is not None:
        overrides["num_envs"] = args.num_envs
    if args.hidden is not None:
        overrides["hidden"] = tuple(int(w) for w in args.hidden.split(","))
    if args.eval_every is not None:
        overrides["eval_every"] = args.eval_every
    if args.eval_episodes is not None:
        overrides["eval_episodes"] = args.eval_episodes
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    bundle = make_bundle(args.env)

    from rl_scheduler_tpu.agent.loop import align_checkpoint_interval

    args.checkpoint_every = align_checkpoint_interval(
        args.checkpoint_every, 500, args.updates_per_dispatch
    )

    run_name = args.run_name or f"DQN_{args.preset}_{time.strftime('%Y%m%d_%H%M%S')}"
    run_dir = Path(args.run_root) / run_name
    run_dir.mkdir(parents=True, exist_ok=True)
    metrics_file = (run_dir / "metrics.jsonl").open("a")

    from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

    ckpt = CheckpointManager(run_dir, keep=args.keep)

    from rl_scheduler_tpu.agent.loop import (
        TensorBoardLogger,
        make_eval_log_fn,
        make_jsonl_log_fn,
        make_periodic_checkpoint_fn,
    )

    def print_line(i: int, sps: float, metrics: dict) -> None:
        if (i + 1) % args.log_every == 0 or (i + 1) == args.iterations:
            print(
                f"Iteration {i + 1}: "
                f"reward_mean={metrics['episode_reward_mean']:.2f} "
                f"loss={metrics['loss']:.4f} eps={metrics['epsilon']:.3f} "
                f"buffer={int(metrics['buffer_size'])} | {sps:,.0f} env-steps/s",
                flush=True,
            )

    tb = TensorBoardLogger(run_dir) if args.tensorboard else None
    log_fn = make_jsonl_log_fn(metrics_file, cfg.collect_steps * cfg.num_envs,
                               print_line=print_line, tb=tb)
    checkpoint_fn = make_periodic_checkpoint_fn(
        ckpt, args.checkpoint_every, args.iterations,
        lambda runner: {
            "params": runner.params,
            "target_params": runner.target_params,
            "opt_state": runner.opt_state,
        },
        extras={
            "algo": "dqn",
            "preset": args.preset,
            "env": args.env,
            "hidden": list(cfg.hidden),
        },
    )

    scope = observer = recorder = None
    if args.metrics_window:
        from rl_scheduler_tpu.agent.loop import make_graftscope
        from rl_scheduler_tpu.utils.metrics import dqn_scope_spec

        scope = dqn_scope_spec(bundle.num_actions)
        observer, recorder = make_graftscope(
            scope, args.metrics_window, run_dir, metrics_file, tb,
            config={"algo": "dqn", "preset": args.preset,
                    "env": args.env, "seed": args.seed,
                    "iterations": args.iterations,
                    "metrics_window": args.metrics_window,
                    "hidden": list(cfg.hidden)},
        )

    eval_log = make_eval_log_fn(metrics_file, tb)
    if recorder is not None:
        eval_log = recorder.wrap_eval_log(eval_log, threshold=None)
    print(f"Training DQN preset={args.preset} env={args.env} on "
          f"{jax.devices()[0].platform} "
          f"({cfg.num_envs} envs x {cfg.collect_steps} steps/iter)")
    try:
        dqn_train(bundle, cfg, args.iterations, seed=args.seed,
                  log_fn=log_fn, checkpoint_fn=checkpoint_fn,
                  sync_every=args.sync_every,
                  eval_log_fn=eval_log,
                  debug_checks=args.debug_checks,
                  updates_per_dispatch=args.updates_per_dispatch,
                  scope=scope, observer=observer)
    except Exception as e:
        # --debug-checks composition: preserve the steps leading up to
        # the first NaN before the checkified error unwinds.
        if recorder is not None:
            recorder.dump_exception(e)
        raise
    metrics_file.close()
    if tb is not None:
        tb.close()
    print(f"Training finished! Checkpoints in {run_dir}")
    return run_dir


if __name__ == "__main__":
    main()
