"""DQN training entry point (BASELINE config 1).

The reference has no DQN; BASELINE.json's first config asks for a 2-layer
MLP DQN on the single-cluster env, 1 env, CPU. This CLI mirrors
``train_ppo``'s conventions — presets, run directory with JSONL metrics,
periodic keep-N checkpoints — on top of :func:`rl_scheduler_tpu.agent.dqn.dqn_train`.

Usage::

    python -m rl_scheduler_tpu.agent.train_dqn --preset config1 --iterations 2000
    python -m rl_scheduler_tpu.agent.train_dqn --env multi_cloud \
        --preset vector256 --iterations 500
"""

from __future__ import annotations

import argparse
import dataclasses
import time
from pathlib import Path

import jax

from rl_scheduler_tpu.agent.dqn import dqn_train
from rl_scheduler_tpu.agent.presets import DQN_PRESETS
from rl_scheduler_tpu.config import EnvConfig, RuntimeConfig
from rl_scheduler_tpu.env import core as env_core

# DQN pairs with the flat-obs envs; the set/graph envs use actor-critic
# policies trained by train_ppo (BASELINE configs 4-5).
ENVS = ("single_cluster", "multi_cloud")


def make_bundle(env_name: str, scenario=None):
    if env_name == "single_cluster":
        from rl_scheduler_tpu.env.bundle import single_cluster_bundle

        return single_cluster_bundle()
    if env_name == "multi_cloud":
        from rl_scheduler_tpu.env.bundle import multi_cloud_bundle

        table = None
        random_start = False
        if scenario is not None:
            # Scenario layer (docs/scenarios.md): swap the CSV replay for
            # the scenario's compiled cloud tables + per-episode random
            # phases. The flat obs shape is unchanged, so the Q-network
            # and the serving stack carry over untouched.
            from rl_scheduler_tpu.scenarios import cloud_table

            table = cloud_table(scenario)
            random_start = bool(scenario.knob("random_phase", False))
        return multi_cloud_bundle(
            env_core.make_params(EnvConfig(), table=table),
            random_start=random_start)
    raise ValueError(f"unknown env {env_name!r}; choose from {ENVS}")


def main(argv: list[str] | None = None) -> Path:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--preset", default="config1", choices=sorted(DQN_PRESETS))
    p.add_argument("--env", default="single_cluster", choices=ENVS,
                   help="env family: single_cluster (BASELINE config 1) or "
                        "multi_cloud")
    p.add_argument("--iterations", type=int, default=2000,
                   help="learner iterations (each = collect_steps x num_envs "
                        "env steps + one learner step)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--scenario", default=None,
                   help="multi_cloud only: train on a workload scenario's "
                        "compiled cloud tables instead of the CSV replay "
                        "(bursty | price_spike — the families with a "
                        "cloud-level story; docs/scenarios.md). Recorded "
                        "in checkpoint meta")
    p.add_argument("--scenario-seed", type=int, default=0,
                   help="seed for the scenario's table compilation")
    p.add_argument("--run-name", default=None)
    p.add_argument("--run-root", default=RuntimeConfig().checkpoint_dir)
    p.add_argument("--checkpoint-every", type=int, default=None,
                   help="checkpoint cadence in iterations (default 500, "
                        "auto-aligned up to --updates-per-dispatch; an "
                        "explicit misaligned value errors)")
    p.add_argument("--keep", type=int, default=5)
    p.add_argument("--eval-every", type=int, default=None,
                   help="run a greedy (epsilon=0) evaluation every N "
                        "iterations during training (reference "
                        "train_final.py:19 semantics); 0 disables")
    p.add_argument("--eval-episodes", type=int, default=None,
                   help="episodes per in-training evaluation (default 20)")
    p.add_argument("--resume", action="store_true",
                   help="continue from the latest VERIFIED checkpoint in "
                        "the run dir (requires --run-name of an existing "
                        "run). graftguard full-state checkpoints resume "
                        "bitwise-deterministically: replay buffer, env "
                        "state and RNG stream all carry over")
    p.add_argument("--num-envs", type=int, default=None,
                   help="override the preset's parallel env count")
    p.add_argument("--hidden", default=None,
                   help="comma-separated Q-network widths, e.g. 64,64")
    p.add_argument("--log-every", type=int, default=100,
                   help="print one progress line every N iterations (all "
                        "iterations always go to metrics.jsonl)")
    p.add_argument("--tensorboard", action="store_true",
                   help="also log metrics to TensorBoard under <run>/tb")
    p.add_argument("--sync-every", type=int, default=100,
                   help="fetch metrics for N iterations in one device->host "
                        "transfer; a DQN iteration is tiny, so per-iteration "
                        "syncing (~100 ms round-trip on a tunneled "
                        "accelerator) would dominate the run")
    p.add_argument("--updates-per-dispatch", type=int, default=1,
                   help="fuse K whole iterations into one jitted dispatch "
                        "(lax.scan over the update). sync-every only batches "
                        "metric FETCHES; this also removes the per-iteration "
                        "Python dispatch, the config-1 bottleneck. iterations "
                        "and checkpoint/eval intervals should be multiples "
                        "of K")
    p.add_argument("--debug-checks", action="store_true",
                   help="checkify the update: raise on the first NaN/"
                        "zero-division/out-of-bounds index instead of "
                        "silently corrupting training (slower; for "
                        "debugging; incompatible with "
                        "--updates-per-dispatch > 1)")
    p.add_argument("--metrics-window", type=int, default=0, metavar="N",
                   help="graftscope (docs/observability.md): device-"
                        "resident replay/grad distribution metrics "
                        "accumulated inside the jitted update, ONE host "
                        "fetch per N iterations, plus the anomaly flight "
                        "recorder (<run>/flight_recorder.jsonl). 0 "
                        "disables (the default)")
    args = p.parse_args(argv)

    from rl_scheduler_tpu.agent.loop import validate_metrics_window

    validate_metrics_window(args.metrics_window, args.updates_per_dispatch)

    cfg = DQN_PRESETS[args.preset]
    overrides = {}
    if args.num_envs is not None:
        overrides["num_envs"] = args.num_envs
    if args.hidden is not None:
        overrides["hidden"] = tuple(int(w) for w in args.hidden.split(","))
    if args.eval_every is not None:
        overrides["eval_every"] = args.eval_every
    if args.eval_episodes is not None:
        overrides["eval_episodes"] = args.eval_episodes
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    scenario = None
    if args.scenario is not None:
        if args.env != "multi_cloud":
            raise SystemExit(
                f"--scenario shapes the multi_cloud tables; --env "
                f"{args.env} has no scenario families here (the "
                "structured scenarios train through train_ppo)")
        from rl_scheduler_tpu.scenarios import get_scenario

        try:
            scenario = get_scenario(args.scenario, seed=args.scenario_seed)
        except ValueError as e:
            raise SystemExit(f"--scenario: {e}")
        if scenario.family not in ("bursty_diurnal", "price_spike"):
            raise SystemExit(
                f"--scenario {args.scenario} (family {scenario.family}) "
                "has no cloud-level tables; multi_cloud DQN takes "
                "bursty | price_spike")
    bundle = make_bundle(args.env, scenario=scenario)
    scenario_extras = {"scenario": None}
    if scenario is not None:
        from rl_scheduler_tpu.scenarios import scenario_meta

        scenario_extras = scenario_meta(scenario)

    from rl_scheduler_tpu.agent.loop import align_checkpoint_interval

    args.checkpoint_every = align_checkpoint_interval(
        args.checkpoint_every, 500, args.updates_per_dispatch
    )

    run_name = args.run_name or f"DQN_{args.preset}_{time.strftime('%Y%m%d_%H%M%S')}"
    run_dir = Path(args.run_root) / run_name
    run_dir.mkdir(parents=True, exist_ok=True)
    metrics_file = (run_dir / "metrics.jsonl").open("a")

    from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

    ckpt = CheckpointManager(run_dir, keep=args.keep)

    restore = None
    if args.resume:
        # graftguard verified selection: corrupt steps are quarantined and
        # the resume falls back to the newest step whose manifest checks
        # out (docs/robustness.md).
        latest = ckpt.latest_verified_step()
        if latest is None:
            raise SystemExit(
                f"--resume: no checkpoints under {run_dir} — pass "
                "--run-name of an existing run (drop --resume to start "
                "fresh)"
            )
        if latest >= args.iterations:
            raise SystemExit(
                f"--resume: run already has {latest} iterations; "
                f"--iterations is a TOTAL, so pass a value > {latest}"
            )
        meta = ckpt.restore_meta(latest)
        # PPO meta predates the algo key (train_ppo never writes one), so
        # a missing key means PPO — defaulting to "dqn" would wave a PPO
        # run dir through and fail deep inside the Orbax restore instead.
        if meta.get("algo", "ppo") != "dqn":
            raise SystemExit(
                f"--resume: run was trained by algo "
                f"{meta.get('algo', 'ppo')!r}; this is the DQN CLI "
                "(use train_ppo for PPO runs)"
            )
        ckpt_env = meta.get("env")
        if ckpt_env is not None and ckpt_env != args.env:
            raise SystemExit(
                f"--resume: run was trained on --env {ckpt_env}; pass "
                f"--env {ckpt_env}"
            )
        ckpt_preset = meta.get("preset")
        if ckpt_preset is not None and ckpt_preset != args.preset:
            raise SystemExit(
                f"--resume: run was trained with --preset {ckpt_preset}; "
                f"resuming as {args.preset!r} would silently switch "
                f"optimizer hyperparameters mid-run (pass --preset "
                f"{ckpt_preset})"
            )
        if meta.get("hidden") is not None and \
                tuple(meta["hidden"]) != tuple(cfg.hidden):
            raise SystemExit(
                f"--resume: checkpoint hidden={meta['hidden']} does not "
                f"match configured hidden={list(cfg.hidden)} (pass --hidden "
                f"{','.join(str(w) for w in meta['hidden'])})"
            )
        if meta.get("scenario") != args.scenario:
            raise SystemExit(
                f"--resume: run was trained on "
                f"{'scenario ' + repr(meta.get('scenario')) if meta.get('scenario') else 'the CSV replay'}; "
                "resuming with a different workload would silently switch "
                "the training distribution mid-run "
                + (f"(pass --scenario {meta['scenario']})"
                   if meta.get("scenario") else "(drop --scenario)"))
        if (args.scenario is not None
                and meta.get("scenario_seed") is not None
                and meta.get("scenario_seed") != args.scenario_seed):
            # Same guard as train_ppo's resume path: a different table
            # seed is a different compiled workload.
            raise SystemExit(
                f"--resume: run was trained with --scenario-seed "
                f"{meta['scenario_seed']}; resuming with "
                f"{args.scenario_seed} would swap the compiled workload "
                f"tables mid-run (pass --scenario-seed "
                f"{meta['scenario_seed']})")
        from rl_scheduler_tpu.agent.dqn import make_dqn

        init_fn, _, _ = make_dqn(bundle, cfg)
        abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(args.seed))
        target = {"params": abstract.params,
                  "target_params": abstract.target_params,
                  "opt_state": abstract.opt_state}
        ckpt_full = bool(meta.get("full_state"))
        shape_keys = ("num_envs", "collect_steps", "buffer_size")
        shape_ok = all(meta.get(k) == getattr(cfg, k) for k in shape_keys)
        if ckpt_full:
            target["loop"] = {
                "buffer": abstract.buffer._asdict(),
                "env_state": abstract.env_state,
                "obs": abstract.obs,
                "key": abstract.key,
                "env_steps": abstract.env_steps,
                "ep_return": abstract.ep_return,
                "last_episode_return": abstract.last_episode_return,
            }
        tree, _ = ckpt.restore(latest, target=target)
        if ckpt_full and not shape_ok:
            # Orbax needs the 'loop' item in the target (the target must
            # cover the checkpoint's structure; shapes it takes from
            # disk), but the buffer/env arrays are shaped for the OLD
            # knobs. Scaling a run is legitimate — drop them and resume
            # learning state only.
            tree.pop("loop")
            print("note: checkpoint env/buffer shape "
                  f"({', '.join(f'{k}={meta.get(k)}' for k in shape_keys)}) "
                  "differs from the configured run — resuming learning "
                  "state only (replay buffer and env/RNG stream restart "
                  "fresh; deterministic resume needs identical shapes)")
        restore = (tree, latest)
        import json

        metrics_file.write(json.dumps({"resumed_from_iteration": latest}) + "\n")
        metrics_file.flush()
        print(f"Resuming from iteration {latest} (checkpoints in {run_dir})")

    from rl_scheduler_tpu.agent.loop import (
        TensorBoardLogger,
        make_eval_log_fn,
        make_jsonl_log_fn,
        make_periodic_checkpoint_fn,
    )

    start_iteration = restore[1] if restore is not None else 0

    def print_line(i: int, sps: float, metrics: dict) -> None:
        if (i + 1) % args.log_every == 0 or (i + 1) == args.iterations:
            print(
                f"Iteration {i + 1}: "
                f"reward_mean={metrics['episode_reward_mean']:.2f} "
                f"loss={metrics['loss']:.4f} eps={metrics['epsilon']:.3f} "
                f"buffer={int(metrics['buffer_size'])} | {sps:,.0f} env-steps/s",
                flush=True,
            )

    tb = TensorBoardLogger(run_dir) if args.tensorboard else None
    log_fn = make_jsonl_log_fn(metrics_file, cfg.collect_steps * cfg.num_envs,
                               start_iteration, print_line=print_line, tb=tb)
    checkpoint_fn = make_periodic_checkpoint_fn(
        ckpt, args.checkpoint_every, args.iterations,
        # graftguard full-state tree: the replay buffer, env state, and
        # RNG stream ride along so interrupt-and-resume replays the
        # uninterrupted run exactly (docs/robustness.md).
        lambda runner: {
            "params": runner.params,
            "target_params": runner.target_params,
            "opt_state": runner.opt_state,
            "loop": {
                "buffer": runner.buffer._asdict(),
                "env_state": runner.env_state,
                "obs": runner.obs,
                "key": runner.key,
                "env_steps": runner.env_steps,
                "ep_return": runner.ep_return,
                "last_episode_return": runner.last_episode_return,
            },
        },
        extras={
            "algo": "dqn",
            "preset": args.preset,
            "env": args.env,
            "hidden": list(cfg.hidden),
            # Scenario provenance (None = CSV replay): the resume guard
            # and serving read it back.
            **scenario_extras,
            "full_state": True,
            # The 'loop' subtree's shapes are keyed on these; resume
            # degrades to params-only when they differ.
            "num_envs": cfg.num_envs,
            "collect_steps": cfg.collect_steps,
            "buffer_size": cfg.buffer_size,
        },
    )

    scope = observer = recorder = None
    if args.metrics_window:
        from rl_scheduler_tpu.agent.loop import make_graftscope
        from rl_scheduler_tpu.utils.metrics import dqn_scope_spec

        scope = dqn_scope_spec(bundle.num_actions)
        observer, recorder = make_graftscope(
            scope, args.metrics_window, run_dir, metrics_file, tb,
            config={"algo": "dqn", "preset": args.preset,
                    "env": args.env, "seed": args.seed,
                    "iterations": args.iterations,
                    "metrics_window": args.metrics_window,
                    "hidden": list(cfg.hidden)},
        )

    eval_log = make_eval_log_fn(metrics_file, tb)
    if recorder is not None:
        eval_log = recorder.wrap_eval_log(eval_log, threshold=None)
    print(f"Training DQN preset={args.preset} env={args.env} on "
          f"{jax.devices()[0].platform} "
          f"({cfg.num_envs} envs x {cfg.collect_steps} steps/iter)")

    import os

    from rl_scheduler_tpu.utils.preemption import guard_from_env

    # SIGTERM/SIGINT -> finish the in-flight dispatch, final checkpoint +
    # flight-recorder manifest, clean exit (same contract as train_ppo).
    guard = guard_from_env(os.environ.get("GRAFTGUARD_PREEMPT_AFTER"))
    on_preempt = None
    if recorder is not None:
        def on_preempt(iteration, _runner, _rec=recorder):
            _rec.dump("preemption", iteration,
                      detail=f"signal={guard.signum or 'simulated'}; final "
                             "checkpoint written at this iteration")
    try:
        with guard:
            dqn_train(bundle, cfg, args.iterations, seed=args.seed,
                      log_fn=log_fn, checkpoint_fn=checkpoint_fn,
                      sync_every=args.sync_every,
                      eval_log_fn=eval_log,
                      debug_checks=args.debug_checks,
                      updates_per_dispatch=args.updates_per_dispatch,
                      scope=scope, observer=observer, restore=restore,
                      preemption=guard, on_preempt=on_preempt)
    except Exception as e:
        # --debug-checks composition: preserve the steps leading up to
        # the first NaN before the checkified error unwinds.
        if recorder is not None:
            recorder.dump_exception(e)
        raise
    metrics_file.close()
    if tb is not None:
        tb.close()
    # Finalize the async save: an unfinalized final save has no integrity
    # manifest and would restore as 'legacy'.
    ckpt.close()
    if guard.stopped_at is not None:
        print(f"Preempted: clean shutdown after iteration "
              f"{guard.stopped_at + 1}; verified checkpoints in {run_dir} "
              "(resume with --resume)")
    else:
        print(f"Training finished! Checkpoints in {run_dir}")
    return run_dir


if __name__ == "__main__":
    main()
