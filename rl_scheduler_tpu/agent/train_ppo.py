"""PPO training entry point (reference ``train_ppo.py`` / ``train_final.py``).

Usage::

    python -m rl_scheduler_tpu.agent.train_ppo --preset quick --iterations 5
    python -m rl_scheduler_tpu.agent.train_ppo --preset final --iterations 80 \
        --run-name FINAL_PPO_AWS_AZURE

Prints per-iteration ``episode_reward_mean`` like the reference, checkpoints
periodically (keep-N + at-end, reference ``train_final.py:27-31``), and
writes metrics to a JSONL file in the run directory.
"""

from __future__ import annotations

import argparse
import json
import time
from pathlib import Path

import jax

from rl_scheduler_tpu.agent.ppo import ppo_train
from rl_scheduler_tpu.agent.presets import PPO_PRESETS, PRESET_IMPLIES
from rl_scheduler_tpu.config import EnvConfig, RuntimeConfig
from rl_scheduler_tpu.env import core as env_core


ENVS = ("multi_cloud", "single_cluster", "cluster_set", "cluster_graph")


class EvalStall(RuntimeError):
    """Raised by the --reseed-on-stall guard when the in-training greedy
    eval has not crossed the node-baseline threshold by the deadline —
    the measured signature of a fragile seed (docs/scaling.md §1b)."""

    def __init__(self, iteration: int, best_eval: float, threshold: float):
        self.iteration = iteration
        self.best_eval = best_eval
        self.threshold = threshold
        super().__init__(
            f"in-training eval {best_eval:.1f} below the node-baseline "
            f"threshold {threshold:.1f} at iteration {iteration}"
        )


def make_stall_guard(eval_log_fn, decision_iter: int, final_iter: int,
                     threshold: float, raise_on_stall: bool = True,
                     on_stall=None):
    """Wrap an eval-log sink with the bad-seed detector.

    Two checkpoints, both measured necessary (the 9-seed fleet64 study,
    docs/scaling.md §1b):

    - EARLY (``decision_iter``): a never-converging seed's eval never
      crosses ``threshold`` — detectable by ~iteration 16, so abandon
      after ~1 minute instead of a full run.
    - FINAL ACCEPTANCE (``final_iter``, the last eval of the run): some
      seeds read healthy at the deadline and then degrade (seeds 5/8 of
      the study: above the bar at 16, −9.7%/−53% final) — the last eval
      must ALSO beat the baseline or the run is rejected. This checks
      the same metric the final evaluation measures, up to eval
      sampling noise (different episode count/key stream; the measured
      failures sit 10-50% below the bar, far outside that noise).

    Raises :class:`EvalStall` at whichever checkpoint fails (or warns
    when the reseed budget is spent). ``on_stall(iteration, value)``
    fires right before either outcome — the flight recorder's
    eval-collapse dump hook, called exactly when the guard trips (NOT on
    pre-deadline evals, which are expected below the bar) and before the
    raise, so a reseeded attempt leaves its artifact behind.
    """
    best = float("-inf")

    def guarded(i: int, metrics: dict) -> None:
        nonlocal best
        eval_log_fn(i, metrics)
        iteration = i + 1
        current = metrics["eval_episode_reward_mean"]
        if iteration <= decision_iter:
            best = max(best, current)
        stalled = (
            (iteration == decision_iter and best < threshold)
            or (iteration == final_iter and current < threshold)
        )
        if not stalled:
            return
        value = best if iteration == decision_iter else current
        if on_stall is not None:
            on_stall(iteration, value)
        if raise_on_stall:
            raise EvalStall(iteration, value, threshold)
        print(
            f"  WARNING: eval {value:.1f} below the node-baseline "
            f"threshold {threshold:.1f} at iteration {iteration} and "
            "the reseed budget is spent — this seed's greedy policy is "
            "below baseline (docs/scaling.md §1b)",
            flush=True,
        )

    return guarded


def make_bundle_and_net(env_name: str, cfg, legacy_reward_sign: bool = False,
                        fault_prob: float | None = None,
                        num_heads: int | None = None,
                        fused_gnn: bool = False,
                        fused_set: bool = False,
                        num_nodes: int | None = None,
                        flash_attn: bool = False,
                        fused_set_block: bool = False,
                        scenario=None,
                        mixture=None,
                        mixture_seed: int = 0):
    """``(bundle, net)`` for each BASELINE env family.

    ``net=None`` means the default flat-obs ActorCritic; the set/graph envs
    pair with their structured policies (configs 4-5). ``fused_gnn``
    swaps the cluster_graph policy for the fused Pallas kernel variant
    (``ops/pallas_gnn.py`` — same checkpoint tree). ``fused_set`` swaps
    the cluster_set policy for the batch-minor fast path
    (``models/set_fast.py`` — same checkpoint tree, ~1.7x the honest
    end-to-end update throughput at tpu4096, see docs/status.md).
    ``fused_set_block`` swaps it for the whole-network fused Pallas
    kernel (``ops/pallas_set_block.py`` — same checkpoint tree, fleet
    node counts only; the fleet presets auto-select it on TPU).
    ``num_nodes`` sizes the structured envs' node set (default 8, the
    small-cluster regime). The set/GNN policies share per-node weights,
    so one checkpoint applies at any N — the env size is a training-
    distribution choice, not an architecture change (fleet-scale regime:
    docs/scaling.md).

    ``scenario`` (a :class:`rl_scheduler_tpu.scenarios.Scenario`) swaps
    the env's CSV replay for the scenario's compiled tables and
    per-episode randomization (docs/scenarios.md): cluster_set takes
    every family (the heterogeneous family substitutes its widened env,
    ``scenarios/het_env.py``, keeping the same flax set policy);
    multi_cloud takes bursty_diurnal/price_spike cloud tables (plus
    random episode phases); cluster_graph takes the price_spike family's
    raw dollar regimes.

    ``mixture`` (graftmix, a :class:`rl_scheduler_tpu.mixtures.
    MixtureSpec`) swaps the cluster_set env for the stacked mixture
    bundle: a per-episode family index drawn from the vmapped reset key
    selects which component's tables the episode replays
    (``mixtures/env.py``). The observation keeps the classic 6-feature
    layout, so every cluster_set policy path — flax, ``fused_set``,
    ``fused_set_block``, flash — composes unchanged; ``mixture_seed``
    re-seeds every component's table compilation (``--scenario-seed``).
    """
    dtype = None
    if cfg.compute_dtype == "bfloat16":
        import jax.numpy as jnp

        dtype = jnp.bfloat16
    if env_name == "multi_cloud":
        from rl_scheduler_tpu.env.bundle import multi_cloud_bundle

        kwargs = {} if fault_prob is None else {"fault_prob": fault_prob}
        table = None
        random_start = False
        if scenario is not None:
            from rl_scheduler_tpu.scenarios import cloud_table

            table = cloud_table(scenario)  # bursty/price_spike families
            random_start = bool(scenario.knob("random_phase", False))
        params = env_core.make_params(
            EnvConfig(legacy_reward_sign=legacy_reward_sign, **kwargs),
            table=table,
        )
        return multi_cloud_bundle(params, random_start=random_start), None
    if env_name == "single_cluster":
        from rl_scheduler_tpu.env.bundle import single_cluster_bundle

        return single_cluster_bundle(), None
    if env_name == "cluster_set":
        from rl_scheduler_tpu.env import cluster_set as cs
        from rl_scheduler_tpu.env.bundle import cluster_set_bundle

        if scenario is not None and scenario.family == "heterogeneous":
            # The widened multi-resource env pairs with the SAME flax set
            # policy (the embed layer infers its width from the obs); the
            # shape-specialized fast paths are refused by the CLI.
            from rl_scheduler_tpu.models import SetTransformerPolicy
            from rl_scheduler_tpu.scenarios import scenario_bundle

            het = scenario_bundle(
                scenario, num_nodes if num_nodes is not None else 8)
            kwargs = {} if num_heads is None else {"num_heads": num_heads}
            if flash_attn:
                kwargs["attn_impl"] = "flash"
            return het, SetTransformerPolicy(dim=64, depth=2, dtype=dtype,
                                             **kwargs)
        if mixture is not None:
            # graftmix: the stacked mixture bundle (classic obs layout —
            # every policy path below composes unchanged).
            from rl_scheduler_tpu.mixtures import (
                mixture_bundle,
                mixture_set_params,
            )

            set_bundle = mixture_bundle(mixture_set_params(
                mixture, num_nodes if num_nodes is not None else 8,
                seed=mixture_seed))
        elif scenario is not None:
            from rl_scheduler_tpu.scenarios import cluster_set_params

            set_bundle = cluster_set_bundle(cluster_set_params(
                scenario, num_nodes if num_nodes is not None else 8))
        else:
            set_bundle = cluster_set_bundle(cs.make_params(
                **({} if num_nodes is None else {"num_nodes": num_nodes})
            ))
        if fused_set_block:
            from rl_scheduler_tpu.models.set_fast import FusedBlockSetPolicy

            # Shape-specialized kernel: built at the env's actual node
            # count (constructor refuses non-fleet N with the pointer to
            # the dense path).
            return set_bundle, FusedBlockSetPolicy(
                num_nodes=set_bundle.num_actions, dim=64, depth=2,
                dtype=dtype,
            )
        if fused_set:
            from rl_scheduler_tpu.models.set_fast import BatchMinorSetPolicy

            return set_bundle, BatchMinorSetPolicy(
                dim=64, depth=2, dtype=dtype
            )
        from rl_scheduler_tpu.models import SetTransformerPolicy

        kwargs = {} if num_heads is None else {"num_heads": num_heads}
        if flash_attn:
            kwargs["attn_impl"] = "flash"
        return set_bundle, SetTransformerPolicy(
            dim=64, depth=2, dtype=dtype, **kwargs
        )
    if env_name == "cluster_graph":
        import numpy as np

        from rl_scheduler_tpu.env import cluster_graph
        from rl_scheduler_tpu.env.bundle import cluster_graph_bundle

        graph_kwargs = {} if num_nodes is None else {"num_nodes": num_nodes}
        if scenario is not None:
            from rl_scheduler_tpu.scenarios import raw_prices

            graph_kwargs["prices"] = raw_prices(scenario)  # price_spike
        params = cluster_graph.make_params(**graph_kwargs)
        if fused_gnn:
            from rl_scheduler_tpu.ops.pallas_gnn import FusedGNNPolicy

            net = FusedGNNPolicy(
                np.asarray(params.adjacency), dim=64, depth=3, dtype=dtype
            )
        else:
            from rl_scheduler_tpu.models import GNNPolicy

            net = GNNPolicy.from_adjacency(
                np.asarray(params.adjacency), dim=64, depth=3, dtype=dtype
            )
        return cluster_graph_bundle(params), net
    raise ValueError(f"unknown env {env_name!r}; choose from {ENVS}")


def main(argv: list[str] | None = None) -> Path:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--preset", default="quick", choices=sorted(PPO_PRESETS))
    p.add_argument("--env", default=None, choices=ENVS,
                   help="env family: multi_cloud (flagship; the default), "
                        "single_cluster (config 1), cluster_set + set "
                        "transformer (config 4), cluster_graph + GNN "
                        "(config 5). The set_fast/gnn_fast presets imply "
                        "their env (and fast-path policy)")
    p.add_argument("--iterations", type=int, default=5)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--reseed-on-stall", type=int, default=None, metavar="N",
                   help="structured envs: if the in-training greedy eval "
                        "has not crossed the best hand-coded node "
                        "baseline by --stall-deadline, abandon the "
                        "attempt and restart with the next seed (up to N "
                        "times). Automates the measured bad-seed "
                        "detection recipe of docs/scaling.md §1b; "
                        "requires --eval-every")
    p.add_argument("--stall-deadline", type=int, default=16, metavar="ITER",
                   help="iteration by which the in-training eval must "
                        "beat the node-baseline threshold (default 16 — "
                        "the measured separation point at fleet N)")
    p.add_argument("--scenario", default=None,
                   help="train on a workload scenario instead of the flat "
                        "CSV replay (rl_scheduler_tpu/scenarios/, "
                        "docs/scenarios.md): bursty | heterogeneous | "
                        "churn | price_spike. cluster_set (the default "
                        "env when this flag is set) takes every family; "
                        "multi_cloud takes bursty/price_spike; "
                        "cluster_graph takes price_spike. Recorded in "
                        "checkpoint meta — evaluation rebuilds the same "
                        "scenario and serving refuses a mismatch")
    p.add_argument("--scenario-seed", type=int, default=0,
                   help="seed for the scenario's table compilation "
                        "(independent of --seed, so a reseeded training "
                        "attempt keeps the SAME workload); with "
                        "--mixture it re-seeds every component's tables")
    p.add_argument("--mixture", default=None,
                   help="graftmix (docs/scenarios.md): train the "
                        "GENERALIST on a seeded mixture curriculum over "
                        "scenario families instead of one workload — a "
                        "registered preset (generalist | "
                        "generalist_anneal) or an inline "
                        "mixture:<scenario>*<w>+...[@anneal=E&from=...] "
                        "spec. Each episode draws its family from the "
                        "env's own vmapped reset key; weight-zero "
                        "components are refused as inert. cluster_set "
                        "only (the default env when this flag is set); "
                        "composable with --scenario-seed, "
                        "--overlap-collect, and the fleet presets. "
                        "Recorded in checkpoint meta — evaluation "
                        "rebuilds the same mixture, the transfer grid "
                        "reads the trained families, and serving "
                        "conformance answers --scenario with the "
                        "mixture name")
    p.add_argument("--sample-temp-anneal", type=float, default=None,
                   metavar="T_END",
                   help="anti-latch intervention (ROADMAP 3b, "
                        "docs/studies.md): anneal the rollout SAMPLING "
                        "temperature linearly from 1.0 to T_END over "
                        "--sample-temp-iters iterations (default: the "
                        "whole run), held there after. The iteration's "
                        "tempered policy is used consistently for "
                        "sampling, behavior log-probs, and the loss, so "
                        "each iteration is exact PPO on the tempered "
                        "policy. T_END < 1 moves training toward the "
                        "argmax the greedy eval scores; recorded in "
                        "checkpoint meta and pinned by --resume. "
                        "Composable with --scenario and "
                        "--reseed-on-stall; measure it with "
                        "`python -m rl_scheduler_tpu.studies`")
    p.add_argument("--sample-temp-iters", type=int, default=None,
                   metavar="N",
                   help="iterations over which --sample-temp-anneal ramps "
                        "(0 holds T_END from the start; default: "
                        "--iterations)")
    p.add_argument("--argmax-penalty", type=float, default=None,
                   metavar="COEFF",
                   help="anti-latch intervention (ROADMAP 3b): add COEFF x "
                        "argmax-concentration to the PPO loss "
                        "(ops/losses.py argmax_concentration — collision "
                        "probability of the batch-pooled soft-argmax "
                        "policy; penalizes an argmax latched onto one "
                        "static node premium, which per-state entropy "
                        "cannot see). 0 disables; recorded in checkpoint "
                        "meta and pinned by --resume")
    p.add_argument("--overlap-collect", action="store_true",
                   help="graftpipe (docs/roofline.md): pipeline collect "
                        "against learn — iteration k+1's rollout is "
                        "collected with the PRE-update params of "
                        "iteration k (a 1-iteration-stale behavior "
                        "policy; exact PPO off-policy correction holds "
                        "because behavior log-probs are recorded at "
                        "collect time), so inside a fused "
                        "--updates-per-dispatch program the rollout of "
                        "k+1 has no data dependency on SGD k and XLA "
                        "can overlap them. Also fuses the update "
                        "prologue (GAE routed through the Pallas kernel "
                        "at fleet shapes, epoch shuffle fused with the "
                        "minibatch gather). Off: byte-identical to the "
                        "unpipelined update. Recorded in checkpoint "
                        "meta and pinned by --resume; composes with "
                        "--dp/--sp and --sample-temp-anneal (the "
                        "collecting iteration's tau); refused with --tp")
    p.add_argument("--run-name", default=None)
    p.add_argument("--run-root", default=RuntimeConfig().checkpoint_dir)
    p.add_argument("--checkpoint-every", type=int, default=None,
                   help="checkpoint cadence in iterations (default 10, "
                        "auto-aligned up to --updates-per-dispatch; an "
                        "explicit misaligned value errors)")
    p.add_argument("--keep", type=int, default=5)
    p.add_argument("--eval-every", type=int, default=None,
                   help="run a greedy evaluation every N iterations during "
                        "training (reference train_final.py:19 evaluates "
                        "every 5; the 'final' preset defaults to that). "
                        "0 disables; eval metrics go to the console, "
                        "metrics.jsonl, and TensorBoard")
    p.add_argument("--eval-episodes", type=int, default=None,
                   help="episodes per in-training evaluation (default 20, "
                        "the reference's evaluation_duration)")
    p.add_argument("--legacy-reward-sign", action="store_true",
                   help="reproduce the reference's positive reward (SURVEY.md §7.0.1)")
    p.add_argument("--fault-from-loadtest", action="store_true",
                   help="calibrate the simulator's fault_prob from the "
                        "Locust stats exports in data/ (failure fraction "
                        "across clouds; SURVEY.md §5.3)")
    p.add_argument("--warm-start", default=None, metavar="RUN_DIR",
                   help="graftloop fine-tune: initialize the policy "
                        "PARAMS from another run's newest verified "
                        "checkpoint (graftguard-verified restore), then "
                        "train fresh from iteration 0 — new optimizer "
                        "state, new env/scenario, new RNG. Unlike "
                        "--resume this crosses scenarios on purpose "
                        "(retrain-on-what-you-serve warm-starts the "
                        "incumbent onto the compiled trace workload); "
                        "the source run dir is recorded in checkpoint "
                        "meta as warm_start provenance")
    p.add_argument("--resume", action="store_true",
                   help="continue from the latest checkpoint in the run dir "
                        "(requires --run-name of an existing run)")
    p.add_argument("--resume-best", action="store_true",
                   help="continue from the BEST-in-training-eval checkpoint "
                        "(<run>/best, kept automatically whenever "
                        "--eval-every is active) instead of the latest — "
                        "salvages a late-degrade run by training onward "
                        "from its peak (docs/scaling.md §1b)")
    p.add_argument("--num-envs", type=int, default=None,
                   help="override the preset's parallel env count")
    p.add_argument("--rollout-steps", type=int, default=None,
                   help="override the preset's rollout length per iteration")
    p.add_argument("--minibatch-size", type=int, default=None)
    p.add_argument("--num-epochs", type=int, default=None,
                   help="SGD epochs per iteration (RLlib num_sgd_iter; "
                        "presets mirror the reference's 10/15). Fewer "
                        "epochs trade sample efficiency for env-steps/s "
                        "at roughly constant wall-clock-to-convergence "
                        "on the structured-policy configs — see "
                        "docs/status.md")
    p.add_argument("--hidden", default=None,
                   help="comma-separated MLP widths, e.g. 64,64")
    p.add_argument("--fused-gnn", action="store_true",
                   help="cluster_graph only: run the policy through the "
                        "fused Pallas kernel (whole forward+backward in "
                        "VMEM per row block; same checkpoint tree — see "
                        "docs/status.md for measured throughput)")
    p.add_argument("--fused-set", action="store_true",
                   help="cluster_set only: run the policy through the "
                        "batch-minor fast path (models/set_fast.py): "
                        "identical function and checkpoint tree, "
                        "activations batch-in-lanes, bf16 block compute "
                        "by default (override with --compute-dtype "
                        "float32); ~1.7x honest end-to-end throughput at "
                        "tpu4096")
    p.add_argument("--fused-set-block", action="store_true",
                   help="cluster_set at fleet node counts (>= 32, "
                        "multiple of 8) only: run the set policy through "
                        "the whole-network fused Pallas kernel "
                        "(ops/pallas_set_block.py): embed + blocks + "
                        "heads VMEM-resident per row block, identical "
                        "function and checkpoint tree. The fleet presets "
                        "auto-select this on TPU (off-chip it runs "
                        "interpret mode: correct but slow). Single-head "
                        "only; incompatible with --fused-set/"
                        "--flash-attn/--sp")
    p.add_argument("--flash-attn", action="store_true",
                   help="cluster_set only: run the set policy's attention "
                        "through the Pallas TPU flash kernel "
                        "(ops/flash_attention.py). For node sets >= 1024 "
                        "where the dense [B, N, N] score tensor is the "
                        "memory wall — measured ~5x SLOWER below it, so "
                        "dense stays the default; --num-nodes must be a "
                        "multiple of 128")
    p.add_argument("--num-nodes", type=int, default=None,
                   help="node-set size for the structured envs "
                        "(cluster_set/cluster_graph; default 8). The "
                        "policies share per-node weights, so a checkpoint "
                        "trained at one N evaluates and serves at any N")
    p.add_argument("--num-heads", type=int, default=None,
                   help="set-transformer attention heads (cluster_set only; "
                        "default 1 — multi-head measured 3x slower at small "
                        "node sets; needed to resume runs trained with an "
                        "older multi-head default)")
    p.add_argument("--compute-dtype", default=None,
                   choices=("float32", "bfloat16"),
                   help="torso/block compute precision (params stay f32)")
    p.add_argument("--sync-every", type=int, default=1,
                   help="fetch metrics for N iterations in one device->host "
                        "transfer (prints then arrive in bursts of N); raise "
                        "on remote/tunneled accelerators where every sync "
                        "costs a network round-trip")
    p.add_argument("--dp", type=int, default=1,
                   help="data-parallel device count: shard the env batch "
                        "over a dp mesh axis with pmean gradient sync over "
                        "ICI (shard_map). -1 = all visible devices; "
                        "--num-envs stays the GLOBAL count; both num-envs "
                        "and minibatch-size must divide by dp")
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel device count (cluster_set only): "
                        "shard the set policy's NODE axis over an sp mesh "
                        "axis — attention runs as ring attention over ICI "
                        "(parallel/ring_attention.py). Composes with --dp "
                        "into one dp x sp mesh; the node count (8) must "
                        "divide by sp")
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel device count (flat-obs envs): "
                        "Megatron column/row-shard the MLP torso weights "
                        "over a tp mesh axis (parallel/tensor_parallel.py). "
                        "Composes with --dp into one dp x tp mesh; the "
                        "column widths (even indices of --hidden) must "
                        "divide by tp and --hidden needs an even number "
                        "of widths (col/row pairs)")
    p.add_argument("--updates-per-dispatch", type=int, default=1,
                   help="fuse K whole PPO iterations into one jitted "
                        "dispatch (lax.scan over the update); removes the "
                        "per-iteration dispatch round-trip that dominates "
                        "small configs (tpu64). iterations and checkpoint/"
                        "eval intervals should be multiples of K; "
                        "incompatible with --debug-checks")
    p.add_argument("--debug-checks", action="store_true",
                   help="checkify the update: raise on the first NaN/"
                        "zero-division/out-of-bounds index instead of "
                        "silently corrupting training (slower; for "
                        "debugging)")
    p.add_argument("--metrics-window", type=int, default=0, metavar="N",
                   help="graftscope (docs/observability.md): accumulate "
                        "device-resident distribution metrics (grad-norm/"
                        "ratio/advantage histograms, Welford stats, "
                        "per-cloud action counts) INSIDE the jitted "
                        "update and flush ONE summary per N iterations "
                        "(a single device_get — the GL008/GL009 "
                        "discipline). Also arms the anomaly flight "
                        "recorder (NaN/grad-spike/eval-collapse ring "
                        "dump to <run>/flight_recorder.jsonl). 0 "
                        "disables (the default)")
    p.add_argument("--tensorboard", action="store_true",
                   help="also log metrics to TensorBoard under <run>/tb")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler trace of the whole run into "
                        "this directory (keep --iterations small; view in "
                        "TensorBoard/Perfetto)")
    args = p.parse_args(argv)

    # Recipe presets (set_fast/gnn_fast) name a full measured
    # configuration: fill their implied env/fast-path flags so
    # `--preset set_fast` alone reproduces the docs/status.md row, and
    # refuse contradictions rather than silently ignoring the preset.
    implied = PRESET_IMPLIES.get(args.preset, {})
    if implied:
        if args.env is not None and args.env != implied["env"]:
            raise SystemExit(
                f"--preset {args.preset} is the measured --env "
                f"{implied['env']} recipe; it cannot train --env "
                f"{args.env} (pick a scale preset like tpu4096/tpu8192 "
                "instead)"
            )
        args.env = implied["env"]
        args.fused_set = args.fused_set or implied.get("fused_set", False)
        args.fused_gnn = args.fused_gnn or implied.get("fused_gnn", False)
        if args.num_nodes is None:
            # Node count is a scale knob, not part of the recipe identity:
            # an explicit --num-nodes overrides a preset's implied default.
            args.num_nodes = implied.get("num_nodes")
    if args.env is None:
        # A scenario (or mixture) names a workload for the structured
        # set family by default; the flat flagship stays the no-flag
        # default.
        args.env = ("cluster_set"
                    if args.scenario is not None or args.mixture is not None
                    else "multi_cloud")

    if args.resume and args.resume_best:
        # Validate before ANY side effect (run dir, managers): the two
        # flags name different restore sources.
        raise SystemExit(
            "--resume and --resume-best name different restore sources "
            "(latest vs best-in-training-eval); pick one")
    if args.warm_start is not None and (args.resume or args.resume_best):
        raise SystemExit(
            "--warm-start initializes a FRESH run from another run's "
            "params; --resume/--resume-best continue THIS run — pick one")
    if args.warm_start is not None and (args.dp != 1 or args.sp > 1
                                        or args.tp > 1):
        raise SystemExit(
            "--warm-start is single-chip for now (the sharded init paths "
            "own their param layout); drop --dp/--sp/--tp")

    mixture = None
    if args.mixture is not None:
        if args.scenario is not None:
            raise SystemExit(
                "--mixture IS a distribution over scenarios; --scenario "
                "names a single one — pick one flag")
        if args.env != "cluster_set":
            raise SystemExit(
                f"--mixture trains the set family's generalist; --env "
                f"{args.env} has no mixture bundle (use cluster_set)")
        from rl_scheduler_tpu.mixtures import get_mixture

        try:
            mixture = get_mixture(args.mixture)
        except ValueError as e:
            raise SystemExit(f"--mixture: {e}")

    scenario = None
    if args.scenario is not None:
        from rl_scheduler_tpu.scenarios import get_scenario, node_feat_for

        try:
            scenario = get_scenario(args.scenario, seed=args.scenario_seed)
        except ValueError as e:
            raise SystemExit(f"--scenario: {e}")
        env_families = {
            "multi_cloud": ("bursty_diurnal", "price_spike"),
            "cluster_set": ("bursty_diurnal", "heterogeneous", "churn",
                            "price_spike", "domain_random",
                            "trace_replay", "external_trace"),
            "cluster_graph": ("price_spike",),
        }
        allowed = env_families.get(args.env, ())
        if scenario.family not in allowed:
            raise SystemExit(
                f"--scenario {args.scenario} (family {scenario.family}) "
                f"does not shape --env {args.env}"
                + (f" (that env takes: {', '.join(allowed)})" if allowed
                   else " (scenarios shape multi_cloud/cluster_set/"
                        "cluster_graph)"))
        if scenario.family == "heterogeneous" and (
                args.fused_set or args.fused_set_block):
            raise SystemExit(
                "--scenario heterogeneous widens the observation to "
                f"{node_feat_for(scenario)} features; the shape-"
                "specialized fast paths (--fused-set/--fused-set-block) "
                "compile the classic 6-feature layout — train the flax "
                "set policy (drop the fast-path flag)")

    from rl_scheduler_tpu.parallel import maybe_initialize_distributed

    maybe_initialize_distributed()  # no-op unless multi-host coords are set

    if implied.get("fused_set_block") == "tpu" and not args.fused_set_block:
        # Fleet presets auto-select the whole-network fused kernel ON TPU
        # (where the round-5 roofline rows measured the XLA body an order
        # off its HBM floor). The implication yields to anything that
        # contradicts it: another policy path, a node-axis sharding
        # (--sp), a non-fleet --num-nodes override, or --resume (resumes
        # keep the checkpoint's recorded path — pass --fused-set-block
        # explicitly to resume a fused-block run). This platform probe
        # touches the backend, so it must stay AFTER
        # maybe_initialize_distributed() — jax.distributed refuses to
        # initialize once a backend exists.
        from rl_scheduler_tpu.ops.gae import default_platform
        from rl_scheduler_tpu.ops.pallas_set_block import is_fleet_node_count

        nodes = args.num_nodes if args.num_nodes is not None else 8
        eligible = (default_platform() == "tpu"
                    and not (args.fused_set or args.flash_attn)
                    and args.sp == 1
                    and not (args.resume or args.resume_best)
                    and args.num_heads in (None, 1)
                    and is_fleet_node_count(nodes)
                    # The fused kernel compiles the classic 6-feature
                    # layout; the het scenario's widened obs keeps flax.
                    and (scenario is None
                         or scenario.family != "heterogeneous"))
        if eligible:
            args.fused_set_block = True
            print(f"Preset {args.preset} implies --fused-set-block on TPU "
                  "(whole-network fused kernel; identical checkpoints — "
                  "train without it by picking the flags explicitly)")

    import dataclasses

    cfg = PPO_PRESETS[args.preset]
    overrides = {
        k: getattr(args, k)
        for k in ("num_envs", "rollout_steps", "minibatch_size", "num_epochs",
                  "compute_dtype", "eval_every", "eval_episodes")
        if getattr(args, k) is not None
    }
    if args.hidden is not None:
        overrides["hidden"] = tuple(int(w) for w in args.hidden.split(","))
    if overrides:
        try:
            cfg = dataclasses.replace(cfg, **overrides)
        except ValueError as e:
            # PPOTrainConfig.__post_init__ validates field ranges (e.g.
            # --num-epochs 0 would scan over zero SGD passes); surface it
            # as the CLI's actionable exit, before the run dir exists.
            raise SystemExit(str(e).replace("num_epochs", "--num-epochs", 1))
    if args.sample_temp_iters is not None and args.sample_temp_anneal is None:
        raise SystemExit(
            "--sample-temp-iters shapes the --sample-temp-anneal schedule; "
            "pass both (or drop --sample-temp-iters)")
    if (args.sample_temp_anneal is not None
            or args.argmax_penalty is not None) and args.tp > 1:
        raise SystemExit(
            "--sample-temp-anneal/--argmax-penalty instrument the shared "
            "PPO collect/loss path; the tensor-parallel trainer builds its "
            "own update (drop --tp — the anti-latch target is the "
            "structured fleet recipes anyway)")
    if args.sample_temp_anneal is not None:
        if args.sample_temp_anneal <= 0:
            raise SystemExit(
                f"--sample-temp-anneal {args.sample_temp_anneal}: the "
                "sampling temperature must stay positive (anneal TOWARD "
                "determinism, e.g. 0.5; tau=0 is the argmax limit)")
        temp_iters = (args.sample_temp_iters
                      if args.sample_temp_iters is not None
                      else args.iterations)
        if temp_iters < 0:
            raise SystemExit(
                f"--sample-temp-iters {temp_iters}: pass an iteration "
                "count >= 0 (0 holds T_END from the start)")
        cfg = dataclasses.replace(cfg, sample_temp_end=args.sample_temp_anneal,
                                  sample_temp_iters=temp_iters)
    if args.argmax_penalty is not None:
        if args.argmax_penalty < 0:
            raise SystemExit(
                f"--argmax-penalty {args.argmax_penalty}: the "
                "concentration penalty is a loss weight >= 0 (0 disables)")
        cfg = dataclasses.replace(cfg, argmax_penalty_coeff=args.argmax_penalty)
    if args.overlap_collect:
        if args.tp > 1:
            # Same boundary as the anti-latch flags: the tensor-parallel
            # trainer builds its own update, so a silently-unpipelined
            # run would misattribute its throughput to graftpipe.
            raise SystemExit(
                "--overlap-collect pipelines the shared PPO update "
                "(make_ppo_bundle); the tensor-parallel trainer builds "
                "its own update — drop --tp (the fleet structured "
                "recipes graftpipe targets never shard over tp)")
        cfg = dataclasses.replace(cfg, overlap_collect=True)
    if args.legacy_reward_sign and args.env != "multi_cloud":
        raise SystemExit(
            "--legacy-reward-sign reproduces the multi-cloud reference "
            f"reward bug and has no meaning for --env {args.env}"
        )
    if args.hidden is not None and args.env in ("cluster_set", "cluster_graph"):
        raise SystemExit(
            f"--hidden configures the MLP policy; --env {args.env} uses a "
            "structured policy with its own dimensions"
        )
    if args.num_nodes is not None:
        if args.env not in ("cluster_set", "cluster_graph"):
            raise SystemExit(
                f"--num-nodes sizes the structured envs' node set; --env "
                f"{args.env} has no node axis (use cluster_set/cluster_graph)"
            )
        floor = 4 if args.env == "cluster_graph" else 2
        if args.num_nodes < floor:
            raise SystemExit(
                f"--num-nodes {args.num_nodes}: --env {args.env} needs at "
                f"least {floor} nodes"
            )
    if args.flash_attn:
        if args.env != "cluster_set":
            raise SystemExit(
                f"--flash-attn selects the set policy's attention kernel; "
                f"it has no meaning for --env {args.env}"
            )
        if args.fused_set:
            raise SystemExit(
                "--flash-attn needs the flax policy's attention seam; "
                "--fused-set is the batch-minor path (drop one)"
            )
        from rl_scheduler_tpu.ops.flash_attention import FLASH_MIN_NODES

        flash_nodes = args.num_nodes if args.num_nodes is not None else 8
        if flash_nodes % FLASH_MIN_NODES:
            raise SystemExit(
                f"--flash-attn: --num-nodes {flash_nodes} must be a "
                f"multiple of {FLASH_MIN_NODES} (the kernel's block "
                "size); the dense default is also the measured faster "
                "choice below the N~1k memory wall"
            )
    if args.num_heads is not None and args.env != "cluster_set":
        raise SystemExit(
            f"--num-heads configures the set transformer; --env {args.env} "
            "has no attention heads"
        )
    if args.num_heads is not None and (args.num_heads < 1 or 64 % args.num_heads):
        raise SystemExit(
            f"--num-heads {args.num_heads}: must be a positive divisor of "
            "the set transformer's dim (64)"
        )
    fault_prob = None
    if args.fault_from_loadtest:
        if args.env != "multi_cloud":
            raise SystemExit(
                "--fault-from-loadtest calibrates the multi-cloud simulator; "
                f"it has no meaning for --env {args.env}"
            )
        from rl_scheduler_tpu.data.loadtest import failure_rate

        fault_prob = failure_rate()
        if fault_prob is None:
            raise SystemExit(
                "--fault-from-loadtest: no local_*_load_stats.csv exports in "
                "data/ — run `python -m rl_scheduler_tpu.data.generate` or "
                "drop in real Locust exports"
            )
        if fault_prob >= 0.99:
            # The reference's own recorded exports measure 100% failures
            # (its kind clusters were unreachable) — training against
            # always-down clusters is faithful to that data but useless.
            raise SystemExit(
                f"--fault-from-loadtest: measured failure rate "
                f"{fault_prob:.2%} means the load test never reached the "
                "clusters; calibrating from it would fault every step. "
                "Fix the exports or set EnvConfig.fault_prob explicitly."
            )
        print(f"Fault injection calibrated from load test: "
              f"fault_prob={fault_prob:.4f}")
    if args.fused_gnn and args.env != "cluster_graph":
        raise SystemExit(
            f"--fused-gnn selects the Pallas cluster_graph policy; it has "
            f"no meaning for --env {args.env}"
        )
    if args.fused_set:
        if args.env != "cluster_set":
            raise SystemExit(
                f"--fused-set selects the batch-minor cluster_set policy; "
                f"it has no meaning for --env {args.env}"
            )
        if args.num_heads is not None and args.num_heads != 1:
            raise SystemExit(
                f"--fused-set is single-head; --num-heads {args.num_heads} "
                "needs the flax policy (drop --fused-set)"
            )
        if args.compute_dtype is None:
            # The fast path's measured win includes bf16 block compute;
            # make it the default unless the user pins a dtype.
            cfg = dataclasses.replace(cfg, compute_dtype="bfloat16")
    if args.fused_set_block:
        if args.env != "cluster_set":
            raise SystemExit(
                f"--fused-set-block selects the fused set-transformer "
                f"kernel; it has no meaning for --env {args.env}"
            )
        if args.fused_set:
            raise SystemExit(
                "--fused-set-block and --fused-set are different "
                "cluster_set fast paths (whole-network Pallas kernel vs "
                "batch-minor XLA formulation); pick one"
            )
        if args.flash_attn:
            raise SystemExit(
                "--fused-set-block fuses its own attention in-kernel; "
                "--flash-attn needs the flax policy's attention seam "
                "(drop one)"
            )
        if args.num_heads is not None and args.num_heads != 1:
            raise SystemExit(
                f"--fused-set-block is single-head; --num-heads "
                f"{args.num_heads} needs the flax policy (drop "
                "--fused-set-block)"
            )
        from rl_scheduler_tpu.ops.pallas_set_block import (
            MIN_FLEET_NODES,
            is_fleet_node_count,
        )

        fb_nodes = args.num_nodes if args.num_nodes is not None else 8
        if not is_fleet_node_count(fb_nodes):
            if fb_nodes < MIN_FLEET_NODES:
                hint = ("below the fleet floor, where the hand-fused "
                        "kernel measured 3-5x WORSE than XLA "
                        "(docs/roofline.md) — use --fused-set or the "
                        "flax default there")
            else:
                hint = ("not a multiple of 8 (the kernel's sublane "
                        "tile) — round the node count, e.g. "
                        f"{fb_nodes + (-fb_nodes) % 8}")
            raise SystemExit(
                f"--fused-set-block targets fleet node counts (multiples "
                f"of 8, >= {MIN_FLEET_NODES}); --num-nodes {fb_nodes} is "
                f"{hint}"
            )
        if args.compute_dtype is None:
            # Same measured-recipe default as --fused-set: bf16 block
            # compute (LN stats / softmax / heads stay f32 in-kernel).
            cfg = dataclasses.replace(cfg, compute_dtype="bfloat16")
    if args.dp != 1 or args.sp != 1 or args.tp != 1:
        # Full validation here, BEFORE the run directory is created: every
        # bad flag combination in this CLI exits with an actionable message
        # rather than a mid-setup traceback and an empty run dir.
        if args.dp == 0 or args.dp < -1:
            raise SystemExit(
                f"--dp {args.dp}: pass a device count >= 2, or -1 for all "
                "visible devices"
            )
        if args.sp < 1 or args.tp < 1:
            raise SystemExit(
                f"--sp {args.sp} / --tp {args.tp}: pass device counts >= 1"
            )
        if args.sp > 1 and args.tp > 1:
            raise SystemExit(
                "--sp and --tp cannot combine: sp shards the structured "
                "policies' node axis, tp shards the flat MLP torso — no "
                "policy has both. Compose --dp with ONE of them."
            )
        if args.debug_checks:
            raise SystemExit(
                "--debug-checks cannot instrument the shard_map'd update; "
                "drop --dp/--sp/--tp for checkified debugging"
            )
        if args.sp > 1:
            if args.env != "cluster_set":
                raise SystemExit(
                    f"--sp shards the set policy's node axis; --env "
                    f"{args.env} has no sequence-parallel policy (use "
                    "cluster_set)"
                )
            if args.fused_set:
                raise SystemExit(
                    "--fused-set is the single-chip batch-minor path; "
                    "sequence parallelism needs the flax policy's ring "
                    "attention (drop one of the flags)"
                )
            if args.fused_set_block:
                raise SystemExit(
                    "--fused-set-block is the single-chip fused kernel "
                    "(whole node axis in VMEM); sequence parallelism "
                    "needs the flax policy's ring attention (drop one of "
                    "the flags)"
                )
            if args.flash_attn:
                raise SystemExit(
                    "--flash-attn is the single-chip flash kernel; ring "
                    "attention owns the sharded node axis under --sp "
                    "(drop one of the flags)"
                )
            sp_nodes = args.num_nodes if args.num_nodes is not None else 8
            if sp_nodes % args.sp:
                raise SystemExit(
                    f"--sp {args.sp}: the cluster_set node axis "
                    f"({sp_nodes}) must divide by sp"
                )
        if args.tp > 1:
            if args.env not in ("multi_cloud", "single_cluster"):
                raise SystemExit(
                    f"--tp shards the flat MLP policy; --env {args.env} "
                    "uses a structured policy (tp applies to multi_cloud/"
                    "single_cluster)"
                )
            if len(cfg.hidden) % 2:
                raise SystemExit(
                    f"--tp needs col/row layer pairs: --hidden has "
                    f"{len(cfg.hidden)} widths (pass an even count)"
                )
            bad = [h for i, h in enumerate(cfg.hidden) if i % 2 == 0 and h % args.tp]
            if bad:
                raise SystemExit(
                    f"--tp {args.tp}: column widths {bad} must divide by tp"
                )
        # Mirror make_mesh's arithmetic exactly so every bad device split
        # exits here with an actionable message, not as a ValueError after
        # the run directory exists.
        n_visible = len(jax.devices())
        fixed = args.sp * args.tp
        if args.dp == -1:
            if n_visible % fixed:
                raise SystemExit(
                    f"--dp -1 with sp*tp={fixed}: {n_visible} visible "
                    "devices do not divide evenly (pass an explicit --dp)"
                )
            ndev = n_visible // fixed
        else:
            ndev = args.dp
            if ndev * fixed > n_visible:
                raise SystemExit(
                    f"mesh dp={ndev} x sp={args.sp} x tp={args.tp} needs "
                    f"{ndev * fixed} devices; only {n_visible} visible"
                )
        if cfg.num_envs % ndev or cfg.minibatch_size % ndev:
            raise SystemExit(
                f"--dp {ndev}: num_envs={cfg.num_envs} and "
                f"minibatch_size={cfg.minibatch_size} must both divide by "
                "the device count"
            )
    from rl_scheduler_tpu.agent.loop import validate_metrics_window

    validate_metrics_window(args.metrics_window, args.updates_per_dispatch)
    if args.metrics_window and (args.dp != 1 or args.sp != 1 or args.tp != 1):
        raise SystemExit(
            "--metrics-window instruments the single-chip update; the "
            "sharded paths pmean scalar metrics, which would corrupt "
            "the Welford counts — drop --dp/--sp/--tp or the window"
        )

    def guard_ineligible() -> str | None:
        """Why the reseed guard cannot run with this invocation — ONE
        predicate for both the implied path (auto-disable with a note)
        and the explicit flag (hard error); two copies already drifted
        once."""
        if cfg.eval_every <= 0:
            return ("needs the in-training eval signal: pass "
                    "--eval-every (e.g. 8 — the measured recipe)")
        if cfg.eval_every > args.stall_deadline:
            return (f"--eval-every {cfg.eval_every} fires no eval at or "
                    f"before --stall-deadline {args.stall_deadline}; the "
                    "guard could never trigger")
        if args.stall_deadline >= args.iterations:
            return (f"--stall-deadline {args.stall_deadline} >= "
                    f"--iterations {args.iterations}: the guard would "
                    "fire at or after the end of training (raise "
                    "--iterations or lower the deadline)")
        if args.resume or args.resume_best:
            return ("restarts training from scratch on a stalled eval; "
                    "that contradicts --resume/--resume-best (drop one)")
        return None

    if args.reseed_on_stall is None:
        # Fleet presets imply the guard (the measured ~44% per-seed
        # greedy failure rate, docs/scaling.md §1b) — whenever the
        # invocation can use it; smoke runs and resumes auto-disable it
        # with a note instead of erroring.
        implied_guard = implied.get("reseed_on_stall")
        reason = guard_ineligible() if implied_guard else None
        args.reseed_on_stall = implied_guard if (implied_guard
                                                 and reason is None) else 0
        if args.reseed_on_stall:
            print(f"Preset {args.preset} implies --reseed-on-stall "
                  f"{implied_guard} (pass --reseed-on-stall 0 to disable)")
        elif implied_guard:
            print(f"note: preset {args.preset}'s implied reseed guard is "
                  f"disabled for this invocation ({reason})")
    if args.reseed_on_stall < 0:
        raise SystemExit(
            f"--reseed-on-stall {args.reseed_on_stall}: pass a maximum "
            "reseed count >= 1 (0 disables the guard)"
        )
    if args.reseed_on_stall:
        # The guard compares the in-training greedy eval against the
        # hand-coded NODE baselines, which only the structured envs have;
        # the flat families have no measured seed fragility to guard.
        if args.env not in ("cluster_set", "cluster_graph"):
            raise SystemExit(
                f"--reseed-on-stall guards the structured envs' measured "
                f"greedy-eval seed fragility (docs/scaling.md §1b); --env "
                f"{args.env} has no node baselines to threshold against"
            )
        reason = guard_ineligible()
        if reason is not None:
            raise SystemExit(f"--reseed-on-stall {reason}")
    bundle, net = make_bundle_and_net(args.env, cfg, args.legacy_reward_sign,
                                      fault_prob, args.num_heads,
                                      fused_gnn=args.fused_gnn,
                                      fused_set=args.fused_set,
                                      num_nodes=args.num_nodes,
                                      flash_attn=args.flash_attn,
                                      fused_set_block=args.fused_set_block,
                                      scenario=scenario, mixture=mixture,
                                      mixture_seed=args.scenario_seed)
    eval_net = None
    if args.sp > 1:
        # Training net: the bundle's own policy cloned with axis_name="sp"
        # so its attention rides the ring over ICI inside shard_map; the
        # plain policy (identical parameter tree) stays as the in-training
        # eval twin, which runs outside shard_map.
        eval_net = net
        net = net.clone(axis_name="sp")

    from rl_scheduler_tpu.agent.loop import align_checkpoint_interval

    args.checkpoint_every = align_checkpoint_interval(
        args.checkpoint_every, 10, args.updates_per_dispatch
    )

    run_name = args.run_name or f"PPO_{args.preset}_{time.strftime('%Y%m%d_%H%M%S')}"
    run_dir = Path(args.run_root) / run_name
    run_dir.mkdir(parents=True, exist_ok=True)
    metrics_file = (run_dir / "metrics.jsonl").open("a")

    from rl_scheduler_tpu.agent.loop import BEST_DIR
    from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

    ckpt = CheckpointManager(run_dir, keep=args.keep)

    restore = None
    restored_seed = None
    if args.resume or args.resume_best:
        resume_flag = "--resume-best" if args.resume_best else "--resume"
        # --resume-best restores from the best-eval keeper (<run>/best,
        # ROADMAP item 3a) instead of the newest periodic step; everything
        # else — verification, quarantine fallback, architecture guards —
        # is identical, and the continuation's new checkpoints land in
        # the MAIN manager as usual.
        resume_mgr = (CheckpointManager(run_dir / BEST_DIR, keep=1)
                      if args.resume_best else ckpt)
        # Integrity-verified selection (graftguard): the newest step whose
        # manifest checks out — corrupt/truncated steps are quarantined
        # and the resume falls back, so a torn final write costs one
        # checkpoint interval, not the run (docs/robustness.md).
        latest = resume_mgr.latest_verified_step()
        if latest is None:
            hint = ("no best-eval checkpoint (the keeper runs whenever "
                    "--eval-every is active)" if args.resume_best
                    else "no checkpoints")
            raise SystemExit(
                f"{resume_flag}: {hint} under "
                f"{run_dir / BEST_DIR if args.resume_best else run_dir} — "
                f"pass --run-name of an existing run (drop {resume_flag} "
                "to start fresh)"
            )
        if latest >= args.iterations:
            raise SystemExit(
                f"{resume_flag}: run already has {latest} iterations; "
                f"--iterations is a TOTAL, so pass a value > {latest} to "
                "train further"
            )
        # Validate architecture from the cheap meta record BEFORE the
        # state restore — a hidden-size mismatch would otherwise surface
        # as a raw Orbax structure error.
        meta = resume_mgr.restore_meta(latest)
        ckpt_scn = meta.get("scenario")
        if ckpt_scn != args.scenario:
            raise SystemExit(
                f"{resume_flag}: run was trained on "
                f"{'scenario ' + repr(ckpt_scn) if ckpt_scn else 'the CSV replay'}; "
                f"resuming on "
                f"{'scenario ' + repr(args.scenario) if args.scenario else 'the CSV replay'} "
                "would silently switch the training distribution mid-run "
                + (f"(pass --scenario {ckpt_scn})" if ckpt_scn
                   else "(drop --scenario)"))
        if ((args.scenario is not None or args.mixture is not None)
                and meta.get("scenario_seed") is not None
                and meta.get("scenario_seed") != args.scenario_seed):
            raise SystemExit(
                f"{resume_flag}: run was trained with --scenario-seed "
                f"{meta['scenario_seed']}; resuming with "
                f"{args.scenario_seed} would swap the compiled workload "
                f"tables mid-run (pass --scenario-seed "
                f"{meta['scenario_seed']})")
        # graftmix: the mixture spec is the training DISTRIBUTION — a
        # resumed run must keep it verbatim (canonical-name compare, so
        # a preset name and its inline expansion match). Checkpoints
        # from before the flag recorded nothing -> no mixture.
        ckpt_mix = meta.get("mixture")
        want_mix = mixture.canonical_name() if mixture is not None else None
        if ckpt_mix != want_mix:
            raise SystemExit(
                f"{resume_flag}: run was trained on "
                f"{'mixture ' + repr(ckpt_mix) if ckpt_mix else 'a single workload'}; "
                f"resuming on "
                f"{'mixture ' + repr(want_mix) if want_mix else 'a single workload'} "
                "would silently switch the training distribution mid-run "
                + (f"(pass --mixture {ckpt_mix!r})" if ckpt_mix
                   else "(drop --mixture)"))
        # The seed that INITIALIZED the weights: carried forward into the
        # resumed run's checkpoint meta so attribution survives a resume
        # under a different --seed (which only changes the continuation's
        # RNG stream, not the weights' provenance). Pre-seed-key
        # checkpoints resume with an explicit None — unknown provenance
        # must not be misattributed to this invocation's --seed.
        restored_seed = meta.get("seed", "unknown")
        ckpt_env = meta.get("env")
        if ckpt_env is not None and ckpt_env != args.env:
            raise SystemExit(
                f"--resume: run was trained on --env {ckpt_env}; "
                f"resuming on {args.env!r} would restore an incompatible "
                f"policy (pass --env {ckpt_env})"
            )
        ckpt_preset = meta.get("preset")
        if ckpt_preset is not None and ckpt_preset != args.preset:
            raise SystemExit(
                f"--resume: run was trained with --preset {ckpt_preset}; "
                f"resuming as {args.preset!r} would silently switch optimizer "
                f"hyperparameters mid-run (pass --preset {ckpt_preset})"
            )
        if meta.get("hidden") is not None and tuple(meta["hidden"]) != tuple(cfg.hidden):
            raise SystemExit(
                f"--resume: checkpoint hidden={meta['hidden']} does not match "
                f"configured hidden={list(cfg.hidden)} (pass --hidden "
                f"{','.join(str(w) for w in meta['hidden'])})"
            )
        ckpt_heads = meta.get("num_heads")
        if ckpt_heads is None and meta.get("env") == "cluster_set":
            # Checkpoints from before num_heads was recorded were always
            # built with the then-default of 4 heads.
            ckpt_heads = 4
        net_heads = getattr(net, "num_heads", None)
        if ckpt_heads is not None and net_heads is not None and ckpt_heads != net_heads:
            raise SystemExit(
                f"--resume: checkpoint attention uses num_heads={ckpt_heads} "
                f"but this run would build {net_heads} (the default changed "
                f"from 4 to 1); pass --num-heads {ckpt_heads}"
            )
        if args.env in ("cluster_set", "cluster_graph"):
            # Pre-fleet checkpoints (no num_nodes key) were always N=8.
            ckpt_nodes = meta.get("num_nodes") or 8
            want_nodes = args.num_nodes if args.num_nodes is not None else 8
            if ckpt_nodes != want_nodes:
                raise SystemExit(
                    f"--resume: run was trained at --num-nodes {ckpt_nodes}; "
                    f"resuming at {want_nodes} would silently change the "
                    f"training distribution mid-run (pass --num-nodes "
                    f"{ckpt_nodes}, or start a fresh run to fine-tune at a "
                    "different node count)"
                )
        ckpt_fblock = meta.get("fused_set_block")
        if ckpt_fblock is not None and bool(ckpt_fblock) != args.fused_set_block:
            # The checkpoint TREE is identical either way; the guard keeps
            # the run's recorded recipe identity stable across resumes —
            # silently switching the policy path mid-run would make the
            # run's recorded throughput provenance a lie. (The fleet
            # presets' TPU auto-selection deliberately skips --resume for
            # the same reason.)
            raise SystemExit(
                f"--resume: run was trained with "
                f"{'--fused-set-block' if ckpt_fblock else 'the dense set path'}; "
                f"{'pass' if ckpt_fblock else 'drop'} --fused-set-block to "
                "keep the recorded policy path (checkpoints are "
                "identical, but the run's recipe identity must not "
                "switch silently mid-run)"
            )
        ckpt_legacy = meta.get("legacy_reward_sign")
        if ckpt_legacy is not None and ckpt_legacy != args.legacy_reward_sign:
            raise SystemExit(
                f"--resume: checkpoint was trained with "
                f"legacy_reward_sign={ckpt_legacy}; resuming with the "
                f"opposite sign would silently negate rewards mid-run "
                f"({'add' if ckpt_legacy else 'drop'} --legacy-reward-sign)"
            )
        # Anti-latch flags are part of the training objective: a resumed
        # run must keep the recorded schedule/penalty (checkpoints from
        # before the flags existed recorded nothing -> the off defaults).
        for meta_key, flag, configured, off in (
                ("sample_temp_end", "--sample-temp-anneal",
                 cfg.sample_temp_end, 1.0),
                ("sample_temp_iters", "--sample-temp-iters",
                 cfg.sample_temp_iters, 0),
                ("argmax_penalty", "--argmax-penalty",
                 cfg.argmax_penalty_coeff, 0.0)):
            recorded = meta.get(meta_key)
            recorded = off if recorded is None else recorded
            if recorded != configured:
                raise SystemExit(
                    f"{resume_flag}: run was trained with "
                    f"{meta_key}={recorded}; resuming with {configured} "
                    "would silently change the training objective mid-run "
                    f"({'pass' if recorded != off else 'drop'} {flag}"
                    f"{' ' + str(recorded) if recorded != off else ''})"
                )
        # graftpipe: the overlap flag changes behavior-policy staleness
        # (and the full-state tree's shape), so a resumed run must keep
        # the recorded setting. Checkpoints from before the flag existed
        # recorded nothing -> the off default.
        recorded_overlap = bool(meta.get("overlap_collect"))
        if recorded_overlap != cfg.overlap_collect:
            raise SystemExit(
                f"{resume_flag}: run was trained with "
                f"{'--overlap-collect' if recorded_overlap else 'the unpipelined update'}; "
                f"{'pass' if recorded_overlap else 'drop'} --overlap-collect "
                "to keep the recorded pipeline semantics (the behavior "
                "policy's staleness must not switch silently mid-run)"
            )
        ckpt_tp = meta.get("tp") or 1
        if ckpt_tp != args.tp:
            # The PARAM tree differs (TPActorCritic col/row pairs vs
            # ActorCritic Dense stack), not just the sharding — a silent
            # restore would fail deep in Orbax or train the wrong module.
            raise SystemExit(
                f"--resume: run was trained with --tp {ckpt_tp}; resuming "
                f"with --tp {args.tp} would restore a different network "
                f"layout (pass --tp {ckpt_tp})"
            )
        if (meta.get("sp") or 1) != args.sp:
            raise SystemExit(
                f"--resume: run was trained with --sp {meta.get('sp') or 1}; "
                f"pass the same --sp (param shapes match, but the RNG/env "
                "replication layout does not)"
            )
        ckpt_full = bool(meta.get("full_state"))
        ckpt_env_shape_ok = (meta.get("num_envs") == cfg.num_envs and
                             meta.get("rollout_steps") == cfg.rollout_steps)
        if args.tp > 1:
            from rl_scheduler_tpu.parallel.tensor_parallel import (
                tp_abstract_state,
            )

            tree, _ = resume_mgr.restore(latest,
                                         target=tp_abstract_state(bundle, cfg))
        else:
            from rl_scheduler_tpu.agent.ppo import make_ppo_bundle

            # For sp runs the abstract tree comes from the unsharded twin
            # (identical param shapes; the sp net's collectives cannot
            # trace outside shard_map).
            init_fn, _, _ = make_ppo_bundle(
                bundle, cfg, net=eval_net if args.sp > 1 else net
            )
            abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(args.seed))
            target = {"params": abstract.params,
                      "opt_state": abstract.opt_state}
            if ckpt_full:
                # graftguard full-state checkpoint: env state, obs, RNG
                # key, episode returns — the deterministic-resume tree
                # (interrupt-and-resume == uninterrupted, bitwise).
                target["loop"] = {
                    "env_state": abstract.env_state,
                    "obs": abstract.obs,
                    "key": abstract.key,
                    "ep_return": abstract.ep_return,
                    "update_idx": abstract.update_idx,
                }
                if cfg.overlap_collect:
                    # graftpipe pipelined runner (the guard above pinned
                    # the flag to the checkpoint's record, so the slot is
                    # present exactly when configured).
                    target["loop"]["collect_params"] = \
                        abstract.collect_params
            tree, _ = resume_mgr.restore(latest, target=target)
            if ckpt_full and not ckpt_env_shape_ok:
                # Orbax needs the 'loop' item in the target at all (the
                # target must cover the checkpoint's structure; shapes it
                # takes from disk), but its arrays are shaped for the OLD
                # env knobs. Scaling a run up/down is legitimate — drop
                # them and resume learning state only.
                tree.pop("loop")
                print("note: checkpoint env shape (num_envs="
                      f"{meta.get('num_envs')}, rollout_steps="
                      f"{meta.get('rollout_steps')}) differs from the "
                      "configured run — resuming learning state only "
                      "(env/RNG stream restarts fresh; deterministic "
                      "resume needs identical env-shape flags)")
            elif ckpt_full and (args.dp != 1 or args.sp > 1):
                # The sharded init paths own their env/RNG layout; carry
                # only the learning state and let the continuation draw
                # fresh randomness (the pre-graftguard resume semantics).
                tree.pop("loop")
                print("note: full-state checkpoint resumed onto a "
                      "sharded mesh — env/RNG state restarts fresh "
                      "(deterministic resume is single-chip only)")
        restore = (tree, latest)
        if resume_mgr is not ckpt:
            # The best keeper was only a restore source here; the
            # continuation's own best saves reopen it below.
            resume_mgr.close()
            # Salvage semantics: training onward from the peak ABANDONS
            # the degraded tail — and frees its step numbers, or the
            # continuation's periodic/final saves at them would be
            # refused by Orbax and silently swallowed (non-fatal save
            # contract), leaving the continued run persisted nowhere
            # while --resume/evaluate still select the degraded weights.
            stale = [s for s in (ckpt.latest_step(),) if s is not None
                     and s > latest]
            ckpt.delete_steps_after(latest)
            if stale:
                print(f"--resume-best: abandoned the degraded tail past "
                      f"iteration {latest} (checkpoints newer than the "
                      "peak deleted; the continuation re-trains them)")
        # Mark the resume point in the metrics log so post-crash duplicate
        # iteration entries are separable by downstream analysis.
        metrics_file.write(json.dumps(
            {"resumed_from_iteration": latest,
             "resume_source": "best" if args.resume_best else "latest"})
            + "\n")
        metrics_file.flush()
        print(f"Resuming from iteration {latest} "
              f"({'best-eval checkpoint' if args.resume_best else 'latest'}; "
              f"checkpoints in {run_dir})")

    warm_start_params = None
    if args.warm_start is not None:
        # graftloop fine-tune-from-trace: params-only init from another
        # run's newest VERIFIED checkpoint (graftguard digests; corrupt
        # steps quarantine + fall back inside the manager). Architecture
        # mismatches fail with the meta-level message where possible;
        # ppo_train's tree-structure/shape check backstops the rest.
        from rl_scheduler_tpu.utils.checkpoint import load_policy_params

        src = Path(args.warm_start)
        if not src.is_dir():
            raise SystemExit(f"--warm-start: {src} is not a run directory")
        try:
            warm_start_params, src_meta = load_policy_params(src)
        except Exception as e:  # noqa: BLE001 — orbax raises its own zoo;
            # every restore failure here means the same thing to the user
            raise SystemExit(
                f"--warm-start: could not restore verified params from "
                f"{src}: {e}")
        src_env = src_meta.get("env")
        if src_env is not None and src_env != args.env:
            raise SystemExit(
                f"--warm-start: {src} was trained on --env {src_env}; "
                f"its params cannot initialize an {args.env!r} policy")
        src_heads = src_meta.get("num_heads")
        net_heads = getattr(net, "num_heads", None)
        if (src_heads is not None and net_heads is not None
                and src_heads != net_heads):
            raise SystemExit(
                f"--warm-start: {src} uses num_heads={src_heads}; pass "
                f"--num-heads {src_heads}")
        print(f"Warm start: params from {src} "
              f"(env {src_env}, scenario {src_meta.get('scenario')}) — "
              "fresh optimizer/env/RNG from iteration 0")

    from rl_scheduler_tpu.agent.loop import (
        TensorBoardLogger,
        make_eval_log_fn,
        make_jsonl_log_fn,
        make_periodic_checkpoint_fn,
    )

    start_iteration = restore[1] if restore is not None else 0

    def print_line(i: int, sps: float, metrics: dict) -> None:
        if metrics.get("episodes_completed", 1) > 0:
            reward_str = f"reward_mean={metrics['episode_reward_mean']:.2f}"
        else:
            # No episode finished inside this rollout (short rollouts /
            # long episodes): the episode mean is undefined, show the
            # per-step mean instead of a misleading 0.00.
            reward_str = f"step_reward_mean={metrics['reward_mean']:.4f}"
        print(f"Iteration {i + 1}: {reward_str} | {sps:,.0f} env-steps/s",
              flush=True)

    tb = TensorBoardLogger(run_dir) if args.tensorboard else None
    log_fn = make_jsonl_log_fn(metrics_file, cfg.batch_size,
                               start_iteration, print_line, tb=tb)
    checkpoint_extras = {"preset": args.preset,
                "env": args.env,
                # hidden describes the default MLP only; the set/graph
                # policies own their dimensions.
                "hidden": list(cfg.hidden) if net is None else None,
                # attention head count for the set policy (resume guard)
                "num_heads": getattr(net, "num_heads", None),
                # node-set size for the structured envs (resume guard +
                # evaluation rebuilds the env at the trained N; serving
                # is N-agnostic and ignores it)
                "num_nodes": (bundle.obs_shape[0]
                              if args.env in ("cluster_set", "cluster_graph")
                              else None),
                # provenance: the fused/flash paths produce identical
                # checkpoints, but reproductions need to know which path
                # the run's throughput came from — and evaluation rebuilds
                # flash-trained fleet-giant checkpoints with flash so the
                # dense [B, N, N] scores never materialize there
                "fused_gnn": args.fused_gnn,
                "fused_set": args.fused_set,
                "fused_set_block": args.fused_set_block,
                "flash_attn": args.flash_attn,
                # mesh axes: tp changes the param-tree layout (serving
                # converts it, parallel/tensor_parallel.py); sp only
                # changes the training-time replication layout
                "tp": args.tp,
                "sp": args.sp,
                # graftguard: single-chip runs checkpoint the FULL runner
                # (env state, obs, RNG key, episode returns) so a
                # preempted run resumes bitwise-deterministically; the
                # sharded paths keep the learning-state-only tree (their
                # init owns the env/RNG layout).
                "full_state": args.dp == 1 and args.sp == 1 and args.tp == 1,
                # The 'loop' subtree's shapes are keyed on these; resume
                # degrades to params-only when they differ.
                "num_envs": cfg.num_envs,
                "rollout_steps": cfg.rollout_steps,
                "legacy_reward_sign": args.legacy_reward_sign,
                # Anti-latch interventions (ROADMAP 3b): part of the
                # training objective, so the resume guard pins them —
                # silently switching the temperature schedule or the
                # concentration penalty mid-run would make the run's
                # verdict unattributable (docs/studies.md).
                "sample_temp_end": cfg.sample_temp_end,
                "sample_temp_iters": cfg.sample_temp_iters,
                "argmax_penalty": cfg.argmax_penalty_coeff,
                # graftpipe: the pipelined update's behavior policy is
                # one iteration stale, so the flag is part of the
                # training semantics (resume guard pins it) AND shapes
                # the full-state tree (the in-flight collect_params
                # slot below). Legacy checkpoints (no key) restore as
                # overlap-off.
                "overlap_collect": cfg.overlap_collect,
                # graftloop provenance: which run's params initialized
                # this one (None = random init). Not a resume guard —
                # a fine-tune's continuation must not need the
                # incumbent on disk.
                "warm_start": args.warm_start}
    if scenario is not None:
        # Scenario provenance: evaluation rebuilds the same workload from
        # this record, the resume guard refuses a mismatch, and serving
        # refuses a serve config whose scenario (or observation width)
        # disagrees (scheduler/extender.py).
        from rl_scheduler_tpu.scenarios import scenario_meta

        checkpoint_extras.update(scenario_meta(scenario))
    elif mixture is not None:
        # graftmix provenance: the canonical mixture name rebuilds the
        # training distribution at eval time, the resume guard pins it,
        # the transfer grid reads the trained families from it, and the
        # extender's conformance demand answers --scenario with it.
        from rl_scheduler_tpu.mixtures import mixture_meta

        checkpoint_extras.update(mixture_meta(mixture, args.scenario_seed))
    else:
        checkpoint_extras["scenario"] = None

    def checkpoint_tree_fn(runner):
        tree = {"params": runner.params, "opt_state": runner.opt_state}
        if checkpoint_extras["full_state"]:
            tree["loop"] = {"env_state": runner.env_state,
                            "obs": runner.obs,
                            "key": runner.key,
                            "ep_return": runner.ep_return,
                            "update_idx": runner.update_idx}
            if cfg.overlap_collect:
                # The pipelined runner's in-flight stale-params slot:
                # without it a resumed overlap run would restart the
                # pipeline warm (collect == params) and diverge from
                # the uninterrupted stream.
                tree["loop"]["collect_params"] = runner.collect_params
        return tree

    def make_checkpoint_fn(attempt_seed: int):
        # The seed lands in checkpoint meta so reproductions (and the
        # reseed-on-stall guard's final attempt) are attributable to the
        # exact seed that INITIALIZED the weights — on resume the
        # original run's seed is carried forward, not this invocation's
        # (an explicit null for pre-seed-key checkpoints: unknown
        # provenance, not this invocation's --seed).
        if restored_seed is not None:
            attempt_seed = (None if restored_seed == "unknown"
                            else restored_seed)
        return make_periodic_checkpoint_fn(
            ckpt, args.checkpoint_every, args.iterations,
            checkpoint_tree_fn,
            extras={**checkpoint_extras, "seed": attempt_seed},
        )

    mesh = None
    if args.dp != 1 or args.sp > 1 or args.tp > 1:
        from rl_scheduler_tpu.parallel import make_mesh

        axes = {"dp": args.dp}
        if args.sp > 1:
            axes["sp"] = args.sp
        if args.tp > 1:
            axes["tp"] = args.tp
        mesh = make_mesh(axes)
        desc = " x ".join(f"{k}={v}" for k, v in mesh.shape.items())
        print(f"Mesh {desc} ({cfg.num_envs} global envs -> "
              f"{cfg.num_envs // mesh.shape['dp']}/dp-member)")

    stall_threshold = decision_iter = None
    if args.reseed_on_stall:
        from rl_scheduler_tpu.agent.evaluate import best_node_baseline_reward

        stall_threshold = best_node_baseline_reward(
            args.env, bundle, cfg.eval_episodes, seed=args.seed)
        # Last eval firing at or before the deadline (eval_every divides
        # it into the schedule; validated > 0 above).
        decision_iter = (args.stall_deadline // cfg.eval_every) * cfg.eval_every
        # Final-acceptance checkpoint: the run's LAST eval must also beat
        # the bar (late-degrading seeds pass the early deadline — 2 of
        # the 9-seed study's 4 failures — docs/scaling.md §1b).
        final_iter = (args.iterations // cfg.eval_every) * cfg.eval_every
        print(f"Stall guard: in-training eval must beat the best node "
              f"baseline ({stall_threshold:.1f}) by iteration "
              f"{decision_iter} AND at the final eval (iteration "
              f"{final_iter}); up to {args.reseed_on_stall} reseed(s)")

    scope = observer = recorder = None
    if args.metrics_window:
        from rl_scheduler_tpu.agent.loop import make_graftscope
        from rl_scheduler_tpu.utils.metrics import ppo_scope_spec

        scope = ppo_scope_spec(bundle.num_actions)
        observer, recorder = make_graftscope(
            scope, args.metrics_window, run_dir, metrics_file, tb,
            config={**checkpoint_extras, "seed": args.seed,
                    "iterations": args.iterations,
                    "metrics_window": args.metrics_window,
                    "num_envs": cfg.num_envs,
                    "compute_dtype": cfg.compute_dtype},
        )

    print(f"Training PPO preset={args.preset} env={args.env} on "
          f"{jax.devices()[0].platform} "
          f"({cfg.num_envs} envs x {cfg.rollout_steps} steps/iter)")
    if args.profile_dir is not None:
        from rl_scheduler_tpu.utils.profiling import trace_iterations

        ctx = trace_iterations(args.profile_dir)
    else:
        import contextlib

        ctx = contextlib.nullcontext()

    import os

    from rl_scheduler_tpu.utils.preemption import guard_from_env

    # SIGTERM/SIGINT -> finish the in-flight dispatch, final checkpoint +
    # flight-recorder manifest, clean exit; GRAFTGUARD_PREEMPT_AFTER=<n>
    # arms the chaos harness's deterministic stand-in (docs/robustness.md).
    guard = guard_from_env(os.environ.get("GRAFTGUARD_PREEMPT_AFTER"))
    on_preempt = None
    if recorder is not None:
        def on_preempt(iteration, _runner, _rec=recorder):
            _rec.dump("preemption", iteration,
                      detail=f"signal={guard.signum or 'simulated'}; final "
                             "checkpoint written at this iteration")
    # Best-in-training-eval keeper (ROADMAP item 3a): whenever the eval
    # hook is active, the peak-eval runner is saved to <run>/best (keep=1,
    # async manifested saves — nearly free). Salvages the measured
    # late-degrade seeds: the final eval can reject the run while best/
    # still holds its peak (--resume-best / evaluate --best select it).
    best_ckpt = None
    initial_best = None
    if cfg.eval_every > 0:
        best_ckpt = CheckpointManager(run_dir / BEST_DIR, keep=1)
        if args.resume or args.resume_best:
            try:
                # A prior attempt's best must not be clobbered by a worse
                # continuation eval: seed the tracker's running maximum.
                initial_best = best_ckpt.restore_meta().get("best_eval")
            except FileNotFoundError:
                initial_best = None

    with guard, ctx:
        attempt = 0
        while True:
            attempt_seed = args.seed + attempt
            eval_log = make_eval_log_fn(metrics_file, tb)
            on_eval = None
            if best_ckpt is not None:
                from rl_scheduler_tpu.agent.loop import (
                    make_best_checkpoint_hook,
                )

                meta_seed = attempt_seed
                if restored_seed is not None:
                    meta_seed = (None if restored_seed == "unknown"
                                 else restored_seed)
                on_eval = make_best_checkpoint_hook(
                    best_ckpt, checkpoint_tree_fn,
                    extras={**checkpoint_extras, "seed": meta_seed},
                    initial_best=initial_best)
            if stall_threshold is not None:
                on_stall = None
                if recorder is not None:
                    def on_stall(iteration, value, _rec=recorder):
                        _rec.dump(
                            "eval_collapse", iteration - 1,
                            detail=f"eval_episode_reward_mean={value:.3f} "
                                   f"below node-baseline threshold "
                                   f"{stall_threshold:.3f}")
                eval_log = make_stall_guard(
                    eval_log, decision_iter, final_iter, stall_threshold,
                    raise_on_stall=attempt < args.reseed_on_stall,
                    on_stall=on_stall)
            if recorder is not None:
                # NaN-eval check only: collapse dumps route through the
                # guard's on_stall at its decision/final checkpoints.
                # Pre-deadline evals are EXPECTED below the baseline
                # (untrained policy), so threshold-dumping each would
                # spend max_dumps before a late real anomaly could
                # leave its ring.
                eval_log = recorder.wrap_eval_log(eval_log, threshold=None)
            try:
                ppo_train(bundle, cfg, args.iterations, seed=attempt_seed,
                          net=net, log_fn=log_fn,
                          checkpoint_fn=make_checkpoint_fn(attempt_seed),
                          restore=restore, debug_checks=args.debug_checks,
                          sync_every=args.sync_every, eval_log_fn=eval_log,
                          updates_per_dispatch=args.updates_per_dispatch,
                          mesh=mesh, eval_net=eval_net,
                          scope=scope, observer=observer,
                          preemption=guard, on_preempt=on_preempt,
                          on_eval=on_eval,
                          warm_start_params=warm_start_params)
                break
            except EvalStall as stall:
                attempt += 1
                print(f"Reseed {attempt}/{args.reseed_on_stall}: {stall} — "
                      f"restarting with seed {args.seed + attempt} "
                      "(fragile-seed signature, docs/scaling.md §1b)",
                      flush=True)
                # Marker line in the metrics log (same convention as the
                # resume marker): downstream analysis can split the
                # abandoned attempt's duplicate iteration numbers.
                metrics_file.write(json.dumps({
                    "reseed": attempt, "from_seed": attempt_seed,
                    "to_seed": args.seed + attempt,
                    "stall_iteration": stall.iteration,
                    "best_eval": stall.best_eval,
                    "threshold": stall.threshold}) + "\n")
                metrics_file.flush()
                if tb is not None:
                    # The replacement attempt re-writes the same step
                    # numbers; this marker is what makes the zig-zag
                    # attributable in the TB UI.
                    tb.add_text(
                        "reseed",
                        f"attempt {attempt}: seed {attempt_seed} -> "
                        f"{args.seed + attempt} (eval {stall.best_eval:.1f}"
                        f" < threshold {stall.threshold:.1f} at iteration "
                        f"{stall.iteration})",
                        step=attempt)
                # The abandoned attempt's checkpoints must not shadow its
                # replacement (same step numbers — Orbax would refuse the
                # overwrite and the evaluator would read stale weights).
                ckpt.clear()
                if best_ckpt is not None:
                    # Same rule for the best keeper: the reseeded attempt
                    # starts its own best race from scratch.
                    best_ckpt.clear()
                    initial_best = None
                if recorder is not None:
                    # Same reasoning for the flight recorder: the
                    # replacement re-uses iteration numbers under a new
                    # seed, so stale ring rows would be misattributed in
                    # a later dump. The manifest tags which attempt a
                    # dump belongs to.
                    recorder.reset(reseed_attempt=attempt,
                                   seed=args.seed + attempt)
            except Exception as e:
                # --debug-checks composition (and any other mid-run
                # failure): a checkified JaxRuntimeError unwinds here —
                # dump the ring so the steps LEADING UP to the first
                # NaN are preserved, then re-raise unchanged.
                if recorder is not None:
                    recorder.dump_exception(e)
                raise
    metrics_file.close()
    if tb is not None:
        tb.close()
    # Finalize the async save (graftguard: an unfinalized final save has
    # no integrity manifest and would restore as 'legacy').
    ckpt.close()
    if best_ckpt is not None:
        best_ckpt.close()
    if guard.stopped_at is not None:
        print(f"Preempted: clean shutdown after iteration "
              f"{guard.stopped_at + 1}; verified checkpoints in {run_dir} "
              "(resume with --resume)")
    else:
        print(f"Training finished! Checkpoints in {run_dir}")
    return run_dir


if __name__ == "__main__":
    main()
