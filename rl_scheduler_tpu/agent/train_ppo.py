"""PPO training entry point (reference ``train_ppo.py`` / ``train_final.py``).

Usage::

    python -m rl_scheduler_tpu.agent.train_ppo --preset quick --iterations 5
    python -m rl_scheduler_tpu.agent.train_ppo --preset final --iterations 80 \
        --run-name FINAL_PPO_AWS_AZURE

Prints per-iteration ``episode_reward_mean`` like the reference, checkpoints
periodically (keep-N + at-end, reference ``train_final.py:27-31``), and
writes metrics to a JSONL file in the run directory.
"""

from __future__ import annotations

import argparse
import json
import time
from pathlib import Path

import jax

from rl_scheduler_tpu.agent.ppo import ppo_train
from rl_scheduler_tpu.agent.presets import PPO_PRESETS
from rl_scheduler_tpu.config import EnvConfig, RuntimeConfig
from rl_scheduler_tpu.env import core as env_core


def main(argv: list[str] | None = None) -> Path:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--preset", default="quick", choices=sorted(PPO_PRESETS))
    p.add_argument("--iterations", type=int, default=5)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--run-name", default=None)
    p.add_argument("--run-root", default=RuntimeConfig().checkpoint_dir)
    p.add_argument("--checkpoint-every", type=int, default=10)
    p.add_argument("--keep", type=int, default=5)
    p.add_argument("--legacy-reward-sign", action="store_true",
                   help="reproduce the reference's positive reward (SURVEY.md §7.0.1)")
    p.add_argument("--resume", action="store_true",
                   help="continue from the latest checkpoint in the run dir "
                        "(requires --run-name of an existing run)")
    p.add_argument("--num-envs", type=int, default=None,
                   help="override the preset's parallel env count")
    p.add_argument("--rollout-steps", type=int, default=None,
                   help="override the preset's rollout length per iteration")
    p.add_argument("--minibatch-size", type=int, default=None)
    p.add_argument("--hidden", default=None,
                   help="comma-separated MLP widths, e.g. 64,64")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler trace of the whole run into "
                        "this directory (keep --iterations small; view in "
                        "TensorBoard/Perfetto)")
    args = p.parse_args(argv)

    from rl_scheduler_tpu.parallel import maybe_initialize_distributed

    maybe_initialize_distributed()  # no-op unless multi-host coords are set

    import dataclasses

    cfg = PPO_PRESETS[args.preset]
    overrides = {
        k: getattr(args, k)
        for k in ("num_envs", "rollout_steps", "minibatch_size")
        if getattr(args, k) is not None
    }
    if args.hidden is not None:
        overrides["hidden"] = tuple(int(w) for w in args.hidden.split(","))
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    env_params = env_core.make_params(EnvConfig(legacy_reward_sign=args.legacy_reward_sign))

    run_name = args.run_name or f"PPO_{args.preset}_{time.strftime('%Y%m%d_%H%M%S')}"
    run_dir = Path(args.run_root) / run_name
    run_dir.mkdir(parents=True, exist_ok=True)
    metrics_file = (run_dir / "metrics.jsonl").open("a")

    from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

    ckpt = CheckpointManager(run_dir, keep=args.keep)

    restore = None
    if args.resume:
        latest = ckpt.latest_step()
        if latest is None:
            raise SystemExit(
                f"--resume: no checkpoints under {run_dir} — pass --run-name "
                "of an existing run (drop --resume to start fresh)"
            )
        if latest >= args.iterations:
            raise SystemExit(
                f"--resume: run already has {latest} iterations; --iterations "
                f"is a TOTAL, so pass a value > {latest} to train further"
            )
        # Validate architecture from the cheap meta record BEFORE the
        # state restore — a hidden-size mismatch would otherwise surface
        # as a raw Orbax structure error.
        meta = ckpt.restore_meta(latest)
        ckpt_preset = meta.get("preset")
        if ckpt_preset is not None and ckpt_preset != args.preset:
            raise SystemExit(
                f"--resume: run was trained with --preset {ckpt_preset}; "
                f"resuming as {args.preset!r} would silently switch optimizer "
                f"hyperparameters mid-run (pass --preset {ckpt_preset})"
            )
        if meta.get("hidden") is not None and tuple(meta["hidden"]) != tuple(cfg.hidden):
            raise SystemExit(
                f"--resume: checkpoint hidden={meta['hidden']} does not match "
                f"configured hidden={list(cfg.hidden)} (pass --hidden "
                f"{','.join(str(w) for w in meta['hidden'])})"
            )
        ckpt_legacy = meta.get("legacy_reward_sign")
        if ckpt_legacy is not None and ckpt_legacy != args.legacy_reward_sign:
            raise SystemExit(
                f"--resume: checkpoint was trained with "
                f"legacy_reward_sign={ckpt_legacy}; resuming with the "
                f"opposite sign would silently negate rewards mid-run "
                f"({'add' if ckpt_legacy else 'drop'} --legacy-reward-sign)"
            )
        from rl_scheduler_tpu.agent.ppo import make_ppo

        init_fn, _, _ = make_ppo(env_params, cfg)
        abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(args.seed))
        tree, _ = ckpt.restore(
            latest,
            target={"params": abstract.params, "opt_state": abstract.opt_state},
        )
        restore = (tree, latest)
        # Mark the resume point in the metrics log so post-crash duplicate
        # iteration entries are separable by downstream analysis.
        metrics_file.write(json.dumps({"resumed_from_iteration": latest}) + "\n")
        metrics_file.flush()
        print(f"Resuming from iteration {latest} (checkpoints in {run_dir})")

    t_start = time.time()
    steps_per_iter = cfg.batch_size
    start_iteration = restore[1] if restore is not None else 0

    def log_fn(i: int, metrics: dict) -> None:
        elapsed = time.time() - t_start
        sps = steps_per_iter * (i + 1 - start_iteration) / elapsed
        line = {"iteration": i + 1, "env_steps_per_sec": round(sps, 1), **metrics}
        metrics_file.write(json.dumps(line) + "\n")
        metrics_file.flush()
        print(
            f"Iteration {i + 1}: reward_mean={metrics['episode_reward_mean']:.2f} "
            f"| {sps:,.0f} env-steps/s",
            flush=True,
        )

    def checkpoint_fn(i: int, runner) -> None:
        if (i + 1) % args.checkpoint_every == 0 or (i + 1) == args.iterations:
            ckpt.save(i + 1, {"params": runner.params, "opt_state": runner.opt_state},
                      extras={"preset": args.preset,
                              "hidden": list(cfg.hidden),
                              "legacy_reward_sign": args.legacy_reward_sign})

    print(f"Training PPO preset={args.preset} on {jax.devices()[0].platform} "
          f"({cfg.num_envs} envs x {cfg.rollout_steps} steps/iter)")
    if args.profile_dir is not None:
        from rl_scheduler_tpu.utils.profiling import trace_iterations

        ctx = trace_iterations(args.profile_dir)
    else:
        import contextlib

        ctx = contextlib.nullcontext()
    with ctx:
        ppo_train(env_params, cfg, args.iterations, seed=args.seed,
                  log_fn=log_fn, checkpoint_fn=checkpoint_fn, restore=restore)
    metrics_file.close()
    print(f"Training finished! Checkpoints in {run_dir}")
    return run_dir


if __name__ == "__main__":
    main()
