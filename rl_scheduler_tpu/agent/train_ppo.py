"""PPO training entry point (reference ``train_ppo.py`` / ``train_final.py``).

Usage::

    python -m rl_scheduler_tpu.agent.train_ppo --preset quick --iterations 5
    python -m rl_scheduler_tpu.agent.train_ppo --preset final --iterations 80 \
        --run-name FINAL_PPO_AWS_AZURE

Prints per-iteration ``episode_reward_mean`` like the reference, checkpoints
periodically (keep-N + at-end, reference ``train_final.py:27-31``), and
writes metrics to a JSONL file in the run directory.
"""

from __future__ import annotations

import argparse
import json
import time
from pathlib import Path

import jax

from rl_scheduler_tpu.agent.ppo import ppo_train
from rl_scheduler_tpu.agent.presets import PPO_PRESETS
from rl_scheduler_tpu.config import EnvConfig, RuntimeConfig
from rl_scheduler_tpu.env import core as env_core


def main(argv: list[str] | None = None) -> Path:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--preset", default="quick", choices=sorted(PPO_PRESETS))
    p.add_argument("--iterations", type=int, default=5)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--run-name", default=None)
    p.add_argument("--run-root", default=RuntimeConfig().checkpoint_dir)
    p.add_argument("--checkpoint-every", type=int, default=10)
    p.add_argument("--keep", type=int, default=5)
    p.add_argument("--legacy-reward-sign", action="store_true",
                   help="reproduce the reference's positive reward (SURVEY.md §7.0.1)")
    args = p.parse_args(argv)

    cfg = PPO_PRESETS[args.preset]
    env_params = env_core.make_params(EnvConfig(legacy_reward_sign=args.legacy_reward_sign))

    run_name = args.run_name or f"PPO_{args.preset}_{time.strftime('%Y%m%d_%H%M%S')}"
    run_dir = Path(args.run_root) / run_name
    run_dir.mkdir(parents=True, exist_ok=True)
    metrics_file = (run_dir / "metrics.jsonl").open("a")

    from rl_scheduler_tpu.utils.checkpoint import CheckpointManager

    ckpt = CheckpointManager(run_dir, keep=args.keep)

    t_start = time.time()
    steps_per_iter = cfg.batch_size

    def log_fn(i: int, metrics: dict) -> None:
        elapsed = time.time() - t_start
        sps = steps_per_iter * (i + 1) / elapsed
        line = {"iteration": i + 1, "env_steps_per_sec": round(sps, 1), **metrics}
        metrics_file.write(json.dumps(line) + "\n")
        metrics_file.flush()
        print(
            f"Iteration {i + 1}: reward_mean={metrics['episode_reward_mean']:.2f} "
            f"| {sps:,.0f} env-steps/s",
            flush=True,
        )

    def checkpoint_fn(i: int, runner) -> None:
        if (i + 1) % args.checkpoint_every == 0 or (i + 1) == args.iterations:
            ckpt.save(i + 1, {"params": runner.params, "opt_state": runner.opt_state},
                      extras={"preset": args.preset,
                              "hidden": list(cfg.hidden),
                              "legacy_reward_sign": args.legacy_reward_sign})

    print(f"Training PPO preset={args.preset} on {jax.devices()[0].platform} "
          f"({cfg.num_envs} envs x {cfg.rollout_steps} steps/iter)")
    ppo_train(env_params, cfg, args.iterations, seed=args.seed,
              log_fn=log_fn, checkpoint_fn=checkpoint_fn)
    metrics_file.close()
    print(f"Training finished! Checkpoints in {run_dir}")
    return run_dir


if __name__ == "__main__":
    main()
