"""Named hyperparameter presets mirroring the reference training scripts.

- ``quick``: the reference's ``train_ppo.py:14-19`` / ``train_and_compare.py``
  set — train batch 4000, minibatch 256, 10 SGD epochs, lr 3e-4, γ 0.99.
- ``final``: the reference's ``train_final.py:11-17`` Tune run — batch 8000,
  minibatch 512, 15 epochs, lr 5e-4, γ 0.995 (24 parallel envs there; the
  env-batch axis replaces Ray workers here).
- ``tpu4096`` / ``tpu8192``: the BASELINE.json scale configs — thousands of
  vmapped envs on TPU; batch sizes scale with the env count so each update
  still sees ~2 episodes per env.
"""

from __future__ import annotations

from rl_scheduler_tpu.agent.dqn import DQNConfig
from rl_scheduler_tpu.agent.ppo import PPOTrainConfig

PPO_PRESETS: dict[str, PPOTrainConfig] = {
    # 40 envs x 100 steps = 4000 = reference train_batch_size (train_ppo.py)
    "quick": PPOTrainConfig(
        num_envs=40,
        rollout_steps=100,
        minibatch_size=256,
        num_epochs=10,
        lr=3e-4,
        gamma=0.99,
    ),
    # 80 envs x 100 steps = 8000 = reference train_batch_size (train_final.py)
    # eval every 5 iters for 20 episodes = reference train_final.py:19
    # (evaluation_interval=5, evaluation_duration=20)
    "final": PPOTrainConfig(
        num_envs=80,
        rollout_steps=100,
        minibatch_size=512,
        num_epochs=15,
        lr=5e-4,
        gamma=0.995,
        eval_every=5,
        eval_episodes=20,
    ),
    # BASELINE config 2: 64 vmapped envs on one TPU core
    "tpu64": PPOTrainConfig(
        num_envs=64,
        rollout_steps=100,
        minibatch_size=512,
        num_epochs=10,
        lr=3e-4,
        gamma=0.99,
    ),
    # BASELINE config 3: 4096 vmapped envs (pmap/shard_map data-parallel on
    # a v4-8). Large batch -> larger minibatch + fewer epochs + higher lr.
    # compute_dtype stays f32: measured on a v5e chip, bf16 torsos give no
    # speedup at these 256-wide shapes (the update is bound by full-batch
    # epoch compute, not MXU precision) — the knob exists for the wider
    # transformer/GNN policies. Re-confirmed round 4 under honest sync,
    # same-process interleaved: 36.8 (bf16) vs 38.1 (f32) ms/update at 6
    # epochs and dead-even at 1 epoch — within pool noise, so the
    # roofline's "halve activation bytes" hypothesis does not cash out
    # (the f32 optimizer/loss chain keeps the traffic).
    "tpu4096": PPOTrainConfig(
        num_envs=4096,
        rollout_steps=100,
        minibatch_size=32768,
        num_epochs=6,
        lr=1e-3,
        gamma=0.99,
    ),
    # BASELINE config 5 scale: 8192 envs.
    "tpu8192": PPOTrainConfig(
        num_envs=8192,
        rollout_steps=100,
        minibatch_size=65536,
        num_epochs=6,
        lr=1e-3,
        gamma=0.99,
    ),
    # The measured config-4 headline recipe (docs/status.md row 4:
    # 2.30M env-steps/s steady-state, convergence criterion reached in
    # ~35 s wall): tpu4096 scale, ONE SGD epoch (the update body is
    # bandwidth-bound, so epochs are nearly pure overhead — fewer epochs
    # cost iterations but win wall-clock), bf16 block compute. The CLI
    # implies --env cluster_set --fused-set for this preset
    # (PRESET_IMPLIES below), so `--preset set_fast` alone reproduces
    # the row.
    "set_fast": PPOTrainConfig(
        num_envs=4096,
        rollout_steps=100,
        minibatch_size=32768,
        num_epochs=1,
        lr=1e-3,
        gamma=0.99,
        compute_dtype="bfloat16",
    ),
    # The measured config-5 headline recipe (docs/status.md row 5:
    # 4.51M env-steps/s steady-state, convergence in ~34 s wall):
    # tpu8192 scale, one SGD epoch, Pallas kron GNN kernel (implied
    # --env cluster_graph --fused-gnn). compute_dtype stays the f32
    # default — faithful to the recorded headline command, and a round-4
    # same-process check measured bf16 dtype-neutral at this recipe
    # (~140 ms/update both ways: the 1-epoch update is rollout-bound,
    # and the kernel's matmuls are not the binding term).
    "gnn_fast": PPOTrainConfig(
        num_envs=8192,
        rollout_steps=100,
        minibatch_size=65536,
        num_epochs=1,
        lr=1e-3,
        gamma=0.99,
    ),
    # Fleet-scale cluster_set (round 5): N=64 nodes — the regime a
    # production cluster actually schedules over (VERDICT r4 item 1; the
    # extender protocol's node lists are this shape). Implies --env
    # cluster_set --num-nodes 64 (PRESET_IMPLIES); an explicit
    # --num-nodes overrides the 64. Policy: the flax set transformer in
    # bf16 — at N=64 the batch-minor fast path's advantage vanishes
    # (tiles fill; same-process A/B measured flax_bf16 417 vs
    # fused-matmul 420 ms/update, with the N=8-optimal chunk loop at
    # 709 ms), and the flax policy keeps multi-head and --sp ring
    # attention available. Env count drops 4096 -> 1024 because
    # per-sample compute grows ~10x with the node set (4096 envs
    # measured the same steps/s with 4x the memory). Measured
    # (docs/scaling.md): 245k env-steps/s steady-state, greedy eval
    # +17-26% over the best node baseline on converged seeds — a 9-seed
    # study measured ~44% of seeds failing the greedy eval while their
    # training reward looks healthy, so the preset implies the reseed
    # guard (catches both measured failure modes; docs/scaling.md §1b)
    # — serving p50 <1 ms at N=64.
    "set_fleet64": PPOTrainConfig(
        num_envs=1024,
        rollout_steps=100,
        minibatch_size=12800,
        num_epochs=1,
        lr=1e-3,
        gamma=0.99,
        compute_dtype="bfloat16",
        # The measured recipe INCLUDES the eval cadence the reseed
        # guard needs (docs/scaling.md §1b); the CLI implies
        # --reseed-on-stall 2 for runs long enough to use it.
        eval_every=8,
        eval_episodes=64,
    ),
    # N=256 fleet recipe: same shape as set_fleet64 with envs scaled
    # down another 4x (per-sample compute grows with N; the flax policy
    # WINS outright here — 299 vs 391 ms/update against fused-matmul,
    # same process). Measured: 85.7k env-steps/s steady-state, greedy
    # eval +25.8% over the best node baseline at 100 episodes
    # (docs/scaling.md).
    "set_fleet256": PPOTrainConfig(
        num_envs=256,
        rollout_steps=100,
        minibatch_size=3200,
        num_epochs=1,
        lr=1e-3,
        gamma=0.99,
        compute_dtype="bfloat16",
        eval_every=8,
        eval_episodes=64,
    ),
}

# CLI implications: these presets name a full measured recipe (env family
# + fast-path policy), not just hyperparameters. train_ppo fills the
# implied flags when the user leaves them unset and refuses contradictory
# combinations (e.g. `--preset set_fast --env cluster_graph`).
PRESET_IMPLIES: dict[str, dict] = {
    "set_fast": {"env": "cluster_set", "fused_set": True},
    "gnn_fast": {"env": "cluster_graph", "fused_gnn": True},
    # The fleet presets imply the bad-seed guard (the measured ~44%
    # per-seed greedy failure rate, docs/scaling.md §1b): the CLI fills
    # reseed_on_stall when the user left it unset AND the run is long
    # enough for the stall deadline to fire (auto-disabled with an info
    # line otherwise — smoke runs with --iterations 1 stay valid).
    # fused_set_block "tpu": the whole-network fused kernel
    # (ops/pallas_set_block.py) is auto-selected ON TPU at fleet N —
    # where the round-5 roofline rows put the ~65-op XLA body at
    # 8.9-12.4% of its HBM floor — and stays off elsewhere (off-chip the
    # kernel runs interpret mode: correct but slow; dense XLA is the
    # fallback). An explicit --fused-set-block/--fused-set/--flash-attn/
    # --sp or a non-fleet --num-nodes override disables the implication.
    "set_fleet64": {"env": "cluster_set", "num_nodes": 64,
                    "reseed_on_stall": 2, "fused_set_block": "tpu"},
    "set_fleet256": {"env": "cluster_set", "num_nodes": 256,
                     "reseed_on_stall": 2, "fused_set_block": "tpu"},
}

DQN_PRESETS: dict[str, DQNConfig] = {
    # BASELINE config 1: 2-layer MLP DQN, 1 env — small enough for CPU.
    "config1": DQNConfig(
        num_envs=1,
        collect_steps=4,
        buffer_size=20_000,
        batch_size=64,
        hidden=(64, 64),
    ),
    # Vectorized variant: the env axis widened to 256. Batch/buffer grow
    # with it but NOT proportionally: the replay ratio intentionally drops
    # (4096 samples per 1024 env-steps = 4, vs config1's 64/4 = 16) because
    # 256 decorrelated envs need less sample reuse per step of data.
    "vector256": DQNConfig(
        num_envs=256,
        collect_steps=4,
        buffer_size=262_144,
        batch_size=4096,
        learning_starts=8_192,
        epsilon_decay_steps=200_000,
        hidden=(64, 64),
    ),
}
