"""Train-vs-baseline comparison harness (reference ``train_and_compare.py``).

The reference trains PPO for 5 iterations, runs a round-robin baseline for 5
episodes, prints a side-by-side table, and saves a matplotlib reward plot
(``train_and_compare.py:43-90``). Same deliverables here, with the baselines
evaluated exactly (they are deterministic functions of the data table) and
the trained policy evaluated greedily over a vmapped episode batch.
"""

from __future__ import annotations

import argparse
import json
from pathlib import Path

import jax

from rl_scheduler_tpu.agent.evaluate import (
    BASELINE_POLICIES,
    baseline_episode_cost,
    evaluate,
    greedy_policy_fn,
)
from rl_scheduler_tpu.agent.ppo import ppo_train
from rl_scheduler_tpu.agent.presets import PPO_PRESETS
from rl_scheduler_tpu.config import EnvConfig
from rl_scheduler_tpu.env import core as env_core
from rl_scheduler_tpu.models import ActorCritic
from rl_scheduler_tpu.utils.fsio import atomic_write_json


def compare(
    env_config: EnvConfig | None = None,
    preset: str = "quick",
    iterations: int = 5,
    episodes: int = 100,
    seed: int = 0,
    log_fn=print,
):
    """Train PPO, evaluate against baselines; returns a results dict."""
    env_config = env_config or EnvConfig()
    env_params = env_core.make_params(env_config)
    cfg = PPO_PRESETS[preset]

    history: list[dict] = []

    def train_log(i, metrics):
        history.append(metrics)
        log_fn(
            f"Iteration {i + 1}/{iterations}: "
            f"reward_mean={metrics['episode_reward_mean']:.2f}"
        )

    runner, _ = ppo_train(env_params, cfg, iterations, seed=seed, log_fn=train_log)

    net = ActorCritic(num_actions=env_core.NUM_ACTIONS, hidden=cfg.hidden)
    ppo_report = evaluate(
        env_params, greedy_policy_fn(net, runner.params), episodes, seed
    )
    random_report = evaluate(
        env_params, BASELINE_POLICIES["random"], episodes, seed
    )

    results = {
        "ppo": {
            "episode_cost": ppo_report.avg_episode_cost,
            "episode_reward": ppo_report.avg_episode_reward,
            "choice_fractions": list(ppo_report.choice_fractions),
        },
        "cost_greedy": {"episode_cost": baseline_episode_cost(env_params, "greedy")},
        "round_robin": {"episode_cost": baseline_episode_cost(env_params, "round_robin")},
        "random": {"episode_cost": random_report.avg_episode_cost},
        "reward_curve": [m["episode_reward_mean"] for m in history],
    }
    return results, runner


def format_table(results: dict) -> str:
    rows = [
        ("PPO (trained, greedy)", results["ppo"]["episode_cost"]),
        ("Cost-greedy baseline", results["cost_greedy"]["episode_cost"]),
        ("Round-robin baseline", results["round_robin"]["episode_cost"]),
        ("Random baseline", results["random"]["episode_cost"]),
    ]
    best = min(cost for _, cost in rows)
    lines = [
        f"{'Policy':<24} {'Episode cost':>14} {'vs best':>10}",
        "-" * 50,
    ]
    for name, cost in rows:
        delta = (cost - best) / best * 100.0 if best else 0.0
        marker = "  <-- best" if cost == best else f"  +{delta:.1f}%"
        lines.append(f"{name:<24} {cost:>14.3f}{marker}")
    return "\n".join(lines)


def save_plot(results: dict, path: str | Path) -> bool:
    """Reward-curve plot (reference ``train_and_compare.py:82-90``); returns
    False when matplotlib is unavailable (headless-safe)."""
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        return False
    curve = results["reward_curve"]
    fig, ax = plt.subplots(figsize=(8, 5))
    ax.plot(range(1, len(curve) + 1), curve, marker="o", label="PPO reward mean")
    ax.set_xlabel("Training iteration")
    ax.set_ylabel("Episode reward mean")
    ax.set_title("PPO training vs baselines (multi-cloud scheduling)")
    ax.legend()
    ax.grid(alpha=0.3)
    fig.savefig(path, dpi=120, bbox_inches="tight")
    plt.close(fig)
    return True


def main(argv: list[str] | None = None) -> dict:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--preset", default="quick", choices=sorted(PPO_PRESETS))
    p.add_argument("--iterations", type=int, default=5)
    p.add_argument("--episodes", type=int, default=100)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--results-dir", default="results")
    p.add_argument("--legacy-reward-sign", action="store_true")
    args = p.parse_args(argv)

    print(f"Training PPO ({args.preset}, {args.iterations} iterations) on "
          f"{jax.devices()[0].platform}...")
    results, _ = compare(
        EnvConfig(legacy_reward_sign=args.legacy_reward_sign),
        args.preset, args.iterations, args.episodes, args.seed,
    )
    print()
    print(format_table(results))

    out = Path(args.results_dir)
    out.mkdir(parents=True, exist_ok=True)
    # Atomic: eval tooling tails comparison.json while a rerun overwrites.
    atomic_write_json(out / "comparison.json", results, indent=2)
    if save_plot(results, out / "reward_comparison.png"):
        print(f"\nPlot saved to {out}/reward_comparison.png")
    print(f"Results saved to {out}/comparison.json")
    return results


if __name__ == "__main__":
    main()
