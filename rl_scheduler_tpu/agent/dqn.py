"""DQN with an on-device replay buffer (BASELINE config 1).

The reference has no DQN — BASELINE.json's first config asks for a 2-layer
MLP DQN on the single-cluster env. Like the PPO trainer
(:mod:`rl_scheduler_tpu.agent.ppo`), the whole iteration is one XLA
program: ``collect_steps`` epsilon-greedy env steps write into a circular
device buffer, then one double-DQN learner step samples a minibatch,
applies Adam, and soft-syncs the target network. No host round-trips in
the hot loop; the buffer never leaves HBM.

Works on any :class:`~rl_scheduler_tpu.env.bundle.EnvBundle` (1 env on CPU
for config 1, or thousands vmapped on TPU).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

from rl_scheduler_tpu.env.bundle import EnvBundle
from rl_scheduler_tpu.models import QNetwork
from rl_scheduler_tpu.ops.losses import dqn_loss


@dataclasses.dataclass(frozen=True)
class DQNConfig:
    num_envs: int = 1
    collect_steps: int = 4        # env steps per learner step
    buffer_size: int = 20_000     # transitions (rounded up to num_envs multiple)
    batch_size: int = 64
    lr: float = 1e-3
    gamma: float = 0.99
    epsilon_start: float = 1.0
    epsilon_end: float = 0.05
    epsilon_decay_steps: int = 10_000   # env steps to anneal over
    learning_starts: int = 500          # min transitions before learning
    target_tau: float = 0.01            # soft target update rate
    double_dqn: bool = True
    hidden: tuple = (64, 64)
    # scan: sequential epsilon-greedy steps; open_loop: batch the whole
    # collect horizon for table-replay envs (one Q forward over all steps,
    # mirrors PPOTrainConfig.rollout_impl); auto picks open_loop when the
    # bundle exports a horizon.
    collect_impl: str = "auto"    # scan | open_loop | auto
    # In-training periodic greedy evaluation, mirroring
    # PPOTrainConfig.eval_every/eval_episodes (reference train_final.py:19).
    # 0 disables.
    eval_every: int = 0
    eval_episodes: int = 20


class ReplayBuffer(NamedTuple):
    """Circular transition store as preallocated device arrays."""

    obs: jnp.ndarray        # [cap, *obs_shape]
    action: jnp.ndarray     # [cap]
    reward: jnp.ndarray     # [cap]
    done: jnp.ndarray       # [cap]
    next_obs: jnp.ndarray   # [cap, *obs_shape]
    pos: jnp.ndarray        # scalar int32: next write index
    size: jnp.ndarray       # scalar int32: valid entries

    @property
    def capacity(self) -> int:
        return self.obs.shape[0]


def buffer_init(capacity: int, obs_shape: tuple) -> ReplayBuffer:
    return ReplayBuffer(
        obs=jnp.zeros((capacity, *obs_shape), jnp.float32),
        action=jnp.zeros((capacity,), jnp.int32),
        reward=jnp.zeros((capacity,), jnp.float32),
        done=jnp.zeros((capacity,), jnp.float32),
        next_obs=jnp.zeros((capacity, *obs_shape), jnp.float32),
        pos=jnp.zeros((), jnp.int32),
        size=jnp.zeros((), jnp.int32),
    )


def buffer_add(buf: ReplayBuffer, batch: dict) -> ReplayBuffer:
    """Write ``n`` transitions at the circular write head.

    ``n`` (the env batch) is static, so the scatter indices are a cheap
    ``pos + iota mod cap`` — one fused scatter per field, no host sync.

    A batch larger than the whole buffer (possible via the open-loop
    collect: ``collect_steps * num_envs`` arrives as ONE add) keeps only
    its newest ``capacity`` rows — the older ones would be immediately
    overwritten under circular semantics anyway, and letting them through
    would make the modular scatter indices collide with undefined winners.
    """
    n = batch["action"].shape[0]
    cap = buf.capacity
    # graftlint: disable=GL003 -- cap is buf.capacity == buf.obs.shape[0], a static Python int; this branch is shape-driven and resolves identically at every trace
    if n > cap:
        batch = {k: v[n - cap:] for k, v in batch.items()}
        # The head still advances by the FULL n (as if each row had been
        # written in turn), matching what n sequential adds would leave.
        pos_after = (buf.pos + n) % cap
        idx = (pos_after - cap + jnp.arange(cap, dtype=jnp.int32)) % cap
        return ReplayBuffer(
            obs=buf.obs.at[idx].set(batch["obs"]),
            action=buf.action.at[idx].set(batch["action"]),
            reward=buf.reward.at[idx].set(batch["reward"]),
            done=buf.done.at[idx].set(batch["done"]),
            next_obs=buf.next_obs.at[idx].set(batch["next_obs"]),
            pos=pos_after,
            size=jnp.asarray(cap, buf.size.dtype),
        )
    idx = (buf.pos + jnp.arange(n, dtype=jnp.int32)) % cap
    return ReplayBuffer(
        obs=buf.obs.at[idx].set(batch["obs"]),
        action=buf.action.at[idx].set(batch["action"]),
        reward=buf.reward.at[idx].set(batch["reward"]),
        done=buf.done.at[idx].set(batch["done"]),
        next_obs=buf.next_obs.at[idx].set(batch["next_obs"]),
        pos=(buf.pos + n) % cap,
        size=jnp.minimum(buf.size + n, cap),
    )


def buffer_sample(buf: ReplayBuffer, key: jnp.ndarray, batch_size: int) -> dict:
    idx = jax.random.randint(key, (batch_size,), 0, jnp.maximum(buf.size, 1))
    return {
        "obs": buf.obs[idx],
        "action": buf.action[idx],
        "reward": buf.reward[idx],
        "done": buf.done[idx],
        "next_obs": buf.next_obs[idx],
    }


class DQNRunnerState(NamedTuple):
    params: Any
    target_params: Any
    opt_state: Any
    buffer: ReplayBuffer
    env_state: Any
    obs: jnp.ndarray
    key: jnp.ndarray
    env_steps: jnp.ndarray      # scalar int32: total env steps taken
    ep_return: jnp.ndarray      # [N] running episode return
    last_episode_return: jnp.ndarray  # scalar f32: mean of recently finished eps


def epsilon_by_step(cfg: DQNConfig, env_steps: jnp.ndarray) -> jnp.ndarray:
    frac = jnp.clip(env_steps.astype(jnp.float32) / cfg.epsilon_decay_steps, 0.0, 1.0)
    return cfg.epsilon_start + frac * (cfg.epsilon_end - cfg.epsilon_start)


def make_dqn(
    bundle: EnvBundle, cfg: DQNConfig, net: Any | None = None,
    scope: Any | None = None
) -> tuple[Callable, Callable, Any]:
    """Build ``(init_fn, update_fn, net)``; both are pure and jit-safe.

    ``scope``: a graftscope MetricsSpec (``utils/metrics.dqn_scope_spec``).
    When set, the update returns device-resident stats/histograms over the
    replay batch (reward/td/q streams, grad norm, replayed-action counts)
    under the ``"graftscope"`` metrics key — no host syncs; the loop
    flushes one summary per window. During buffer warm-up the skipped
    learner observes grad_norm 0 (visible underflow-bucket spike, by
    design). ``None`` leaves the update byte-identical."""
    if scope is not None:
        from rl_scheduler_tpu.utils.metrics import validate_spec

        # Build-time guard (same contract as make_ppo_bundle): unknown
        # stream names fail here with the available set spelled out.
        validate_spec(
            scope,
            values=("reward", "td_abs", "q_mean", "grad_norm", "action"),
            context="make_dqn(scope=...)")
    net = net or QNetwork(num_actions=bundle.num_actions, hidden=cfg.hidden)
    tx = optax.adam(cfg.lr)

    def init_fn(key: jnp.ndarray) -> DQNRunnerState:
        pkey, ekey, rkey = jax.random.split(key, 3)
        dummy = jnp.zeros((1, *bundle.obs_shape), jnp.float32)
        params = net.init(pkey, dummy)
        env_state, obs = bundle.reset_batch(ekey, cfg.num_envs)
        return DQNRunnerState(
            params=params,
            target_params=params,
            opt_state=tx.init(params),
            buffer=buffer_init(
                -(-cfg.buffer_size // cfg.num_envs) * cfg.num_envs, bundle.obs_shape
            ),
            env_state=env_state,
            obs=obs,
            key=rkey,
            env_steps=jnp.zeros((), jnp.int32),
            ep_return=jnp.zeros(cfg.num_envs, jnp.float32),
            last_episode_return=jnp.zeros(()),
        )

    def collect(runner: DQNRunnerState):
        """Scan ``collect_steps`` epsilon-greedy steps into the buffer."""
        eps = epsilon_by_step(cfg, runner.env_steps)

        def env_step(carry, _):
            buf, env_state, obs, key, ep_ret, ep_stat = carry
            key, akey, ekey = jax.random.split(key, 3)
            q = net.apply(runner.params, obs)
            greedy = jnp.argmax(q, axis=-1).astype(jnp.int32)
            random_a = jax.random.randint(
                akey, (cfg.num_envs,), 0, bundle.num_actions, jnp.int32
            )
            explore = jax.random.uniform(ekey, (cfg.num_envs,)) < eps
            action = jnp.where(explore, random_a, greedy)
            env_state, ts = bundle.step_batch(env_state, action)
            buf = buffer_add(
                buf,
                {
                    "obs": obs,
                    "action": action,
                    "reward": ts.reward,
                    "done": ts.done.astype(jnp.float32),
                    "next_obs": ts.obs,
                },
            )
            done_f = ts.done.astype(jnp.float32)
            new_ep = ep_ret + ts.reward
            finished = jnp.sum(done_f)
            ep_stat = jnp.where(
                finished > 0, jnp.sum(new_ep * done_f) / jnp.maximum(finished, 1.0), ep_stat
            )
            ep_ret = new_ep * (1.0 - done_f)
            return (buf, env_state, ts.obs, key, ep_ret, ep_stat), None

        carry = (
            runner.buffer,
            runner.env_state,
            runner.obs,
            runner.key,
            runner.ep_return,
            runner.last_episode_return,
        )
        carry, _ = jax.lax.scan(env_step, carry, None, length=cfg.collect_steps)
        return carry, eps

    def collect_open_loop(runner: DQNRunnerState):
        """Whole-horizon epsilon-greedy collection without a scan.

        Same contract as :func:`collect` (the Q-network is frozen at
        ``runner.params`` across the horizon there too, so batching all
        ``collect_steps`` observations into ONE forward is exact, not an
        approximation); only the RNG stream differs.
        """
        s = cfg.collect_steps
        eps = epsilon_by_step(cfg, runner.env_steps)
        key, hkey, akey, ekey = jax.random.split(runner.key, 4)
        obs_all, aux, env_state = bundle.horizon_fn(
            runner.env_state, runner.obs, hkey, s
        )
        n = obs_all.shape[1]
        q = net.apply(runner.params, obs_all[:s].reshape(s * n, *bundle.obs_shape))
        greedy = jnp.argmax(q.reshape(s, n, -1), axis=-1).astype(jnp.int32)
        random_a = jax.random.randint(akey, (s, n), 0, bundle.num_actions, jnp.int32)
        explore = jax.random.uniform(ekey, (s, n)) < eps
        action = jnp.where(explore, random_a, greedy)
        reward = bundle.horizon_reward_fn(aux, action)
        done = aux["dones"]
        flat = lambda x: x.reshape(s * n, *x.shape[2:])
        buf = buffer_add(
            runner.buffer,
            {
                "obs": flat(obs_all[:s]),
                "action": flat(action),
                "reward": flat(reward),
                "done": flat(done),
                "next_obs": flat(obs_all[1:]),
            },
        )

        def book(carry, xs):
            ep_ret, ep_stat = carry
            r, d = xs
            new_ret = ep_ret + r
            finished = jnp.sum(d)
            ep_stat = jnp.where(
                finished > 0,
                jnp.sum(new_ret * d) / jnp.maximum(finished, 1.0),
                ep_stat,
            )
            return (new_ret * (1.0 - d), ep_stat), None

        (ep_ret, ep_stat), _ = jax.lax.scan(
            book, (runner.ep_return, runner.last_episode_return), (reward, done)
        )
        return (buf, env_state, obs_all[s], key, ep_ret, ep_stat), eps

    has_horizon = (
        bundle.horizon_fn is not None and bundle.horizon_reward_fn is not None
    )
    if cfg.collect_impl == "open_loop" and not has_horizon:
        raise ValueError(
            f"collect_impl='open_loop' needs an env with a horizon_fn; "
            f"bundle {bundle.name!r} has none (use 'scan' or 'auto')"
        )
    if cfg.collect_impl not in ("scan", "open_loop", "auto"):
        raise ValueError(
            f"unknown collect_impl {cfg.collect_impl!r}; choose scan|open_loop|auto"
        )
    use_open_loop = cfg.collect_impl == "open_loop" or (
        cfg.collect_impl == "auto" and has_horizon
    )
    collect_fn = collect_open_loop if use_open_loop else collect

    def learner_step(params, target_params, opt_state, batch):
        def loss_fn(p):
            q = net.apply(p, batch["obs"])
            target_q_next = net.apply(target_params, batch["next_obs"])
            # Vanilla DQN == double-DQN with the target net selecting actions.
            online_q_next = (
                net.apply(p, batch["next_obs"]) if cfg.double_dqn else target_q_next
            )
            loss, aux = dqn_loss(
                q, target_q_next, online_q_next,
                batch["action"], batch["reward"], batch["done"], cfg.gamma,
            )
            return loss, {"loss": loss, **aux}

        (_, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        if scope is not None:
            metrics["grad_norm"] = optax.global_norm(grads)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        target_params = optax.incremental_update(params, target_params, cfg.target_tau)
        return params, target_params, opt_state, metrics

    def update_fn(runner: DQNRunnerState):
        """One iteration: collect transitions, then learn (once warm)."""
        with jax.named_scope("collect"):
            (buf, env_state, obs, key, ep_ret, ep_stat), eps = collect_fn(runner)
        key, skey = jax.random.split(key)
        batch = buffer_sample(buf, skey, cfg.batch_size)

        def do_learn(_):
            return learner_step(runner.params, runner.target_params, runner.opt_state, batch)

        def skip(_):
            zero = {
                "loss": jnp.zeros(()),
                "q_mean": jnp.zeros(()),
                "td_abs_mean": jnp.zeros(()),
            }
            if scope is not None:
                zero["grad_norm"] = jnp.zeros(())
            return runner.params, runner.target_params, runner.opt_state, zero

        with jax.named_scope("learn"):
            params, target_params, opt_state, metrics = jax.lax.cond(
                buf.size >= cfg.learning_starts, do_learn, skip, None
            )
        scope_state = None
        if scope is not None:
            from rl_scheduler_tpu.utils.metrics import scope_observe

            with jax.named_scope("scope_metrics"):
                scope_state = scope_observe(
                    scope,
                    values={
                        "reward": batch["reward"],
                        "td_abs": metrics["td_abs_mean"],
                        "q_mean": metrics["q_mean"],
                        "grad_norm": metrics["grad_norm"],
                        "action": batch["action"],
                    },
                )
        new_runner = DQNRunnerState(
            params=params,
            target_params=target_params,
            opt_state=opt_state,
            buffer=buf,
            env_state=env_state,
            obs=obs,
            key=key,
            env_steps=runner.env_steps + cfg.collect_steps * cfg.num_envs,
            ep_return=ep_ret,
            last_episode_return=ep_stat,
        )
        metrics = {
            **metrics,
            "epsilon": eps,
            "buffer_size": buf.size,
            "episode_reward_mean": ep_stat,
        }
        if scope_state is not None:
            metrics["graftscope"] = scope_state
        return new_runner, metrics

    return init_fn, update_fn, net


def dqn_train(
    bundle: EnvBundle,
    cfg: DQNConfig,
    num_iterations: int,
    seed: int = 0,
    log_fn: Callable[[int, dict], None] | None = None,
    checkpoint_fn: Callable[[int, DQNRunnerState], None] | None = None,
    sync_every: int = 1,
    eval_log_fn: Callable[[int, dict], None] | None = None,
    debug_checks: bool = False,
    updates_per_dispatch: int = 1,
    scope: Any | None = None,
    observer: Any | None = None,
    restore: tuple[dict, int] | None = None,
    preemption: Any | None = None,
    on_preempt: Callable[[int, DQNRunnerState], None] | None = None,
    on_eval: Callable[[int, DQNRunnerState, dict], None] | None = None,
):
    """Host-side training loop mirroring :func:`rl_scheduler_tpu.agent.ppo.ppo_train`.

    ``restore=(tree, completed_iterations)`` resumes a checkpointed run.
    A tree with a ``"loop"`` key (graftguard full-state checkpoints:
    buffer/env_state/obs/key/env_steps/ep_return/last_episode_return) is
    a DETERMINISTIC resume — the whole runner, replay buffer included,
    comes from the checkpoint and the RNG is not re-seeded, so
    interrupt-and-resume is bitwise-identical to an uninterrupted run.
    A params/target_params/opt_state-only tree resumes learning state
    with a fresh collection stream (key folded with the resume point).

    ``preemption``/``on_preempt``: see ``run_train_loop`` — polled at
    dispatch boundaries; a stop flushes, force-checkpoints, fires
    ``on_preempt``, and returns cleanly.

    ``scope``/``observer``: graftscope instrumentation, exactly as in
    ``ppo_train`` (see :func:`make_dqn` for the DQN watch set).

    ``sync_every`` batches device->host metric fetches exactly as in
    ``ppo_train``; ``updates_per_dispatch=k`` goes further and fuses ``k``
    whole iterations into ONE dispatched program (``lax.scan`` over the
    update), amortizing Python/dispatch overhead — the lever for config 1,
    whose per-iteration compute is microseconds. Metrics for every fused
    iteration are still logged individually (stacked in-program, unstacked
    by the loop).

    ``debug_checks=True`` checkifies the update (``utils/debug.py``): the
    first NaN/zero-division/out-of-bounds index raises with the failing op
    named instead of silently corrupting training. Slower; for debugging.
    Incompatible with ``updates_per_dispatch > 1`` (checkify must observe
    each iteration's error state before the next dispatches).

    With ``cfg.eval_every > 0``, a greedy (epsilon=0) evaluation of
    ``cfg.eval_episodes`` episodes runs every ``cfg.eval_every`` iterations
    and reports through ``eval_log_fn`` (see ``ppo_train``).
    """
    from rl_scheduler_tpu.agent.loop import make_update, run_train_loop
    from rl_scheduler_tpu.agent.ppo import make_greedy_eval_hook

    init_fn, update_fn, net = make_dqn(bundle, cfg, scope=scope)
    start_iteration = 0
    full_state = restore is not None and "loop" in restore[0]
    key = jax.random.PRNGKey(seed)
    if restore is not None and not full_state:
        key = jax.random.fold_in(key, restore[1])
    runner = jax.jit(init_fn)(key)
    if restore is not None:
        tree, start_iteration = restore
        # Copy: the jitted update donates the runner's buffers (ppo_train
        # has the same guard) — without it the caller's checkpoint tree
        # would be deleted out from under it on accelerator backends.
        tree = jax.tree.map(lambda x: jnp.array(x, copy=True), tree)
        if full_state:
            loop_state = tree["loop"]
            runner = runner._replace(
                params=tree["params"],
                target_params=tree["target_params"],
                opt_state=tree["opt_state"],
                buffer=ReplayBuffer(**loop_state["buffer"]),
                env_state=loop_state["env_state"],
                obs=loop_state["obs"],
                key=loop_state["key"],
                env_steps=loop_state["env_steps"],
                ep_return=loop_state["ep_return"],
                last_episode_return=loop_state["last_episode_return"],
            )
        else:
            runner = runner._replace(
                params=tree["params"],
                target_params=tree["target_params"],
                opt_state=tree["opt_state"],
            )
    update = make_update(update_fn, debug_checks, updates_per_dispatch)
    eval_hook = make_greedy_eval_hook(
        bundle, net, cfg.eval_every, cfg.eval_episodes, seed, eval_log_fn,
        on_eval=on_eval,
    )
    return run_train_loop(
        update, runner, start_iteration, num_iterations,
        sync_every=sync_every, log_fn=log_fn, checkpoint_fn=checkpoint_fn,
        eval_every=cfg.eval_every, eval_hook=eval_hook,
        updates_per_dispatch=updates_per_dispatch, observer=observer,
        preemption=preemption, on_preempt=on_preempt,
    )
