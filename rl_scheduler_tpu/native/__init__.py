"""Native (C++) runtime components.

The reference is pure Python (SURVEY.md §2: no native code anywhere), so
nothing here is a parity port — these are the TPU-framework runtime pieces
where C++ genuinely beats Python: the scheduler extender's per-request
inference hot paths (``mlp_infer.cpp`` for the flat MLP/DQN family,
``set_infer.cpp`` for the set-transformer pointer family, both via
:mod:`~rl_scheduler_tpu.native.build`). The JAX/XLA/Pallas side stays the
compute path for training.
"""

from rl_scheduler_tpu.native.build import (
    NativeMLP,
    NativeSetTransformer,
    NativeSetTransformerInt8,
    ensure_built,
    ensure_built_set,
    pack_mlp,
    pack_set,
)

__all__ = ["NativeMLP", "NativeSetTransformer", "NativeSetTransformerInt8",
           "ensure_built", "ensure_built_set", "pack_mlp", "pack_set"]
