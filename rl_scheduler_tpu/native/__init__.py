"""Native (C++) runtime components.

The reference is pure Python (SURVEY.md §2: no native code anywhere), so
nothing here is a parity port — these are the TPU-framework runtime pieces
where C++ genuinely beats Python: the scheduler extender's per-request
inference hot path (``mlp_infer.cpp`` via :mod:`~rl_scheduler_tpu.native.build`).
The JAX/XLA/Pallas side stays the compute path for training.
"""

from rl_scheduler_tpu.native.build import NativeMLP, ensure_built, pack_mlp

__all__ = ["NativeMLP", "ensure_built", "pack_mlp"]
