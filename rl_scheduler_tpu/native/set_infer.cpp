// Native set-transformer inference core for the extender's set family.
//
// Serves cluster_set pointer checkpoints (SetTransformerPolicy,
// models/transformer.py) from C++: one ctypes hop per decision, node
// count N variable at call time, no per-shape compilation. Two reasons
// this exists beyond the numpy forward (scheduler/set_backend.py):
// ctypes calls release the GIL, so under concurrent serving load N
// threads genuinely run in parallel (the numpy forward serializes on the
// GIL at sustained saturation — measured ~3.3 ms p50 in the round-4
// soak), and the single-stream small-N path skips every numpy dispatch.
//
// Math contract (must match the flax module and the numpy forward, which
// are tolerance-tested against each other):
//   - pre-LN transformer block: LN -> MHA -> residual, LN -> MLP(gelu,
//     2x width) -> residual; final LN; per-node scalar score head.
//   - LayerNorm: mean/variance over the feature axis, eps 1e-6.
//   - gelu: tanh approximation (flax default).
//   - attention: per-head softmax(q k^T / sqrt(head_dim)) v.
//
// Layout contract (must match rl_scheduler_tpu/native/build.py pack_set):
//   dims = [feat, dim, depth, num_heads]
//   weights = embed kernel [feat*dim] + bias [dim], then per block:
//     ln0 scale+bias [dim each], q/k/v/out kernels [dim*dim] each with
//     bias [dim] (head axis folded, numpy [in, out] row-major), ln1
//     scale+bias, mlp w1 [dim*2dim]+b1 [2dim], w2 [2dim*dim]+b2 [dim];
//   then final_norm scale+bias [dim], score kernel [dim] + bias [1].

#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct Dense {
  std::vector<float> kernel;  // [in * out], row-major [in][out]
  std::vector<float> bias;    // [out]
  int in = 0;
  int out = 0;
};

struct Norm {
  std::vector<float> scale;
  std::vector<float> bias;
};

struct Block {
  Norm ln0, ln1;
  Dense q, k, v, out, w1, w2;
};

struct SetNet {
  Dense embed;
  std::vector<Block> blocks;
  Norm final_norm;
  std::vector<float> score_kernel;  // [dim]
  float score_bias = 0.0f;
  int feat = 0;
  int dim = 0;
  int heads = 1;
};

constexpr float kLnEps = 1e-6f;

const float* take(const float*& w, std::vector<float>& dst, size_t n) {
  dst.assign(w, w + n);
  w += n;
  return w;
}

void take_dense(const float*& w, Dense& d, int in, int out) {
  d.in = in;
  d.out = out;
  take(w, d.kernel, static_cast<size_t>(in) * out);
  take(w, d.bias, out);
}

void take_norm(const float*& w, Norm& nrm, int dim) {
  take(w, nrm.scale, dim);
  take(w, nrm.bias, dim);
}

// y[n] = x[n] @ kernel + bias for row n of an [N, in] matrix.
void dense_row(const Dense& d, const float* x, float* y) {
  for (int j = 0; j < d.out; ++j) y[j] = d.bias[j];
  for (int i = 0; i < d.in; ++i) {
    const float xi = x[i];
    const float* row = d.kernel.data() + static_cast<size_t>(i) * d.out;
    for (int j = 0; j < d.out; ++j) y[j] += xi * row[j];
  }
}

void layer_norm_row(const Norm& nrm, const float* x, float* y, int dim) {
  float mean = 0.0f;
  for (int i = 0; i < dim; ++i) mean += x[i];
  mean /= dim;
  float var = 0.0f;
  for (int i = 0; i < dim; ++i) {
    const float c = x[i] - mean;
    var += c * c;
  }
  var /= dim;
  const float inv = 1.0f / std::sqrt(var + kLnEps);
  for (int i = 0; i < dim; ++i)
    y[i] = (x[i] - mean) * inv * nrm.scale[i] + nrm.bias[i];
}

inline float gelu(float x) {
  constexpr float kSqrt2OverPi = 0.7978845608028654f;
  return 0.5f * x * (1.0f + std::tanh(kSqrt2OverPi * (x + 0.044715f * x * x * x)));
}

}  // namespace

extern "C" {

void* set_create(const float* weights, const int32_t* dims, int32_t n_dims) {
  if (weights == nullptr || dims == nullptr || n_dims != 4) return nullptr;
  const int feat = dims[0], dim = dims[1], depth = dims[2], heads = dims[3];
  if (feat <= 0 || dim <= 0 || depth <= 0 || heads <= 0 || dim % heads)
    return nullptr;
  auto* net = new SetNet();
  net->feat = feat;
  net->dim = dim;
  net->heads = heads;
  const float* w = weights;
  take_dense(w, net->embed, feat, dim);
  net->blocks.resize(depth);
  for (auto& blk : net->blocks) {
    take_norm(w, blk.ln0, dim);
    take_dense(w, blk.q, dim, dim);
    take_dense(w, blk.k, dim, dim);
    take_dense(w, blk.v, dim, dim);
    take_dense(w, blk.out, dim, dim);
    take_norm(w, blk.ln1, dim);
    take_dense(w, blk.w1, dim, 2 * dim);
    take_dense(w, blk.w2, 2 * dim, dim);
  }
  take_norm(w, net->final_norm, dim);
  std::vector<float> score;
  take(w, score, dim);
  net->score_kernel = std::move(score);
  net->score_bias = *w;
  return net;
}

// Full forward over obs [n * feat]; writes per-node logits [n]. Returns
// the argmax node index, or -1 on bad input. Thread-safe (per-call
// scratch only) and GIL-free via ctypes.
int32_t set_decide(const void* handle, const float* obs, int32_t n,
                   float* logits_out) {
  const auto* net = static_cast<const SetNet*>(handle);
  if (net == nullptr || obs == nullptr || n <= 0) return -1;
  const int dim = net->dim;
  const int heads = net->heads;
  const int hd = dim / heads;
  const float scale = 1.0f / std::sqrt(static_cast<float>(hd));
  const size_t nd = static_cast<size_t>(n) * dim;

  std::vector<float> h(nd), hn(nd), q(nd), k(nd), v(nd), ctx(nd);
  std::vector<float> scores(n), mlp_mid(2 * dim), tmp(dim);

  for (int i = 0; i < n; ++i)
    dense_row(net->embed, obs + static_cast<size_t>(i) * net->feat,
              h.data() + static_cast<size_t>(i) * dim);

  for (const auto& blk : net->blocks) {
    for (int i = 0; i < n; ++i)
      layer_norm_row(blk.ln0, h.data() + static_cast<size_t>(i) * dim,
                     hn.data() + static_cast<size_t>(i) * dim, dim);
    for (int i = 0; i < n; ++i) {
      const float* row = hn.data() + static_cast<size_t>(i) * dim;
      dense_row(blk.q, row, q.data() + static_cast<size_t>(i) * dim);
      dense_row(blk.k, row, k.data() + static_cast<size_t>(i) * dim);
      dense_row(blk.v, row, v.data() + static_cast<size_t>(i) * dim);
    }
    for (int head = 0; head < heads; ++head) {
      const int off = head * hd;
      for (int i = 0; i < n; ++i) {
        const float* qi = q.data() + static_cast<size_t>(i) * dim + off;
        float mx = -1e30f;
        for (int j = 0; j < n; ++j) {
          const float* kj = k.data() + static_cast<size_t>(j) * dim + off;
          float s = 0.0f;
          for (int c = 0; c < hd; ++c) s += qi[c] * kj[c];
          scores[j] = s * scale;
          if (scores[j] > mx) mx = scores[j];
        }
        float denom = 0.0f;
        for (int j = 0; j < n; ++j) {
          scores[j] = std::exp(scores[j] - mx);
          denom += scores[j];
        }
        float* ci = ctx.data() + static_cast<size_t>(i) * dim + off;
        for (int c = 0; c < hd; ++c) ci[c] = 0.0f;
        for (int j = 0; j < n; ++j) {
          const float wj = scores[j] / denom;
          const float* vj = v.data() + static_cast<size_t>(j) * dim + off;
          for (int c = 0; c < hd; ++c) ci[c] += wj * vj[c];
        }
      }
    }
    for (int i = 0; i < n; ++i) {
      dense_row(blk.out, ctx.data() + static_cast<size_t>(i) * dim, tmp.data());
      float* hi = h.data() + static_cast<size_t>(i) * dim;
      for (int c = 0; c < dim; ++c) hi[c] += tmp[c];
    }
    for (int i = 0; i < n; ++i) {
      float* hi = h.data() + static_cast<size_t>(i) * dim;
      layer_norm_row(blk.ln1, hi, hn.data(), dim);
      dense_row(blk.w1, hn.data(), mlp_mid.data());
      for (int c = 0; c < 2 * dim; ++c) mlp_mid[c] = gelu(mlp_mid[c]);
      dense_row(blk.w2, mlp_mid.data(), tmp.data());
      for (int c = 0; c < dim; ++c) hi[c] += tmp[c];
    }
  }

  int best = 0;
  for (int i = 0; i < n; ++i) {
    layer_norm_row(net->final_norm, h.data() + static_cast<size_t>(i) * dim,
                   tmp.data(), dim);
    float s = net->score_bias;
    for (int c = 0; c < dim; ++c) s += tmp[c] * net->score_kernel[c];
    logits_out[i] = s;
    if (s > logits_out[best]) best = i;
  }
  return best;
}

void set_destroy(void* handle) { delete static_cast<SetNet*>(handle); }

int32_t set_abi_version() { return 1; }

}  // extern "C"
