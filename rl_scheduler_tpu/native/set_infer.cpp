// Native set-transformer inference core for the extender's set family.
//
// Serves cluster_set pointer checkpoints (SetTransformerPolicy,
// models/transformer.py) from C++: one ctypes hop per decision, node
// count N variable at call time, no per-shape compilation. Two reasons
// this exists beyond the numpy forward (scheduler/set_backend.py):
// ctypes calls release the GIL, so under concurrent serving load N
// threads genuinely run in parallel (the numpy forward serializes on the
// GIL at sustained saturation — measured ~3.3 ms p50 in the round-4
// soak), and the single-stream small-N path skips every numpy dispatch.
//
// Math contract (must match the flax module and the numpy forward, which
// are tolerance-tested against each other):
//   - pre-LN transformer block: LN -> MHA -> residual, LN -> MLP(gelu,
//     2x width) -> residual; final LN; per-node scalar score head.
//   - LayerNorm: mean/variance over the feature axis, eps 1e-6.
//   - gelu: tanh approximation (flax default).
//   - attention: per-head softmax(q k^T / sqrt(head_dim)) v.
//
// Layout contract (must match rl_scheduler_tpu/native/build.py pack_set):
//   dims = [feat, dim, depth, num_heads]
//   weights = embed kernel [feat*dim] + bias [dim], then per block:
//     ln0 scale+bias [dim each], q/k/v/out kernels [dim*dim] each with
//     bias [dim] (head axis folded, numpy [in, out] row-major), ln1
//     scale+bias, mlp w1 [dim*2dim]+b1 [2dim], w2 [2dim*dim]+b2 [dim];
//   then final_norm scale+bias [dim], score kernel [dim] + bias [1].
//
// graftfwd (int8 fleet forward): set_create_int8 takes the SAME packed
// fp32 buffer and quantizes every dense kernel to int8 at create time —
// symmetric per-tensor scale (max|w| / 127), recorded in creation order
// (embed, then q/k/v/out/w1/w2 per block) and readable via
// set_int8_scales. The int8 decide quantizes activations per row
// (dynamic symmetric), runs every dense as an int8 dot / int32
// accumulate over kernels stored TRANSPOSED [out][in] (contiguous dots
// autovectorize to pmaddwd/vpdpbusd-class code), computes attention
// scores as int8 q·k dots per head, and accumulates the softmax-
// weighted v in fp32 over fixed j-blocks — the fleet-N crossover table
// says this path is bandwidth/layout-bound, which is exactly what the
// narrower weights and the blocked j-walk attack. LayerNorm, softmax,
// gelu, residuals and the score head stay fp32: the accuracy-critical
// nonlinearities cost O(n*dim), not O(n^2*dim). Serving activation is
// gated on measured top-1 agreement vs fp32 (scheduler/fastpath.py).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <initializer_list>
#include <vector>

namespace {

struct Dense {
  std::vector<float> kernel;  // [in * out], row-major [in][out]
  std::vector<float> bias;    // [out]
  int in = 0;
  int out = 0;
};

struct Norm {
  std::vector<float> scale;
  std::vector<float> bias;
};

struct Block {
  Norm ln0, ln1;
  Dense q, k, v, out, w1, w2;
};

struct SetNet {
  Dense embed;
  std::vector<Block> blocks;
  Norm final_norm;
  std::vector<float> score_kernel;  // [dim]
  float score_bias = 0.0f;
  int feat = 0;
  int dim = 0;
  int heads = 1;
};

constexpr float kLnEps = 1e-6f;

const float* take(const float*& w, std::vector<float>& dst, size_t n) {
  dst.assign(w, w + n);
  w += n;
  return w;
}

void take_dense(const float*& w, Dense& d, int in, int out) {
  d.in = in;
  d.out = out;
  take(w, d.kernel, static_cast<size_t>(in) * out);
  take(w, d.bias, out);
}

void take_norm(const float*& w, Norm& nrm, int dim) {
  take(w, nrm.scale, dim);
  take(w, nrm.bias, dim);
}

// y[n] = x[n] @ kernel + bias for row n of an [N, in] matrix.
void dense_row(const Dense& d, const float* x, float* y) {
  for (int j = 0; j < d.out; ++j) y[j] = d.bias[j];
  for (int i = 0; i < d.in; ++i) {
    const float xi = x[i];
    const float* row = d.kernel.data() + static_cast<size_t>(i) * d.out;
    for (int j = 0; j < d.out; ++j) y[j] += xi * row[j];
  }
}

void layer_norm_row(const Norm& nrm, const float* x, float* y, int dim) {
  float mean = 0.0f;
  for (int i = 0; i < dim; ++i) mean += x[i];
  mean /= dim;
  float var = 0.0f;
  for (int i = 0; i < dim; ++i) {
    const float c = x[i] - mean;
    var += c * c;
  }
  var /= dim;
  const float inv = 1.0f / std::sqrt(var + kLnEps);
  for (int i = 0; i < dim; ++i)
    y[i] = (x[i] - mean) * inv * nrm.scale[i] + nrm.bias[i];
}

inline float gelu(float x) {
  constexpr float kSqrt2OverPi = 0.7978845608028654f;
  return 0.5f * x * (1.0f + std::tanh(kSqrt2OverPi * (x + 0.044715f * x * x * x)));
}

}  // namespace

extern "C" {

void* set_create(const float* weights, const int32_t* dims, int32_t n_dims) {
  if (weights == nullptr || dims == nullptr || n_dims != 4) return nullptr;
  const int feat = dims[0], dim = dims[1], depth = dims[2], heads = dims[3];
  if (feat <= 0 || dim <= 0 || depth <= 0 || heads <= 0 || dim % heads)
    return nullptr;
  auto* net = new SetNet();
  net->feat = feat;
  net->dim = dim;
  net->heads = heads;
  const float* w = weights;
  take_dense(w, net->embed, feat, dim);
  net->blocks.resize(depth);
  for (auto& blk : net->blocks) {
    take_norm(w, blk.ln0, dim);
    take_dense(w, blk.q, dim, dim);
    take_dense(w, blk.k, dim, dim);
    take_dense(w, blk.v, dim, dim);
    take_dense(w, blk.out, dim, dim);
    take_norm(w, blk.ln1, dim);
    take_dense(w, blk.w1, dim, 2 * dim);
    take_dense(w, blk.w2, 2 * dim, dim);
  }
  take_norm(w, net->final_norm, dim);
  std::vector<float> score;
  take(w, score, dim);
  net->score_kernel = std::move(score);
  net->score_bias = *w;
  return net;
}

// Full forward over obs [n * feat]; writes per-node logits [n]. Returns
// the argmax node index, or -1 on bad input. Thread-safe (per-call
// scratch only) and GIL-free via ctypes.
int32_t set_decide(const void* handle, const float* obs, int32_t n,
                   float* logits_out) {
  const auto* net = static_cast<const SetNet*>(handle);
  if (net == nullptr || obs == nullptr || n <= 0) return -1;
  const int dim = net->dim;
  const int heads = net->heads;
  const int hd = dim / heads;
  const float scale = 1.0f / std::sqrt(static_cast<float>(hd));
  const size_t nd = static_cast<size_t>(n) * dim;

  std::vector<float> h(nd), hn(nd), q(nd), k(nd), v(nd), ctx(nd);
  std::vector<float> scores(n), mlp_mid(2 * dim), tmp(dim);

  for (int i = 0; i < n; ++i)
    dense_row(net->embed, obs + static_cast<size_t>(i) * net->feat,
              h.data() + static_cast<size_t>(i) * dim);

  for (const auto& blk : net->blocks) {
    for (int i = 0; i < n; ++i)
      layer_norm_row(blk.ln0, h.data() + static_cast<size_t>(i) * dim,
                     hn.data() + static_cast<size_t>(i) * dim, dim);
    for (int i = 0; i < n; ++i) {
      const float* row = hn.data() + static_cast<size_t>(i) * dim;
      dense_row(blk.q, row, q.data() + static_cast<size_t>(i) * dim);
      dense_row(blk.k, row, k.data() + static_cast<size_t>(i) * dim);
      dense_row(blk.v, row, v.data() + static_cast<size_t>(i) * dim);
    }
    for (int head = 0; head < heads; ++head) {
      const int off = head * hd;
      for (int i = 0; i < n; ++i) {
        const float* qi = q.data() + static_cast<size_t>(i) * dim + off;
        float mx = -1e30f;
        for (int j = 0; j < n; ++j) {
          const float* kj = k.data() + static_cast<size_t>(j) * dim + off;
          float s = 0.0f;
          for (int c = 0; c < hd; ++c) s += qi[c] * kj[c];
          scores[j] = s * scale;
          if (scores[j] > mx) mx = scores[j];
        }
        float denom = 0.0f;
        for (int j = 0; j < n; ++j) {
          scores[j] = std::exp(scores[j] - mx);
          denom += scores[j];
        }
        float* ci = ctx.data() + static_cast<size_t>(i) * dim + off;
        for (int c = 0; c < hd; ++c) ci[c] = 0.0f;
        for (int j = 0; j < n; ++j) {
          const float wj = scores[j] / denom;
          const float* vj = v.data() + static_cast<size_t>(j) * dim + off;
          for (int c = 0; c < hd; ++c) ci[c] += wj * vj[c];
        }
      }
    }
    for (int i = 0; i < n; ++i) {
      dense_row(blk.out, ctx.data() + static_cast<size_t>(i) * dim, tmp.data());
      float* hi = h.data() + static_cast<size_t>(i) * dim;
      for (int c = 0; c < dim; ++c) hi[c] += tmp[c];
    }
    for (int i = 0; i < n; ++i) {
      float* hi = h.data() + static_cast<size_t>(i) * dim;
      layer_norm_row(blk.ln1, hi, hn.data(), dim);
      dense_row(blk.w1, hn.data(), mlp_mid.data());
      for (int c = 0; c < 2 * dim; ++c) mlp_mid[c] = gelu(mlp_mid[c]);
      dense_row(blk.w2, mlp_mid.data(), tmp.data());
      for (int c = 0; c < dim; ++c) hi[c] += tmp[c];
    }
  }

  int best = 0;
  for (int i = 0; i < n; ++i) {
    layer_norm_row(net->final_norm, h.data() + static_cast<size_t>(i) * dim,
                   tmp.data(), dim);
    float s = net->score_bias;
    for (int c = 0; c < dim; ++c) s += tmp[c] * net->score_kernel[c];
    logits_out[i] = s;
    if (s > logits_out[best]) best = i;
  }
  return best;
}

void set_destroy(void* handle) { delete static_cast<SetNet*>(handle); }

int32_t set_abi_version() { return 2; }

}  // extern "C"

// ------------------------------------------------------------------ int8

namespace {

// Int8-quantized dense: TWO int8 planes per kernel, TRANSPOSED to
// [out][in] so each output's dot product is one contiguous scan (the
// layout the compiler widens to pmaddwd-class int16-multiply / int32-
// accumulate vectors). The primary plane quantizes the kernel with
// per-OUTPUT-CHANNEL symmetric scales; the residual plane quantizes
// what the primary missed at ~1/127 the step — all-int8 weight storage
// (2 bytes/weight, half of fp32) with effective ~14-bit precision,
// which is what keeps measured top-1 agreement above the 99.5% gate
// (single-plane per-channel int8 measured ~3-7% logit error on this
// net; the dual plane measures ~5e-4 against fp32). Activations
// quantize per row to int16 (the multiply path is int16 x int16 either
// way — signed-int8 dots have no wider vector instruction to lose).
// The RECORDED per-tensor scale (set_int8_scales) is the primary
// plane's max channel scale: one auditable number per tensor, a
// conservative bound on every channel's step size.
struct QDense {
  // The two int8 planes fold into ONE int16 operand at create time:
  // w = (kResidStep*q1 + q2) * (s1/kResidStep), exactly. One
  // pmaddwd-class GEMV instead of two, same quantized values.
  std::vector<int16_t> kernel_t;  // [out * in], folded planes
  std::vector<float> bias;        // [out]
  std::vector<float> scale;       // [out], folded per-channel scales
  float scale_max = 0.0f;         // recorded per-tensor primary scale
  int act_max = 0;                // activation quant range (overflow-safe)
  int in = 0;
  int out = 0;
};

// Residual-plane step divisor: the folded weight range is
// kResidStep*127 + 127, and the overflow budget 2^31 splits between
// weight range and activation range per dot length. 64 balances the
// two error terms (weight step s1/64 ~ activation step at the wired
// lengths — measured logit error ~5e-4, comfortably inside the 99.5%
// top-1 gate; 127 starved the activations to ~11 bits and tripled the
// error for no agreement gain).
constexpr int kResidStep = 64;
constexpr int kFoldMax = kResidStep * 127 + 127;  // |fold| bound

// Largest symmetric activation range whose int32 dot against operands
// bounded by ``other_max`` cannot overflow at length ``len`` — the
// int32-accumulate loop is what gcc turns into vpmaddwd vectors
// (measured: a float-pair-accumulating dot stays scalar, ~3x slower
// end to end), so overflow safety comes from the RANGE, not the
// accumulator width.
inline int safe_act_max(int other_max, int len) {
  const long long budget = 2147483647LL / (static_cast<long long>(other_max)
                                           * std::max(len, 1));
  return static_cast<int>(std::min<long long>(32767, budget));
}

struct QBlock {
  Norm ln0, ln1;
  QDense q, k, v, out, w1, w2;
};

struct QSetNet {
  QDense embed;
  std::vector<QBlock> blocks;
  Norm final_norm;
  std::vector<float> score_kernel;
  float score_bias = 0.0f;
  std::vector<float> scales;  // creation-order per-tensor record
  int feat = 0;
  int dim = 0;
  int heads = 1;
};

// Blocked-attention tile sizes. Queries process in blocks of kQueryBlock
// rows so every key/value j-tile loaded into cache is reused across the
// whole query block — the unblocked walk streams the full [n, hd] value
// array once PER QUERY (512 MB of traffic per fleet-N decide, the
// measured wall); blocking divides that by kQueryBlock. kAttnBlock is
// the j-tile: one tile's fp32 values (128 * 64 * 4 = 32 KB at hd=64)
// stay L1/L2-hot through the query block's weighted accumulation.
constexpr int kQueryBlock = 32;
constexpr int kAttnBlock = 128;

// Round-half-away via add-and-truncate: std::lround is a libm call the
// vectorizer cannot touch, and the row quantizers round ~3M values per
// fleet-N decide — measured as a top-line cost before this. A half-ulp
// rounding-mode difference is far below the quantization step.
inline int fast_round(float x) {
  return static_cast<int>(x + (x >= 0.0f ? 0.5f : -0.5f));
}

int8_t clamp_i8(float w, float inv) {
  const int v = fast_round(w * inv);
  return static_cast<int8_t>(std::max(-127, std::min(127, v)));
}

QDense quantize_dense(const Dense& d) {
  QDense q;
  q.in = d.in;
  q.out = d.out;
  q.bias = d.bias;
  q.scale.assign(d.out, 1.0f);
  q.act_max = safe_act_max(kFoldMax, d.in);
  q.kernel_t.resize(d.kernel.size());
  for (int j = 0; j < d.out; ++j) {
    float mx = 0.0f;
    for (int i = 0; i < d.in; ++i)
      mx = std::max(mx, std::fabs(
          d.kernel[static_cast<size_t>(i) * d.out + j]));
    const float s1 = mx > 0.0f ? mx / 127.0f : 1.0f;
    q.scale_max = std::max(q.scale_max, s1);
    const float s2 = s1 / kResidStep;  // the residual plane's step
    q.scale[j] = s2;
    const float inv1 = 1.0f / s1;
    const float inv2 = 1.0f / s2;
    for (int i = 0; i < d.in; ++i) {
      const float w = d.kernel[static_cast<size_t>(i) * d.out + j];
      const int q1 = clamp_i8(w, inv1);
      const int q2 = clamp_i8(w - static_cast<float>(q1) * s1, inv2);
      q.kernel_t[static_cast<size_t>(j) * d.in + i] =
          static_cast<int16_t>(kResidStep * q1 + q2);
    }
  }
  return q;
}

// exp(x) for the softmax's shifted scores (x <= 0): exponent
// bit-reconstruction + a degree-5 polynomial for the fraction — ~1e-4
// relative error, far below the quantization noise it sits on, and a
// dozen vectorizable ops where libm's expf was the measured hot spot
// (2M calls per fleet-N decide). memcpy type-punning (not a union) so
// the loop stays autovectorizable. Int8-path only: the fp32 core keeps
// bit-for-bit libm softmax.
inline float exp_approx(float x) {
  x = std::max(x, -87.0f);
  const float t = x * 1.4426950408889634f;  // log2(e)
  const float fi = std::floor(t);
  const float f = t - fi;
  // exp(f * ln2) on [0, 1), Taylor in ln2.
  const float p = 1.0f + f * (0.6931471805599453f + f * (0.2402265069591007f
      + f * (0.0555041086648216f + f * (0.0096181291076285f
      + f * 0.0013333558146428f))));
  int32_t bits;
  std::memcpy(&bits, &p, sizeof(bits));
  bits += static_cast<int32_t>(fi) << 23;
  float out;
  std::memcpy(&out, &bits, sizeof(out));
  return out;
}

// gelu via exp_approx-backed tanh: libm's tanh was the measured linear-
// term hot spot (256k scalar calls per fleet-N decide, ~a third of the
// decide). tanh(t) = 1 - 2/(exp(2t) + 1), t clamped where tanh has
// saturated anyway; error ~1e-4, below the quantization noise.
inline float gelu_approx(float x) {
  constexpr float kSqrt2OverPi = 0.7978845608028654f;
  float t = kSqrt2OverPi * (x + 0.044715f * x * x * x);
  t = std::max(-9.0f, std::min(9.0f, t));
  const float th = 1.0f - 2.0f / (exp_approx(2.0f * t) + 1.0f);
  return 0.5f * x * (1.0f + th);
}

// Symmetric per-row activation quantization into [-max_q, max_q]
// (int16 storage); returns the row scale. ``max_q`` comes from
// safe_act_max so the downstream int32 dot cannot overflow.
float quantize_row_i16(const float* x, int16_t* qx, int n, int max_q) {
  float mx = 0.0f;
  for (int i = 0; i < n; ++i) mx = std::max(mx, std::fabs(x[i]));
  const float scale = mx > 0.0f ? mx / static_cast<float>(max_q) : 1.0f;
  const float inv = 1.0f / scale;
  for (int i = 0; i < n; ++i) {
    const int v = fast_round(x[i] * inv);
    qx[i] = static_cast<int16_t>(std::max(-max_q, std::min(max_q, v)));
  }
  return scale;
}

// int16 x int16 dot with an int32 accumulator — the exact shape gcc
// vectorizes to vpmaddwd/vpaddd (measured: this form runs in zmm
// vectors; a float-pair-accumulating variant stayed scalar). Operand
// ranges are pre-bounded by safe_act_max so the accumulator cannot
// overflow at any wired length.
inline int32_t dot_i16(const int16_t* a, const int16_t* b, int n) {
  int32_t acc = 0;
  for (int c = 0; c < n; ++c)
    acc += static_cast<int32_t>(a[c]) * static_cast<int32_t>(b[c]);
  return acc;
}

// The apply half of the quantized dense, for callers that quantized the
// activation row once and feed several kernels from it (the q/k/v
// triple reads ONE LayerNormed row — re-quantizing it per kernel would
// triple the rounding bill for bit-identical results).
void qdense_apply(const QDense& d, const int16_t* qx, float sx, float* y) {
  for (int j = 0; j < d.out; ++j)
    y[j] = static_cast<float>(
               dot_i16(qx, d.kernel_t.data() +
                               static_cast<size_t>(j) * d.in, d.in)) *
               (sx * d.scale[j]) +
           d.bias[j];
}

// y[n] = dequant(qx . folded_kernel) for one activation row (scratch
// qx provided by the caller so the per-row buffer is reused).
void qdense_row(const QDense& d, const float* x, float* y, int16_t* qx) {
  const float sx = quantize_row_i16(x, qx, d.in, d.act_max);
  qdense_apply(d, qx, sx, y);
}

}  // namespace

extern "C" {

// Quantize the packed fp32 weights into an int8 net. ``scales_out``
// (nullable) receives the per-tensor scales in creation order, up to
// ``scales_cap`` entries; set_int8_scales re-reads them later.
void* set_create_int8(const float* weights, const int32_t* dims,
                      int32_t n_dims, float* scales_out,
                      int32_t scales_cap) {
  void* fp = set_create(weights, dims, n_dims);
  if (fp == nullptr) return nullptr;
  const auto* net = static_cast<const SetNet*>(fp);
  auto* q = new QSetNet();
  q->feat = net->feat;
  q->dim = net->dim;
  q->heads = net->heads;
  q->final_norm = net->final_norm;
  q->score_kernel = net->score_kernel;
  q->score_bias = net->score_bias;
  q->embed = quantize_dense(net->embed);
  q->scales.push_back(q->embed.scale_max);
  q->blocks.reserve(net->blocks.size());
  for (const auto& blk : net->blocks) {
    QBlock qb;
    qb.ln0 = blk.ln0;
    qb.ln1 = blk.ln1;
    qb.q = quantize_dense(blk.q);
    qb.k = quantize_dense(blk.k);
    qb.v = quantize_dense(blk.v);
    qb.out = quantize_dense(blk.out);
    qb.w1 = quantize_dense(blk.w1);
    qb.w2 = quantize_dense(blk.w2);
    for (const QDense* d : {&qb.q, &qb.k, &qb.v, &qb.out, &qb.w1, &qb.w2})
      q->scales.push_back(d->scale_max);
    q->blocks.push_back(std::move(qb));
  }
  set_destroy(fp);
  if (scales_out != nullptr) {
    const int n = std::min<int>(scales_cap,
                                static_cast<int>(q->scales.size()));
    for (int i = 0; i < n; ++i) scales_out[i] = q->scales[i];
  }
  return q;
}

int32_t set_int8_scales(const void* handle, float* out, int32_t cap) {
  const auto* net = static_cast<const QSetNet*>(handle);
  if (net == nullptr) return -1;
  if (out != nullptr) {
    const int n = std::min<int>(cap, static_cast<int>(net->scales.size()));
    for (int i = 0; i < n; ++i) out[i] = net->scales[i];
  }
  return static_cast<int32_t>(net->scales.size());
}

// Int8 forward over obs [n * feat]; same contract as set_decide.
// Thread-safe (per-call scratch only), GIL-free via ctypes.
int32_t set_decide_int8(const void* handle, const float* obs, int32_t n,
                        float* logits_out) {
  const auto* net = static_cast<const QSetNet*>(handle);
  if (net == nullptr || obs == nullptr || n <= 0) return -1;
  const int dim = net->dim;
  const int heads = net->heads;
  const int hd = dim / heads;
  const float scale = 1.0f / std::sqrt(static_cast<float>(hd));
  const size_t nd = static_cast<size_t>(n) * dim;

  std::vector<float> h(nd), hn(nd), q(nd), k(nd), v(nd), ctx(nd);
  std::vector<float> scores(static_cast<size_t>(kQueryBlock) * n);
  std::vector<float> mlp_mid(2 * dim), tmp(dim);
  // Dense activation scratch: sized for the WIDEST dense input — the
  // mlp mid (2*dim) or the raw feature row (a heterogeneous obs can be
  // wider than 2*dim at small model dims; sizing on dim alone would
  // overflow the embed quantization).
  std::vector<int16_t> qx(std::max(2 * dim, net->feat));
  std::vector<int16_t> qq(static_cast<size_t>(n) * hd);  // per-head q rows
  std::vector<int16_t> qk(static_cast<size_t>(n) * hd);  // per-head k rows
  std::vector<float> sq(n), sk(n);                 // per-row quant scales

  for (int i = 0; i < n; ++i)
    qdense_row(net->embed, obs + static_cast<size_t>(i) * net->feat,
               h.data() + static_cast<size_t>(i) * dim, qx.data());

  for (const auto& blk : net->blocks) {
    for (int i = 0; i < n; ++i)
      layer_norm_row(blk.ln0, h.data() + static_cast<size_t>(i) * dim,
                     hn.data() + static_cast<size_t>(i) * dim, dim);
    for (int i = 0; i < n; ++i) {
      const float* row = hn.data() + static_cast<size_t>(i) * dim;
      const float sx = quantize_row_i16(row, qx.data(), dim,
                                        blk.q.act_max);
      qdense_apply(blk.q, qx.data(), sx,
                   q.data() + static_cast<size_t>(i) * dim);
      qdense_apply(blk.k, qx.data(), sx,
                   k.data() + static_cast<size_t>(i) * dim);
      qdense_apply(blk.v, qx.data(), sx,
                   v.data() + static_cast<size_t>(i) * dim);
    }
    for (int head = 0; head < heads; ++head) {
      const int off = head * hd;
      // Re-quantize this head's q/k rows once (the score dots read
      // them n times each — the O(n^2) side of the bandwidth bill).
      // Both sides get the widest overflow-safe symmetric range for an
      // hd-length int32 dot (12-bit-class at hd=64 — score noise well
      // under the dense planes').
      const int attn_max = static_cast<int>(
          std::sqrt(static_cast<double>(2147483647LL / std::max(hd, 1))));
      for (int i = 0; i < n; ++i) {
        sq[i] = quantize_row_i16(
            q.data() + static_cast<size_t>(i) * dim + off,
            qq.data() + static_cast<size_t>(i) * hd, hd, attn_max);
        sk[i] = quantize_row_i16(
            k.data() + static_cast<size_t>(i) * dim + off,
            qk.data() + static_cast<size_t>(i) * hd, hd, attn_max);
      }
      for (int i0 = 0; i0 < n; i0 += kQueryBlock) {
        const int i1 = std::min(n, i0 + kQueryBlock);
        const int qb = i1 - i0;
        // Pass 1: the query block's score rows (int16 q x int8 k dots;
        // the int8 key stream is n*hd bytes and L2-resident, read once
        // per query row), softmaxed in place via the approx exp.
        for (int i = i0; i < i1; ++i) {
          float* sc = scores.data() + static_cast<size_t>(i - i0) * n;
          const int16_t* qi = qq.data() + static_cast<size_t>(i) * hd;
          const float si = sq[i] * scale;
          float mx = -1e30f;
          for (int j = 0; j < n; ++j) {
            sc[j] = static_cast<float>(dot_i16(
                        qi, qk.data() + static_cast<size_t>(j) * hd,
                        hd)) * si * sk[j];
            if (sc[j] > mx) mx = sc[j];
          }
          float denom = 0.0f;
          for (int j = 0; j < n; ++j) {
            sc[j] = exp_approx(sc[j] - mx);
            denom += sc[j];
          }
          const float inv = 1.0f / denom;
          for (int j = 0; j < n; ++j) sc[j] *= inv;
          float* ci = ctx.data() + static_cast<size_t>(i) * dim + off;
          for (int c = 0; c < hd; ++c) ci[c] = 0.0f;
        }
        // Pass 2: weighted-v as a blocked mini-GEMM — each fp32 value
        // j-tile loads once per QUERY BLOCK and feeds every row's
        // hd-wide accumulation while cache-hot.
        for (int j0 = 0; j0 < n; j0 += kAttnBlock) {
          const int j1 = std::min(n, j0 + kAttnBlock);
          for (int i = i0; i < i1; ++i) {
            const float* sc = scores.data()
                + static_cast<size_t>(i - i0) * n;
            float* ci = ctx.data() + static_cast<size_t>(i) * dim + off;
            for (int j = j0; j < j1; ++j) {
              const float wj = sc[j];
              const float* vj = v.data()
                  + static_cast<size_t>(j) * dim + off;
              for (int c = 0; c < hd; ++c) ci[c] += wj * vj[c];
            }
          }
        }
      }
    }
    for (int i = 0; i < n; ++i) {
      qdense_row(blk.out, ctx.data() + static_cast<size_t>(i) * dim,
                 tmp.data(), qx.data());
      float* hi = h.data() + static_cast<size_t>(i) * dim;
      for (int c = 0; c < dim; ++c) hi[c] += tmp[c];
    }
    for (int i = 0; i < n; ++i) {
      float* hi = h.data() + static_cast<size_t>(i) * dim;
      layer_norm_row(blk.ln1, hi, hn.data(), dim);
      qdense_row(blk.w1, hn.data(), mlp_mid.data(), qx.data());
      for (int c = 0; c < 2 * dim; ++c)
        mlp_mid[c] = gelu_approx(mlp_mid[c]);
      qdense_row(blk.w2, mlp_mid.data(), tmp.data(), qx.data());
      for (int c = 0; c < dim; ++c) hi[c] += tmp[c];
    }
  }

  int best = 0;
  for (int i = 0; i < n; ++i) {
    layer_norm_row(net->final_norm, h.data() + static_cast<size_t>(i) * dim,
                   tmp.data(), dim);
    float s = net->score_bias;
    for (int c = 0; c < dim; ++c) s += tmp[c] * net->score_kernel[c];
    logits_out[i] = s;
    if (s > logits_out[best]) best = i;
  }
  return best;
}

void set_destroy_int8(void* handle) {
  delete static_cast<QSetNet*>(handle);
}

}  // extern "C"
