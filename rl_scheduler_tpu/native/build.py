"""Build + load the native inference core (``mlp_infer.cpp``) via ctypes.

Build-on-first-use: ``g++ -O3 -shared -fPIC`` into the user cache dir,
keyed on the source hash so edits rebuild automatically. Everything
degrades gracefully — no compiler, no ``.so``, or a load error just means
the caller falls back to the numpy path (``ensure_built`` returns
``None``). ``make`` in this directory does the same build explicitly.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
from pathlib import Path

import numpy as np

logger = logging.getLogger(__name__)

_SRC = Path(__file__).with_name("mlp_infer.cpp")
ABI_VERSION = 2
ACTIVATIONS = {"tanh": 0, "relu": 1}


def _cache_dir() -> Path:
    root = os.environ.get("XDG_CACHE_HOME") or (Path.home() / ".cache")
    return Path(root) / "rl_scheduler_tpu"


def ensure_built(force: bool = False) -> Path | None:
    """Compile the shared library if needed; returns its path or ``None``."""
    if not _SRC.exists():
        return None
    digest = hashlib.sha256(_SRC.read_bytes()).hexdigest()[:16]
    out = _cache_dir() / f"libmlp_infer_{digest}.so"
    if out.exists() and not force:
        return out
    out.parent.mkdir(parents=True, exist_ok=True)
    # Compile to a temp name + atomic rename: concurrent builders race safely.
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=out.parent)
    os.close(fd)
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           str(_SRC), "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
        return out
    except (subprocess.SubprocessError, OSError) as e:
        logger.warning("native build failed (%s); using numpy fallback", e)
        Path(tmp).unlink(missing_ok=True)
        return None


def pack_mlp(layers: list[tuple[np.ndarray, np.ndarray]]) -> tuple[np.ndarray, np.ndarray]:
    """Pack ``[(kernel [in,out], bias [out]), ...]`` into the flat
    ``(weights, dims)`` buffers ``mlp_create`` expects."""
    dims = [layers[0][0].shape[0]]
    chunks = []
    for kernel, bias in layers:
        if kernel.shape[0] != dims[-1] or kernel.shape[1] != bias.shape[0]:
            raise ValueError(
                f"inconsistent layer shapes: {kernel.shape} after width {dims[-1]}"
            )
        dims.append(kernel.shape[1])
        chunks.append(np.ascontiguousarray(kernel, np.float32).ravel())
        chunks.append(np.ascontiguousarray(bias, np.float32).ravel())
    return np.concatenate(chunks), np.asarray(dims, np.int32)


class NativeMLP:
    """ctypes wrapper over one packed MLP; ``decide`` is thread-safe."""

    def __init__(self, layers: list[tuple[np.ndarray, np.ndarray]],
                 lib_path: Path | None = None, activation: str = "tanh"):
        if activation not in ACTIVATIONS:
            raise ValueError(
                f"unknown activation {activation!r}; choose from {sorted(ACTIVATIONS)}"
            )
        lib_path = lib_path or ensure_built()
        if lib_path is None:
            raise RuntimeError("native library unavailable")
        lib = ctypes.CDLL(str(lib_path))
        lib.mlp_create.restype = ctypes.c_void_p
        lib.mlp_create.argtypes = [
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
            ctypes.c_int32,
        ]
        lib.mlp_decide.restype = ctypes.c_int32
        lib.mlp_decide.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
        ]
        lib.mlp_destroy.argtypes = [ctypes.c_void_p]
        lib.mlp_abi_version.restype = ctypes.c_int32
        if lib.mlp_abi_version() != ABI_VERSION:
            raise RuntimeError("native library ABI mismatch; rebuild")
        self._lib = lib

        weights, dims = pack_mlp(layers)
        self._obs_dim = int(dims[0])
        self._out_dim = int(dims[-1])
        handle = lib.mlp_create(
            weights.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            dims.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(dims),
            ACTIVATIONS[activation],
        )
        if not handle:
            raise RuntimeError("mlp_create rejected the packed weights")
        self._handle = handle

    @property
    def obs_dim(self) -> int:
        return self._obs_dim

    def decide(self, obs: np.ndarray) -> tuple[int, np.ndarray]:
        obs = np.ascontiguousarray(obs, np.float32)
        if obs.shape != (self._obs_dim,):
            raise ValueError(f"expected obs shape ({self._obs_dim},), got {obs.shape}")
        logits = np.empty(self._out_dim, np.float32)
        action = self._lib.mlp_decide(
            self._handle,
            obs.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            logits.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        )
        return int(action), logits

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.mlp_destroy(handle)
            self._handle = None
