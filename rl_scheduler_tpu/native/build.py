"""Build + load the native inference core (``mlp_infer.cpp``) via ctypes.

Build-on-first-use: ``g++ -O3 -shared -fPIC`` into the user cache dir,
keyed on the source hash so edits rebuild automatically. Everything
degrades gracefully — no compiler, no ``.so``, or a load error just means
the caller falls back to the numpy path (``ensure_built`` returns
``None``). ``make`` in this directory does the same build explicitly.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
from pathlib import Path

import numpy as np

logger = logging.getLogger(__name__)

_SRC = Path(__file__).with_name("mlp_infer.cpp")
_SRC_SET = Path(__file__).with_name("set_infer.cpp")
ABI_VERSION = 2
SET_ABI_VERSION = 2
ACTIVATIONS = {"tanh": 0, "relu": 1}


def _cache_dir() -> Path:
    root = os.environ.get("XDG_CACHE_HOME") or (Path.home() / ".cache")
    return Path(root) / "rl_scheduler_tpu"


def _host_isa_tag() -> str:
    """Short tag identifying the build host's ISA — part of the .so
    cache key, because the first build attempt targets -march=native: a
    cache dir on a network home shared across heterogeneous hosts must
    not hand an AVX-512 binary to a machine without it (the load would
    SIGILL mid-decide; the portable-retry only covers COMPILE failures,
    not foreign-ISA loads)."""
    machine = getattr(os, "uname", lambda: None)()
    machine = machine.machine if machine is not None else "unknown"
    flags = ""
    try:
        with open("/proc/cpuinfo", encoding="utf-8") as fh:
            for line in fh:
                if line.startswith("flags"):
                    flags = line
                    break
    except OSError:
        pass
    return f"{machine}-{hashlib.sha256(flags.encode()).hexdigest()[:8]}"


def _build(src: Path, stem: str, force: bool = False) -> Path | None:
    """Compile one source into the cache dir, keyed on its hash + the
    host ISA (see :func:`_host_isa_tag`)."""
    if not src.exists():
        return None
    digest = hashlib.sha256(src.read_bytes()).hexdigest()[:16]
    out = _cache_dir() / f"lib{stem}_{digest}_{_host_isa_tag()}.so"
    if out.exists() and not force:
        return out
    out.parent.mkdir(parents=True, exist_ok=True)
    # Compile to a temp name + atomic rename: concurrent builders race safely.
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=out.parent)
    os.close(fd)
    base = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
            str(src), "-o", tmp]
    # First attempt targets the build host's ISA: the int8 fleet forward
    # (graftfwd) autovectorizes its dot products only as wide as the
    # target allows, and the .so cache key carries the host ISA. The
    # portable build is the retry (compile failure) and the only attempt
    # on machines where -march=native is not known-good. Guarded getattr
    # like _host_isa_tag: a platform without os.uname must fall through
    # to the numpy fallback, not crash construction.
    uname = getattr(os, "uname", lambda: None)()
    machine = uname.machine if uname is not None else ""
    attempts = ([base[:1] + ["-march=native"] + base[1:]]
                if machine in ("x86_64", "aarch64") else [])
    attempts.append(base)
    last_error: Exception | None = None
    for cmd in attempts:
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, out)
            return out
        except (subprocess.SubprocessError, OSError) as e:
            last_error = e
    logger.warning("native build failed (%s); using numpy fallback",
                   last_error)
    Path(tmp).unlink(missing_ok=True)
    return None


def ensure_built(force: bool = False) -> Path | None:
    """Compile the MLP shared library if needed; its path or ``None``."""
    return _build(_SRC, "mlp_infer", force)


def ensure_built_set(force: bool = False) -> Path | None:
    """Compile the set-transformer shared library; its path or ``None``."""
    return _build(_SRC_SET, "set_infer", force)


def pack_mlp(layers: list[tuple[np.ndarray, np.ndarray]]) -> tuple[np.ndarray, np.ndarray]:
    """Pack ``[(kernel [in,out], bias [out]), ...]`` into the flat
    ``(weights, dims)`` buffers ``mlp_create`` expects."""
    dims = [layers[0][0].shape[0]]
    chunks = []
    for kernel, bias in layers:
        if kernel.shape[0] != dims[-1] or kernel.shape[1] != bias.shape[0]:
            raise ValueError(
                f"inconsistent layer shapes: {kernel.shape} after width {dims[-1]}"
            )
        dims.append(kernel.shape[1])
        chunks.append(np.ascontiguousarray(kernel, np.float32).ravel())
        chunks.append(np.ascontiguousarray(bias, np.float32).ravel())
    return np.concatenate(chunks), np.asarray(dims, np.int32)


class NativeMLP:
    """ctypes wrapper over one packed MLP; ``decide`` is thread-safe."""

    def __init__(self, layers: list[tuple[np.ndarray, np.ndarray]],
                 lib_path: Path | None = None, activation: str = "tanh"):
        if activation not in ACTIVATIONS:
            raise ValueError(
                f"unknown activation {activation!r}; choose from {sorted(ACTIVATIONS)}"
            )
        lib_path = lib_path or ensure_built()
        if lib_path is None:
            raise RuntimeError("native library unavailable")
        lib = ctypes.CDLL(str(lib_path))
        lib.mlp_create.restype = ctypes.c_void_p
        lib.mlp_create.argtypes = [
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
            ctypes.c_int32,
        ]
        lib.mlp_decide.restype = ctypes.c_int32
        lib.mlp_decide.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float),
        ]
        lib.mlp_destroy.argtypes = [ctypes.c_void_p]
        lib.mlp_abi_version.restype = ctypes.c_int32
        if lib.mlp_abi_version() != ABI_VERSION:
            raise RuntimeError("native library ABI mismatch; rebuild")
        self._lib = lib

        weights, dims = pack_mlp(layers)
        self._obs_dim = int(dims[0])
        self._out_dim = int(dims[-1])
        handle = lib.mlp_create(
            weights.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            dims.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(dims),
            ACTIVATIONS[activation],
        )
        if not handle:
            raise RuntimeError("mlp_create rejected the packed weights")
        self._handle = handle

    @property
    def obs_dim(self) -> int:
        return self._obs_dim

    def decide(self, obs: np.ndarray) -> tuple[int, np.ndarray]:
        obs = np.ascontiguousarray(obs, np.float32)
        if obs.shape != (self._obs_dim,):
            raise ValueError(f"expected obs shape ({self._obs_dim},), got {obs.shape}")
        logits = np.empty(self._out_dim, np.float32)
        action = self._lib.mlp_decide(
            self._handle,
            obs.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            logits.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        )
        return int(action), logits

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.mlp_destroy(handle)
            self._handle = None


def pack_set(params: dict, depth: int = 2) -> tuple[np.ndarray, np.ndarray]:
    """Pack a ``SetTransformerPolicy`` param subtree (nested dicts, the
    ``{"params": ...}`` wrapper optional) into the flat ``(weights, dims)``
    buffers ``set_create`` expects (layout contract in set_infer.cpp).

    QKV kernels fold the head axis ([dim, H, hd] -> [dim, dim]); the out
    kernel folds [H, hd, dim] -> [dim, dim]. ``dims`` carries num_heads so
    the kernel splits per-head subspaces at the same boundaries."""
    p = params["params"] if "params" in params else params
    chunks: list[np.ndarray] = []

    def flat(x):
        chunks.append(np.ascontiguousarray(np.asarray(x, np.float32)).ravel())

    def dense(leaf, in_dim, out_dim):
        kernel = np.asarray(leaf["kernel"], np.float32).reshape(in_dim, out_dim)
        flat(kernel)
        flat(np.asarray(leaf["bias"], np.float32).reshape(out_dim))

    embed_kernel = np.asarray(p["embed"]["kernel"], np.float32)
    feat, dim = embed_kernel.shape
    qk = np.asarray(
        p["block_0"]["MultiHeadDotProductAttention_0"]["query"]["kernel"]
    )
    heads = qk.shape[1] if qk.ndim == 3 else 1
    dense(p["embed"], feat, dim)
    for i in range(depth):
        blk = p[f"block_{i}"]
        attn = blk["MultiHeadDotProductAttention_0"]
        flat(blk["LayerNorm_0"]["scale"])
        flat(blk["LayerNorm_0"]["bias"])
        for name in ("query", "key", "value"):
            dense(attn[name], dim, dim)
        # out kernel is [H, hd, dim] -> contiguous [dim, dim] in-order.
        out_kernel = np.asarray(attn["out"]["kernel"], np.float32).reshape(dim, dim)
        flat(out_kernel)
        flat(np.asarray(attn["out"]["bias"], np.float32).reshape(dim))
        flat(blk["LayerNorm_1"]["scale"])
        flat(blk["LayerNorm_1"]["bias"])
        dense(blk["Dense_0"], dim, 2 * dim)
        dense(blk["Dense_1"], 2 * dim, dim)
    flat(p["final_norm"]["scale"])
    flat(p["final_norm"]["bias"])
    flat(np.asarray(p["head"]["score_head"]["kernel"], np.float32).reshape(dim))
    flat(np.asarray(p["head"]["score_head"]["bias"], np.float32).reshape(1))
    dims = np.asarray([feat, dim, depth, heads], np.int32)
    return np.concatenate(chunks), dims


class NativeSetTransformer:
    """ctypes wrapper over one packed set transformer; ``decide`` takes
    ``[N, feat]`` obs with N variable per call, is thread-safe, and runs
    GIL-free (ctypes releases the GIL for the call's duration)."""

    def __init__(self, params: dict, depth: int = 2,
                 lib_path: Path | None = None):
        lib_path = lib_path or ensure_built_set()
        if lib_path is None:
            raise RuntimeError("native set library unavailable")
        lib = ctypes.CDLL(str(lib_path))
        lib.set_create.restype = ctypes.c_void_p
        lib.set_create.argtypes = [
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
        ]
        lib.set_decide.restype = ctypes.c_int32
        lib.set_decide.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_float),
        ]
        lib.set_destroy.argtypes = [ctypes.c_void_p]
        lib.set_abi_version.restype = ctypes.c_int32
        if lib.set_abi_version() != SET_ABI_VERSION:
            raise RuntimeError("native set library ABI mismatch; rebuild")
        self._lib = lib
        weights, dims = pack_set(params, depth)
        self._feat = int(dims[0])
        handle = lib.set_create(
            weights.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            dims.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(dims),
        )
        if not handle:
            raise RuntimeError("set_create rejected the packed weights")
        self._handle = handle

    def decide(self, obs: np.ndarray) -> tuple[int, np.ndarray]:
        obs = np.ascontiguousarray(obs, np.float32)
        if obs.ndim != 2 or obs.shape[1] != self._feat:
            raise ValueError(
                f"expected obs shape (N, {self._feat}), got {obs.shape}"
            )
        n = obs.shape[0]
        logits = np.empty(n, np.float32)
        action = self._lib.set_decide(
            self._handle,
            obs.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n,
            logits.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        )
        if action < 0:
            raise RuntimeError("set_decide failed")
        return int(action), logits

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.set_destroy(handle)
            self._handle = None


class NativeSetTransformerInt8:
    """graftfwd: the int8-quantized C++ set forward (``set_decide_int8``).

    Same packed-weight layout as :class:`NativeSetTransformer`;
    quantization happens once at create time inside the core (symmetric
    per-tensor int8 for every dense kernel), and the recorded per-tensor
    scales are exposed as :attr:`scales` — checkpoint-load-time
    quantization with an auditable record, the graftfwd contract.
    ``decide`` is thread-safe and GIL-free like the fp32 core. Serving
    activation is gated on measured top-1 agreement vs fp32
    (``scheduler/fastpath.check_int8_agreement``) — this class only does
    the math."""

    # Per-tensor scale count: embed + (q, k, v, out, w1, w2) per block.
    SCALES_PER_BLOCK = 6

    def __init__(self, params: dict, depth: int = 2,
                 lib_path: Path | None = None):
        lib_path = lib_path or ensure_built_set()
        if lib_path is None:
            raise RuntimeError("native set library unavailable")
        lib = ctypes.CDLL(str(lib_path))
        lib.set_create_int8.restype = ctypes.c_void_p
        lib.set_create_int8.argtypes = [
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int32,
        ]
        lib.set_decide_int8.restype = ctypes.c_int32
        lib.set_decide_int8.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_float),
        ]
        lib.set_int8_scales.restype = ctypes.c_int32
        lib.set_int8_scales.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int32,
        ]
        lib.set_destroy_int8.argtypes = [ctypes.c_void_p]
        lib.set_abi_version.restype = ctypes.c_int32
        if lib.set_abi_version() != SET_ABI_VERSION:
            raise RuntimeError("native set library ABI mismatch; rebuild")
        self._lib = lib
        weights, dims = pack_set(params, depth)
        self._feat = int(dims[0])
        n_scales = 1 + self.SCALES_PER_BLOCK * int(dims[2])
        scales = np.zeros(n_scales, np.float32)
        handle = lib.set_create_int8(
            weights.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            dims.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(dims),
            scales.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n_scales,
        )
        if not handle:
            raise RuntimeError("set_create_int8 rejected the packed weights")
        self._handle = handle
        self.scales = [float(s) for s in scales]

    def decide(self, obs: np.ndarray) -> tuple[int, np.ndarray]:
        obs = np.ascontiguousarray(obs, np.float32)
        if obs.ndim != 2 or obs.shape[1] != self._feat:
            raise ValueError(
                f"expected obs shape (N, {self._feat}), got {obs.shape}"
            )
        n = obs.shape[0]
        logits = np.empty(n, np.float32)
        action = self._lib.set_decide_int8(
            self._handle,
            obs.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n,
            logits.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        )
        if action < 0:
            raise RuntimeError("set_decide_int8 failed")
        return int(action), logits

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.set_destroy_int8(handle)
            self._handle = None
