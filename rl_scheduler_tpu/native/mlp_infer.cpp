// Native MLP inference core for the scheduler extender's CPU serving path.
//
// The serving contract (<1 ms p50 per placement decision, SURVEY.md §6 /
// BASELINE.json) is easily met by the numpy fallback, but every layer of
// Python dispatch costs tens of microseconds under load; this core runs the
// whole tanh-MLP actor forward in one C call so the extender's hot path is
// a single ctypes hop. Weights are packed once at load time; decide() uses
// only stack/scratch-free per-call state, so it is safe to call from many
// server threads concurrently on one handle.
//
// Layout contract (must match rl_scheduler_tpu/native/build.py pack_mlp):
//   dims   = [d_0, d_1, ..., d_L]   layer widths, d_0 = obs dim
//   weights = for each layer i: kernel (d_i x d_{i+1}, row-major, numpy
//             [in, out] order) followed by bias (d_{i+1})
// Hidden layers apply the configured activation (0 = tanh for the PPO
// actor, 1 = relu for the DQN Q-network); the final layer is linear
// (logits / Q-values).

#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct Layer {
  std::vector<float> kernel;  // [in * out], row-major [in][out]
  std::vector<float> bias;    // [out]
  int in = 0;
  int out = 0;
};

enum Activation : int32_t { kTanh = 0, kRelu = 1 };

struct MLP {
  std::vector<Layer> layers;
  int max_width = 0;
  Activation act = kTanh;
};

void forward_layer(const Layer& l, const float* x, float* y, bool activate,
                   Activation act) {
  for (int j = 0; j < l.out; ++j) y[j] = l.bias[j];
  for (int i = 0; i < l.in; ++i) {
    const float xi = x[i];
    const float* row = l.kernel.data() + static_cast<size_t>(i) * l.out;
    for (int j = 0; j < l.out; ++j) y[j] += xi * row[j];
  }
  if (activate) {
    if (act == kRelu) {
      for (int j = 0; j < l.out; ++j) y[j] = y[j] > 0.0f ? y[j] : 0.0f;
    } else {
      for (int j = 0; j < l.out; ++j) y[j] = std::tanh(y[j]);
    }
  }
}

}  // namespace

extern "C" {

// Returns an opaque handle, or nullptr on invalid arguments.
// activation: 0 = tanh, 1 = relu (hidden layers only).
void* mlp_create(const float* weights, const int32_t* dims, int32_t n_dims,
                 int32_t activation) {
  if (weights == nullptr || dims == nullptr || n_dims < 2) return nullptr;
  if (activation != kTanh && activation != kRelu) return nullptr;
  auto* mlp = new MLP();
  mlp->act = static_cast<Activation>(activation);
  size_t off = 0;
  for (int32_t i = 0; i + 1 < n_dims; ++i) {
    if (dims[i] <= 0 || dims[i + 1] <= 0) {
      delete mlp;
      return nullptr;
    }
    Layer l;
    l.in = dims[i];
    l.out = dims[i + 1];
    l.kernel.assign(weights + off, weights + off + static_cast<size_t>(l.in) * l.out);
    off += static_cast<size_t>(l.in) * l.out;
    l.bias.assign(weights + off, weights + off + l.out);
    off += l.out;
    if (l.out > mlp->max_width) mlp->max_width = l.out;
    if (l.in > mlp->max_width) mlp->max_width = l.in;
    mlp->layers.push_back(std::move(l));
  }
  return mlp;
}

// Full forward pass; writes final-layer outputs into logits_out (size =
// last dim). Returns argmax index, or -1 on null handle. Thread-safe.
int32_t mlp_decide(const void* handle, const float* obs, float* logits_out) {
  const auto* mlp = static_cast<const MLP*>(handle);
  if (mlp == nullptr || mlp->layers.empty()) return -1;
  std::vector<float> a(mlp->max_width), b(mlp->max_width);
  const size_t n = mlp->layers.size();
  std::memcpy(a.data(), obs, sizeof(float) * mlp->layers[0].in);
  float* x = a.data();
  float* y = b.data();
  for (size_t i = 0; i < n; ++i) {
    forward_layer(mlp->layers[i], x, y, /*activate=*/i + 1 < n, mlp->act);
    std::swap(x, y);
  }
  // Result lives in x after the final swap.
  const int out_dim = mlp->layers.back().out;
  int best = 0;
  for (int j = 0; j < out_dim; ++j) {
    logits_out[j] = x[j];
    if (x[j] > x[best]) best = j;
  }
  return best;
}

void mlp_destroy(void* handle) { delete static_cast<MLP*>(handle); }

int32_t mlp_abi_version() { return 2; }

}  // extern "C"
