"""Baseline scheduling policies (reference parity).

- Cost-greedy: pick the cloud with the lower observed cost — the reference's
  ``normal_scheduler_step`` (``k8s_multi_cloud_env.py:156-157``).
- Round-robin: alternate clouds by step parity — the inline baseline in the
  reference's comparison harness (``train_and_compare.py:63-69``).
- Random: uniform action (the reference env's ``__main__`` smoke test).

All are jit/vmap-friendly: arrays in, arrays out.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cost_greedy_policy(obs: jnp.ndarray) -> jnp.ndarray:
    """0 (AWS) if obs cost_aws <= cost_azure else 1 (Azure). Works on [6] or
    [N, 6]."""
    return jnp.where(obs[..., 0] <= obs[..., 1], 0, 1).astype(jnp.int32)


def round_robin_policy(step_idx: jnp.ndarray) -> jnp.ndarray:
    """AWS on even steps, Azure on odd (reference parity)."""
    return (step_idx % 2).astype(jnp.int32)


def random_policy(key: jnp.ndarray, shape: tuple = ()) -> jnp.ndarray:
    return jax.random.randint(key, shape, 0, 2, jnp.int32)


# ------------------------------------------------- structured (node-set)
#
# Hand-coded baselines over per-node observations ``[..., N, FEAT]`` —
# the comparison points for the structured policies (configs 4-5,
# docs/status.md convergence rows). Feature columns differ per env
# family (env/cluster_set.py vs env/cluster_graph.py), so the policies
# take the column index rather than hardcoding one family's layout.

STRUCTURED_COLUMNS = {
    # env name -> {feature: column} (see the env modules' _observe)
    "cluster_set": {"cost": 0, "cpu": 2},
    "cluster_graph": {"cost": 0, "cpu": 1},
    # Scenario layer: the heterogeneous multi-resource env widens the set
    # layout but keeps cost first and the first (cpu) utilization column
    # at index 2 (scenarios/het_env.py docstring).
    "cluster_set_het": {"cost": 0, "cpu": 2},
}


def cheapest_node_policy(obs: jnp.ndarray, cost_col: int) -> jnp.ndarray:
    """Pick the node with the lowest cost feature (ties -> lowest index).
    Myopic: ignores utilization, so it overloads the cheap node — the
    failure mode the set env's capacity term exists to punish."""
    return jnp.argmin(obs[..., cost_col], axis=-1).astype(jnp.int32)


def load_spread_policy(obs: jnp.ndarray, cpu_col: int) -> jnp.ndarray:
    """Pick the least-utilized node (ties -> lowest index). Ignores cost."""
    return jnp.argmin(obs[..., cpu_col], axis=-1).astype(jnp.int32)


def random_node_policy(key: jnp.ndarray, obs: jnp.ndarray) -> jnp.ndarray:
    """Uniform over the node axis of ``[..., N, FEAT]`` obs."""
    return jax.random.randint(
        key, obs.shape[:-2], 0, obs.shape[-2], jnp.int32
    )


def structured_baselines(env_name: str, columns: dict | None = None) -> dict:
    """``{name: policy_fn(obs, key) -> actions}`` for a structured env
    family — the baselines the status-table convergence rows compare
    against, reproducible from the evaluation CLI.

    ``columns`` overrides the layout lookup — the scenario eval matrix
    passes each scenario's own column map so every matrix cell's
    baseline reads the right features (a scenario can reorder or widen
    the observation; hardcoding cluster_set's layout would silently
    score the wrong column there)."""
    cols = columns if columns is not None else STRUCTURED_COLUMNS[env_name]
    return {
        "random": lambda obs, key: random_node_policy(key, obs),
        "cheapest_node": lambda obs, key: cheapest_node_policy(obs, cols["cost"]),
        "load_spread": lambda obs, key: load_spread_policy(obs, cols["cpu"]),
    }
