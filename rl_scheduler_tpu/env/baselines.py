"""Baseline scheduling policies (reference parity).

- Cost-greedy: pick the cloud with the lower observed cost — the reference's
  ``normal_scheduler_step`` (``k8s_multi_cloud_env.py:156-157``).
- Round-robin: alternate clouds by step parity — the inline baseline in the
  reference's comparison harness (``train_and_compare.py:63-69``).
- Random: uniform action (the reference env's ``__main__`` smoke test).

All are jit/vmap-friendly: arrays in, arrays out.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cost_greedy_policy(obs: jnp.ndarray) -> jnp.ndarray:
    """0 (AWS) if obs cost_aws <= cost_azure else 1 (Azure). Works on [6] or
    [N, 6]."""
    return jnp.where(obs[..., 0] <= obs[..., 1], 0, 1).astype(jnp.int32)


def round_robin_policy(step_idx: jnp.ndarray) -> jnp.ndarray:
    """AWS on even steps, Azure on odd (reference parity)."""
    return (step_idx % 2).astype(jnp.int32)


def random_policy(key: jnp.ndarray, shape: tuple = ()) -> jnp.ndarray:
    return jax.random.randint(key, shape, 0, 2, jnp.int32)
