"""Gymnasium adapter: the reference's public env API over the functional core.

Drop-in surface parity with the reference ``K8sMultiCloudEnv``
(``rl_scheduler/env/k8s_multi_cloud_env.py:36-157``): same spaces, same
5-tuple ``step`` return, same ``info`` dict (``chosen_cloud`` as a string,
``step``), same ``normal_scheduler_step`` baseline, same
``fast_mode=False`` hook that dry-runs a pod placement against a real
cluster. Internally it is a thin host-side shell: all math happens in the
jitted functional core, so this class stays a convenience for single-env
use and parity tests — training uses the vmapped core directly.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np

try:
    import gymnasium as gym
    from gymnasium import spaces

    _GYM_BASE = gym.Env
except ImportError:  # pragma: no cover - gymnasium is a soft dependency
    gym = None
    spaces = None
    _GYM_BASE = object

from rl_scheduler_tpu.config import EnvConfig
from rl_scheduler_tpu.env import core

_JIT_RESET = jax.jit(core.reset)
_JIT_STEP = jax.jit(core.step)


class K8sMultiCloudEnv(_GYM_BASE):
    """Single multi-cloud scheduling env with the Gymnasium 5-tuple API.

    Episode-end semantics: reaching the end of the replay table is reported
    as a TERMINATION (``done=True``, ``truncated=False``), deliberately
    matching both the reference env (which sets ``done`` at step 99,
    ``k8s_multi_cloud_env.py:139-141``) and this framework's training-side
    GAE, which treats the horizon end as a true terminal state (no value
    bootstrap). Wrap in ``gymnasium.wrappers.TimeLimit`` if an external
    consumer needs truncation-style bootstrapping instead — that mirrors
    the reference's own ``TimeLimit(100)`` variant
    (``train_and_compare.py:18``).
    """

    metadata = {"render_modes": []}

    def __init__(
        self,
        env_config: dict | None = None,
        fast_mode: bool = True,
        config: EnvConfig | None = None,
    ):
        if gym is None:
            raise ImportError("gymnasium is required for the adapter; use env.core directly")
        super().__init__()
        # Unlike the reference (which accepts env_config and ignores it,
        # k8s_multi_cloud_env.py:46), dict entries override EnvConfig fields.
        if config is None:
            config = EnvConfig(**(env_config or {}))
        self.config = config
        self.fast_mode = fast_mode
        self.params = core.make_params(config)
        self.action_space = spaces.Discrete(core.NUM_ACTIONS)
        self.observation_space = spaces.Box(0.0, 1.0, (core.OBS_DIM,), np.float32)
        self.max_steps = int(self.params.max_steps)
        self.current_step = 0
        # Module-level jits: all adapter instances share one compiled program.
        self._jit_reset = _JIT_RESET
        self._jit_step = _JIT_STEP
        self._state = None
        self._placer = None
        if not fast_mode:
            from rl_scheduler_tpu.scheduler.k8s_client import DryRunPodPlacer

            self._placer = DryRunPodPlacer()

    def reset(self, seed: int | None = None, options: dict | None = None):
        if gym is not None:
            super().reset(seed=seed)
        if seed is None:
            # Gymnasium semantics: unseeded resets are nondeterministic and
            # independent across instances/processes.
            seed = int.from_bytes(os.urandom(4), "little")
        self._state, obs = self._jit_reset(self.params, jax.random.PRNGKey(seed))
        self.current_step = 0
        return np.asarray(obs), {}

    def step(self, action):
        action = int(action)
        assert action in (0, 1), f"Invalid action {action}"
        self._state, ts = self._jit_step(self.params, self._state, action)
        if self._placer is not None:
            # Host-side, outside jit: dry-run a pod placement on the chosen
            # cluster (reference slow mode, k8s_multi_cloud_env.py:125-137).
            self._placer.place(cloud="aws" if action == 0 else "azure")
        # ONE device->host transfer for the whole timestep: the previous
        # per-field conversions (float(ts.reward), bool(ts.done), ...) each
        # forced a separate device sync — ~100 ms apiece through a tunneled
        # TPU (GL008, tools/graftlint).
        obs, reward, done, step_idx = jax.device_get(
            (ts.obs, ts.reward, ts.done, ts.step)
        )
        self.current_step = int(step_idx)
        info = {"chosen_cloud": "aws" if action == 0 else "azure", "step": self.current_step}
        return obs, float(reward), bool(done), False, info

    def render(self):
        pass

    def close(self):
        pass

    def normal_scheduler_step(self, obs) -> int:
        """Cost-greedy baseline (reference parity)."""
        return 0 if obs[0] <= obs[1] else 1


def _step_with_final_obs(params, state, action):
    """Same-step autoreset that ALSO returns the terminal observation
    (shared autoreset logic from ``bundle.make_autoreset``)."""
    from rl_scheduler_tpu.env.bundle import make_autoreset

    fn = make_autoreset(
        lambda key: core.reset(params, key),
        lambda st, a: core.step(params, st, a),
        with_final_obs=True,
    )
    return fn(state, action)


_JIT_VEC_STEP = jax.jit(jax.vmap(_step_with_final_obs, in_axes=(None, 0, 0)))


_VEC_BASE = object if gym is None else gym.vector.VectorEnv


class K8sMultiCloudVectorEnv(_VEC_BASE):
    """Gymnasium ``VectorEnv``-style adapter over the vmapped core.

    N simulated clusters step as ONE jitted XLA program per ``step`` call —
    the Gym-ecosystem face of the same vectorization training uses
    (``env/vector.py``). Follows the same-step autoreset convention: when
    env i terminates, ``obs[i]`` is already the next episode's first
    observation and the finishing observation is in
    ``infos["final_obs"][i]`` (with ``infos["_final_obs"]`` as the validity
    mask — the Gymnasium 1.x ``AutoresetMode.SAME_STEP`` convention).

    Host-driven stepping pays one device round-trip per call, so this is
    for external Gym tooling (wrappers, eval harnesses) — training should
    use the functional core, which fuses whole rollouts into one program.

    Episode-end semantics: like the single-env adapter, the replay-horizon
    end is a TERMINATION (``terminations[i]=True``; ``truncations`` is
    always all-False), matching the reference env's ``done`` at step 99 and
    the training-side GAE's no-bootstrap treatment of the horizon. External
    value-bootstrapping wrappers that want Gymnasium time-limit semantics
    should wrap with a TimeLimit-style truncation instead.
    """

    def __init__(self, num_envs: int, config: EnvConfig | None = None):
        if gym is None:
            raise ImportError("gymnasium is required for the adapter; use env.core directly")
        from gymnasium.vector.utils import batch_space

        # Declared so Gymnasium wrappers account episodes correctly
        # (without it they assume NEXT_STEP and mis-handle the reset obs).
        self.metadata = {"autoreset_mode": gym.vector.AutoresetMode.SAME_STEP}
        self.num_envs = num_envs
        self.params = core.make_params(config or EnvConfig())
        self.single_action_space = spaces.Discrete(core.NUM_ACTIONS)
        self.single_observation_space = spaces.Box(0.0, 1.0, (core.OBS_DIM,), np.float32)
        self.action_space = batch_space(self.single_action_space, num_envs)
        self.observation_space = batch_space(self.single_observation_space, num_envs)
        self._state = None

    def reset(self, seed: int | None = None, options: dict | None = None):
        from rl_scheduler_tpu.env.vector import reset_batch

        if seed is None:
            seed = int.from_bytes(os.urandom(4), "little")
        self._state, obs = reset_batch(
            self.params, jax.random.PRNGKey(seed), self.num_envs
        )
        return np.asarray(obs), {}

    def step(self, actions):
        actions = np.asarray(actions, np.int32)
        self._state, obs, ts = _JIT_VEC_STEP(self.params, self._state, actions)
        # One batched fetch for everything the Gym API returns (GL008): the
        # per-field np.asarray calls each cost a device round-trip.
        obs, raw, reward, done = jax.device_get(
            (obs, ts.obs, ts.reward, ts.done)
        )
        infos: dict[str, Any] = {}
        if done.any():
            final = np.empty(self.num_envs, dtype=object)
            for i in np.nonzero(done)[0]:
                final[i] = raw[i]
            infos["final_obs"] = final
            infos["_final_obs"] = done.copy()
        return (
            obs,
            reward,
            done,
            np.zeros(self.num_envs, bool),
            infos,
        )

    def close(self):
        pass


if __name__ == "__main__":
    env = K8sMultiCloudEnv(fast_mode=True)
    obs, _ = env.reset(seed=42)
    print("Initial observation:", obs.round(3))
    for i in range(5):
        action = env.action_space.sample()
        obs, reward, done, truncated, info = env.step(action)
        print(
            f"Step {i + 1} | Action: {info['chosen_cloud']:5} | "
            f"Reward: {reward:8.2f} | Next obs: {obs.round(3)}"
        )
        if done:
            break
    print("Environment test completed")
