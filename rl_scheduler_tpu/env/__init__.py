"""Environments: functional core, vectorized stepping, Gymnasium adapter."""

from rl_scheduler_tpu.env.core import (
    EnvParams,
    EnvState,
    TimeStep,
    OBS_DIM,
    NUM_ACTIONS,
    make_params,
    reset,
    step,
)
from rl_scheduler_tpu.env.vector import (
    reset_batch,
    step_autoreset,
    step_autoreset_batch,
)
from rl_scheduler_tpu.env.baselines import (
    cost_greedy_policy,
    round_robin_policy,
    random_policy,
)
from rl_scheduler_tpu.env.bundle import (
    EnvBundle,
    make_autoreset,
    bundle_from_single,
    multi_cloud_bundle,
    single_cluster_bundle,
    cluster_set_bundle,
    cluster_graph_bundle,
)

__all__ = [
    "EnvParams",
    "EnvState",
    "TimeStep",
    "OBS_DIM",
    "NUM_ACTIONS",
    "make_params",
    "reset",
    "step",
    "reset_batch",
    "step_autoreset",
    "step_autoreset_batch",
    "cost_greedy_policy",
    "round_robin_policy",
    "random_policy",
    "EnvBundle",
    "make_autoreset",
    "bundle_from_single",
    "multi_cloud_bundle",
    "single_cluster_bundle",
    "cluster_set_bundle",
    "cluster_graph_bundle",
]
