"""Environments: functional core, vectorized stepping, Gymnasium adapter."""

from rl_scheduler_tpu.env.core import (
    EnvParams,
    EnvState,
    TimeStep,
    OBS_DIM,
    NUM_ACTIONS,
    make_params,
    reset,
    step,
)
from rl_scheduler_tpu.env.vector import (
    reset_batch,
    step_autoreset,
    step_autoreset_batch,
)
from rl_scheduler_tpu.env.baselines import (
    cost_greedy_policy,
    round_robin_policy,
    random_policy,
)

__all__ = [
    "EnvParams",
    "EnvState",
    "TimeStep",
    "OBS_DIM",
    "NUM_ACTIONS",
    "make_params",
    "reset",
    "step",
    "reset_batch",
    "step_autoreset",
    "step_autoreset_batch",
    "cost_greedy_policy",
    "round_robin_policy",
    "random_policy",
]
