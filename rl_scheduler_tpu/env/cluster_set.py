"""Pod/node-set placement simulator (BASELINE config 4).

The flagship multi-cloud env chooses between two *clouds*
(``k8s_multi_cloud_env.py:51``: ``Discrete(2)``); this env generalizes the
decision to a *set of nodes* — the shape a real kube-scheduler faces: one
pod arrives per step and the agent picks which of ``num_nodes`` nodes
hosts it. Built for the permutation-invariant transformer policy
(``models/transformer.py``): the observation is a ``[num_nodes, FEAT]``
set, node order carries no meaning, and the optimal policy is equivariant
under node permutation (golden-tested).

Per-node features (all in [0, 1], fixed column order):
  0 cost        — the node's cloud cost from the replayed pricing table,
                  plus a static per-node premium drawn at reset
  1 latency     — same construction from the latency table
  2 cpu_used    — current utilization; placements add load, completions
                  drain it geometrically each step
  3 cloud_id    — 0 = aws, 1 = azure (first half of nodes are aws)
  4 pod_cpu     — the arriving pod's cpu request (broadcast to all rows)
  5 step_frac   — episode progress (broadcast), so policies can anticipate
                  table drift

Reward for placing on node ``a``:
    -(w_c * cost[a] + w_l * latency[a]
      + overload_penalty * relu(cpu_used'[a] - 1))
i.e. the multi-cloud cost/latency trade-off (reference
``k8s_multi_cloud_env.py:122``) plus a capacity term that makes *set*
state matter: a greedy cheapest-node policy overloads it and loses to
load-aware placement.

Episode length follows the pricing table (99 steps), like the reference.

Scenario extensions (``rl_scheduler_tpu/scenarios/``): every optional
field below defaults to the legacy behavior — ``None``/``False`` leaves
reset/step bit-identical to the pre-scenario env (same RNG draw order,
same values), so the CSV-replay configs and their measured record are
untouched. When set:

- ``table``/``pod_scale``: scenario-compiled cost/latency tables and a
  per-step arrival-intensity multiplier on the pod draw (bursty-diurnal
  and price-spike families).
- ``avail_mask``/``churn_penalty``: a ``[T, N]`` availability mask
  (node-pool churn) — down nodes observe as maximally loaded/expensive
  and placing on one pays ``churn_penalty`` (scaled by ``reward_scale``
  like every other term).
- ``jitter_range``/``drain_range``/``overload_range``/``random_phase``:
  PER-EPISODE domain randomization, drawn from each env's own
  ``jax.random`` key at reset (fully vmappable): the node-premium scale,
  drain rate, overload penalty, and the table-replay phase offset —
  exactly the static quantities the fleet seed-fragility diagnostic
  found argmax latching onto (docs/scaling.md §1b; ROADMAP item 3b).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from rl_scheduler_tpu.data.loader import load_table

NODE_FEAT = 6


class ClusterSetParams(NamedTuple):
    costs: jnp.ndarray       # [T, 2] normalized cloud costs (table replay)
    latencies: jnp.ndarray   # [T, 2]
    cloud_of_node: jnp.ndarray  # [N] int32, 0=aws 1=azure
    cost_weight: jnp.ndarray
    latency_weight: jnp.ndarray
    reward_scale: jnp.ndarray
    overload_penalty: jnp.ndarray
    node_jitter: jnp.ndarray    # scalar: scale of static per-node premiums
    pod_cpu_low: jnp.ndarray
    pod_cpu_high: jnp.ndarray
    drain_rate: jnp.ndarray     # per-step utilization retention in (0,1)
    max_steps: jnp.ndarray      # scalar int32
    # --- scenario fields (None/False = legacy CSV-replay behavior) ---
    pod_scale: jnp.ndarray | None = None     # [T] arrival-intensity mult
    avail_mask: jnp.ndarray | None = None    # [T, N] 1=up (churn family)
    churn_penalty: jnp.ndarray | None = None  # scalar, with avail_mask
    jitter_range: jnp.ndarray | None = None  # [2] per-episode node_jitter
    drain_range: jnp.ndarray | None = None   # [2] per-episode drain_rate
    overload_range: jnp.ndarray | None = None  # [2] per-episode penalty
    random_phase: bool = False               # per-episode table offset

    @property
    def num_nodes(self) -> int:
        return self.cloud_of_node.shape[0]

    @property
    def num_table_rows(self) -> int:
        return self.costs.shape[0]

    @property
    def episode_randomized(self) -> bool:
        """True when reset draws any per-episode scenario randomization
        (static at trace time — params are closed over, never traced)."""
        return (self.jitter_range is not None
                or self.drain_range is not None
                or self.overload_range is not None
                or self.random_phase)


class ClusterSetState(NamedTuple):
    step_idx: jnp.ndarray   # scalar int32
    cpu_used: jnp.ndarray   # [N] f32
    node_premium: jnp.ndarray  # [N, 2] static per-episode (cost, lat) offsets
    pod_cpu: jnp.ndarray    # scalar f32: the pod awaiting placement
    key: jnp.ndarray
    # Per-episode scenario draws — populated by reset() with the params'
    # static values when randomization is off, so the added leaves never
    # change behavior there (step multiplies by the same numbers). No
    # defaults: a hand-built state missing them should fail loudly, not
    # drain to zero.
    phase: jnp.ndarray      # table-replay offset (0 legacy)
    ep_drain: jnp.ndarray   # this episode's drain rate
    ep_overload: jnp.ndarray  # this episode's overload penalty


class TimeStep(NamedTuple):
    obs: jnp.ndarray        # [N, NODE_FEAT]
    reward: jnp.ndarray
    done: jnp.ndarray
    chosen_cloud: jnp.ndarray  # cloud of the chosen node (stats parity)
    step: jnp.ndarray


# The default per-step pod request draw — named so reconstructions of
# the base workload (the trace compiler's anti-forgetting mixture,
# loopback/compile.py) reference the same range instead of restating it.
DEFAULT_POD_CPU_LOW = 0.1
DEFAULT_POD_CPU_HIGH = 0.4


def make_params(
    num_nodes: int = 8,
    cost_weight: float = 0.6,
    latency_weight: float = 0.4,
    reward_scale: float = 100.0,
    overload_penalty: float = 2.0,
    node_jitter: float = 0.1,
    pod_cpu_low: float = DEFAULT_POD_CPU_LOW,
    pod_cpu_high: float = DEFAULT_POD_CPU_HIGH,
    drain_rate: float = 0.85,
    data_path: str | None = None,
    max_steps: int | None = None,
    table=None,
    pod_scale=None,
    avail_mask=None,
    churn_penalty: float | None = None,
    jitter_range: tuple | None = None,
    drain_range: tuple | None = None,
    overload_range: tuple | None = None,
    random_phase: bool = False,
) -> ClusterSetParams:
    """Build params from the shipped CSV (default) or a scenario's
    compiled tables (``table=``, a :class:`~rl_scheduler_tpu.data.loader.
    CloudTable` or anything with ``.costs``/``.latencies``); the scenario
    keyword fields are documented on the module."""
    if table is None:
        table = load_table(data_path)
    t = table.costs.shape[0]
    f32 = lambda x: jnp.asarray(x, jnp.float32)
    opt = lambda x: None if x is None else f32(x)
    if avail_mask is not None and jnp.asarray(avail_mask).shape != (t, num_nodes):
        raise ValueError(
            f"avail_mask shape {jnp.asarray(avail_mask).shape} != "
            f"(table rows, num_nodes) = ({t}, {num_nodes})")
    if pod_scale is not None and jnp.asarray(pod_scale).shape != (t,):
        raise ValueError(
            f"pod_scale shape {jnp.asarray(pod_scale).shape} != ({t},)")
    # First half aws, second half azure (node order is irrelevant to the
    # permutation-invariant policy; tests shuffle it).
    cloud = (jnp.arange(num_nodes) >= num_nodes // 2).astype(jnp.int32)
    return ClusterSetParams(
        costs=f32(table.costs),
        latencies=f32(table.latencies),
        cloud_of_node=cloud,
        cost_weight=f32(cost_weight),
        latency_weight=f32(latency_weight),
        reward_scale=f32(reward_scale),
        overload_penalty=f32(overload_penalty),
        node_jitter=f32(node_jitter),
        pod_cpu_low=f32(pod_cpu_low),
        pod_cpu_high=f32(pod_cpu_high),
        drain_rate=f32(drain_rate),
        max_steps=jnp.asarray(max_steps if max_steps is not None else t - 1, jnp.int32),
        pod_scale=opt(pod_scale),
        avail_mask=opt(avail_mask),
        churn_penalty=(f32(churn_penalty if churn_penalty is not None else 1.0)
                       if avail_mask is not None else None),
        jitter_range=opt(jitter_range),
        drain_range=opt(drain_range),
        overload_range=opt(overload_range),
        random_phase=bool(random_phase),
    )


def _table_row(params: ClusterSetParams, state: ClusterSetState) -> jnp.ndarray:
    """The table row this step replays: the episode's phase offset shifts
    it (mod T). Legacy phase is 0 and ``step_idx < T`` always, so the mod
    is the identity there — values are unchanged."""
    return (state.step_idx + state.phase) % params.num_table_rows


def node_costs_latencies(
    params: ClusterSetParams, state: ClusterSetState
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-node (cost, latency) at the current table row: cloud value +
    static node premium, clipped to [0, 1]."""
    row = _table_row(params, state)
    row_costs = jax.lax.dynamic_index_in_dim(params.costs, row, keepdims=False)
    row_lats = jax.lax.dynamic_index_in_dim(params.latencies, row, keepdims=False)
    cost = row_costs[params.cloud_of_node] + state.node_premium[:, 0]
    lat = row_lats[params.cloud_of_node] + state.node_premium[:, 1]
    return jnp.clip(cost, 0.0, 1.0), jnp.clip(lat, 0.0, 1.0)


def _avail_row(params: ClusterSetParams, state: ClusterSetState) -> jnp.ndarray:
    """``[N]`` availability at the current row (churn family only)."""
    return jax.lax.dynamic_index_in_dim(
        params.avail_mask, _table_row(params, state), keepdims=False)


def _observe(params: ClusterSetParams, state: ClusterSetState) -> jnp.ndarray:
    cost, lat = node_costs_latencies(params, state)
    cpu_used = state.cpu_used
    if params.avail_mask is not None:
        # A down node observes as maximally expensive/slow/loaded — the
        # serving-time shape of a cordoned node, and argmax-repellent
        # without widening the feature space trained checkpoints expect.
        up = _avail_row(params, state) > 0
        cost = jnp.where(up, cost, 1.0)
        lat = jnp.where(up, lat, 1.0)
        cpu_used = jnp.where(up, cpu_used, 1.0)
    n = params.num_nodes
    step_frac = state.step_idx.astype(jnp.float32) / params.max_steps.astype(jnp.float32)
    return jnp.stack(
        [
            cost,
            lat,
            cpu_used,
            params.cloud_of_node.astype(jnp.float32),
            jnp.full((n,), state.pod_cpu),
            jnp.full((n,), step_frac),
        ],
        axis=-1,
    ).astype(jnp.float32)


def _draw_pod(params: ClusterSetParams, key: jnp.ndarray,
              row: jnp.ndarray | None = None) -> jnp.ndarray:
    pod = jax.random.uniform(
        key, (), jnp.float32, minval=params.pod_cpu_low, maxval=params.pod_cpu_high
    )
    if params.pod_scale is not None and row is not None:
        # Arrival intensity: peak-hours pods are bigger (bursty-diurnal
        # family). Same RNG draw either way — the multiplier is a table
        # gather, so legacy streams are untouched.
        pod = jnp.clip(pod * params.pod_scale[row], 0.0, 1.0)
    return pod


def reset(params: ClusterSetParams, key: jnp.ndarray) -> tuple[ClusterSetState, jnp.ndarray]:
    if params.episode_randomized:
        (carry_key, prem_key, pod_key, jit_key, drain_key, over_key,
         phase_key) = jax.random.split(key, 7)
        rng_between = lambda k, rg, default: (
            default if rg is None else jax.random.uniform(
                k, (), jnp.float32, minval=rg[0], maxval=rg[1]))
        jitter = rng_between(jit_key, params.jitter_range, params.node_jitter)
        ep_drain = rng_between(drain_key, params.drain_range, params.drain_rate)
        ep_overload = rng_between(over_key, params.overload_range,
                                  params.overload_penalty)
        phase = (jax.random.randint(phase_key, (), 0, params.num_table_rows,
                                    jnp.int32)
                 if params.random_phase else jnp.zeros((), jnp.int32))
    else:
        # Legacy path: identical split count and draw order, so CSV-replay
        # trajectories (and every measured baseline) stay bit-identical.
        carry_key, prem_key, pod_key = jax.random.split(key, 3)
        jitter = params.node_jitter
        ep_drain = params.drain_rate
        ep_overload = params.overload_penalty
        phase = jnp.zeros((), jnp.int32)
    premium = jitter * jax.random.uniform(
        prem_key, (params.num_nodes, 2), jnp.float32
    )
    state = ClusterSetState(
        step_idx=jnp.zeros((), jnp.int32),
        cpu_used=jnp.zeros(params.num_nodes, jnp.float32),
        node_premium=premium,
        pod_cpu=jnp.zeros(()),  # placeholder; drawn below with the phase row
        key=carry_key,
        phase=phase,
        ep_drain=jnp.asarray(ep_drain, jnp.float32),
        ep_overload=jnp.asarray(ep_overload, jnp.float32),
    )
    state = state._replace(
        pod_cpu=_draw_pod(params, pod_key, _table_row(params, state)))
    return state, _observe(params, state)


def step(
    params: ClusterSetParams, state: ClusterSetState, action: jnp.ndarray
) -> tuple[ClusterSetState, TimeStep]:
    """Place the pending pod on node ``action``; pure, jit/vmap/scan-safe."""
    action = jnp.asarray(action, jnp.int32)
    carry_key, pod_key = jax.random.split(state.key)

    cost, lat = node_costs_latencies(params, state)
    new_cpu = state.cpu_used.at[action].add(state.pod_cpu)
    overload = jnp.maximum(new_cpu[action] - 1.0, 0.0)
    penalty_terms = (
        params.cost_weight * cost[action]
        + params.latency_weight * lat[action]
        + state.ep_overload * overload
    )
    if params.avail_mask is not None:
        # Placing on a down node costs churn_penalty reward units (the
        # eviction + reschedule a real cluster pays). All-ones mask adds
        # exactly 0.0, preserving the no-churn reward bitwise.
        down = 1.0 - _avail_row(params, state)[action]
        penalty_terms = penalty_terms + params.churn_penalty * down
    reward = -params.reward_scale * penalty_terms

    new_step = state.step_idx + 1
    done = new_step >= params.max_steps
    new_state = ClusterSetState(
        step_idx=new_step,
        cpu_used=new_cpu * state.ep_drain,  # completions drain load
        node_premium=state.node_premium,
        pod_cpu=jnp.zeros(()),
        key=carry_key,
        phase=state.phase,
        ep_drain=state.ep_drain,
        ep_overload=state.ep_overload,
    )
    new_state = new_state._replace(
        pod_cpu=_draw_pod(params, pod_key, _table_row(params, new_state)))
    ts = TimeStep(
        obs=_observe(params, new_state),
        reward=reward.astype(jnp.float32),
        done=done,
        chosen_cloud=params.cloud_of_node[action],
        step=new_step,
    )
    return new_state, ts
