"""Pod/node-set placement simulator (BASELINE config 4).

The flagship multi-cloud env chooses between two *clouds*
(``k8s_multi_cloud_env.py:51``: ``Discrete(2)``); this env generalizes the
decision to a *set of nodes* — the shape a real kube-scheduler faces: one
pod arrives per step and the agent picks which of ``num_nodes`` nodes
hosts it. Built for the permutation-invariant transformer policy
(``models/transformer.py``): the observation is a ``[num_nodes, FEAT]``
set, node order carries no meaning, and the optimal policy is equivariant
under node permutation (golden-tested).

Per-node features (all in [0, 1], fixed column order):
  0 cost        — the node's cloud cost from the replayed pricing table,
                  plus a static per-node premium drawn at reset
  1 latency     — same construction from the latency table
  2 cpu_used    — current utilization; placements add load, completions
                  drain it geometrically each step
  3 cloud_id    — 0 = aws, 1 = azure (first half of nodes are aws)
  4 pod_cpu     — the arriving pod's cpu request (broadcast to all rows)
  5 step_frac   — episode progress (broadcast), so policies can anticipate
                  table drift

Reward for placing on node ``a``:
    -(w_c * cost[a] + w_l * latency[a]
      + overload_penalty * relu(cpu_used'[a] - 1))
i.e. the multi-cloud cost/latency trade-off (reference
``k8s_multi_cloud_env.py:122``) plus a capacity term that makes *set*
state matter: a greedy cheapest-node policy overloads it and loses to
load-aware placement.

Episode length follows the pricing table (99 steps), like the reference.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from rl_scheduler_tpu.data.loader import load_table

NODE_FEAT = 6


class ClusterSetParams(NamedTuple):
    costs: jnp.ndarray       # [T, 2] normalized cloud costs (table replay)
    latencies: jnp.ndarray   # [T, 2]
    cloud_of_node: jnp.ndarray  # [N] int32, 0=aws 1=azure
    cost_weight: jnp.ndarray
    latency_weight: jnp.ndarray
    reward_scale: jnp.ndarray
    overload_penalty: jnp.ndarray
    node_jitter: jnp.ndarray    # scalar: scale of static per-node premiums
    pod_cpu_low: jnp.ndarray
    pod_cpu_high: jnp.ndarray
    drain_rate: jnp.ndarray     # per-step utilization retention in (0,1)
    max_steps: jnp.ndarray      # scalar int32

    @property
    def num_nodes(self) -> int:
        return self.cloud_of_node.shape[0]


class ClusterSetState(NamedTuple):
    step_idx: jnp.ndarray   # scalar int32
    cpu_used: jnp.ndarray   # [N] f32
    node_premium: jnp.ndarray  # [N, 2] static per-episode (cost, lat) offsets
    pod_cpu: jnp.ndarray    # scalar f32: the pod awaiting placement
    key: jnp.ndarray


class TimeStep(NamedTuple):
    obs: jnp.ndarray        # [N, NODE_FEAT]
    reward: jnp.ndarray
    done: jnp.ndarray
    chosen_cloud: jnp.ndarray  # cloud of the chosen node (stats parity)
    step: jnp.ndarray


def make_params(
    num_nodes: int = 8,
    cost_weight: float = 0.6,
    latency_weight: float = 0.4,
    reward_scale: float = 100.0,
    overload_penalty: float = 2.0,
    node_jitter: float = 0.1,
    pod_cpu_low: float = 0.1,
    pod_cpu_high: float = 0.4,
    drain_rate: float = 0.85,
    data_path: str | None = None,
    max_steps: int | None = None,
) -> ClusterSetParams:
    table = load_table(data_path)
    t = table.costs.shape[0]
    f32 = lambda x: jnp.asarray(x, jnp.float32)
    # First half aws, second half azure (node order is irrelevant to the
    # permutation-invariant policy; tests shuffle it).
    cloud = (jnp.arange(num_nodes) >= num_nodes // 2).astype(jnp.int32)
    return ClusterSetParams(
        costs=table.costs,
        latencies=table.latencies,
        cloud_of_node=cloud,
        cost_weight=f32(cost_weight),
        latency_weight=f32(latency_weight),
        reward_scale=f32(reward_scale),
        overload_penalty=f32(overload_penalty),
        node_jitter=f32(node_jitter),
        pod_cpu_low=f32(pod_cpu_low),
        pod_cpu_high=f32(pod_cpu_high),
        drain_rate=f32(drain_rate),
        max_steps=jnp.asarray(max_steps if max_steps is not None else t - 1, jnp.int32),
    )


def node_costs_latencies(
    params: ClusterSetParams, state: ClusterSetState
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-node (cost, latency) at the current table row: cloud value +
    static node premium, clipped to [0, 1]."""
    row_costs = jax.lax.dynamic_index_in_dim(params.costs, state.step_idx, keepdims=False)
    row_lats = jax.lax.dynamic_index_in_dim(params.latencies, state.step_idx, keepdims=False)
    cost = row_costs[params.cloud_of_node] + state.node_premium[:, 0]
    lat = row_lats[params.cloud_of_node] + state.node_premium[:, 1]
    return jnp.clip(cost, 0.0, 1.0), jnp.clip(lat, 0.0, 1.0)


def _observe(params: ClusterSetParams, state: ClusterSetState) -> jnp.ndarray:
    cost, lat = node_costs_latencies(params, state)
    n = params.num_nodes
    step_frac = state.step_idx.astype(jnp.float32) / params.max_steps.astype(jnp.float32)
    return jnp.stack(
        [
            cost,
            lat,
            state.cpu_used,
            params.cloud_of_node.astype(jnp.float32),
            jnp.full((n,), state.pod_cpu),
            jnp.full((n,), step_frac),
        ],
        axis=-1,
    ).astype(jnp.float32)


def _draw_pod(params: ClusterSetParams, key: jnp.ndarray) -> jnp.ndarray:
    return jax.random.uniform(
        key, (), jnp.float32, minval=params.pod_cpu_low, maxval=params.pod_cpu_high
    )


def reset(params: ClusterSetParams, key: jnp.ndarray) -> tuple[ClusterSetState, jnp.ndarray]:
    carry_key, prem_key, pod_key = jax.random.split(key, 3)
    premium = params.node_jitter * jax.random.uniform(
        prem_key, (params.num_nodes, 2), jnp.float32
    )
    state = ClusterSetState(
        step_idx=jnp.zeros((), jnp.int32),
        cpu_used=jnp.zeros(params.num_nodes, jnp.float32),
        node_premium=premium,
        pod_cpu=_draw_pod(params, pod_key),
        key=carry_key,
    )
    return state, _observe(params, state)


def step(
    params: ClusterSetParams, state: ClusterSetState, action: jnp.ndarray
) -> tuple[ClusterSetState, TimeStep]:
    """Place the pending pod on node ``action``; pure, jit/vmap/scan-safe."""
    action = jnp.asarray(action, jnp.int32)
    carry_key, pod_key = jax.random.split(state.key)

    cost, lat = node_costs_latencies(params, state)
    new_cpu = state.cpu_used.at[action].add(state.pod_cpu)
    overload = jnp.maximum(new_cpu[action] - 1.0, 0.0)
    reward = -params.reward_scale * (
        params.cost_weight * cost[action]
        + params.latency_weight * lat[action]
        + params.overload_penalty * overload
    )

    new_step = state.step_idx + 1
    done = new_step >= params.max_steps
    new_state = ClusterSetState(
        step_idx=new_step,
        cpu_used=new_cpu * params.drain_rate,  # completions drain load
        node_premium=state.node_premium,
        pod_cpu=_draw_pod(params, pod_key),
        key=carry_key,
    )
    ts = TimeStep(
        obs=_observe(params, new_state),
        reward=reward.astype(jnp.float32),
        done=done,
        chosen_cloud=params.cloud_of_node[action],
        step=new_step,
    )
    return new_state, ts
