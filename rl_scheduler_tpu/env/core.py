"""Pure-functional multi-cloud scheduling environment.

TPU-first re-design of the reference simulator
(``rl_scheduler/env/k8s_multi_cloud_env.py:36-157``): the mutable Gymnasium
class holding a pandas DataFrame becomes a pair of pure functions over
explicit state, so ``jax.vmap`` steps thousands of simulated clusters in one
fused XLA program and ``lax.scan`` fuses whole rollouts into the training
step. The per-step ``DataFrame.iloc`` row access becomes an O(1) device
gather; the process-global ``random.seed`` in ``reset`` (reference ``:109-111``,
racy and irreproducible across parallel envs) becomes a per-env
``jax.random`` key threaded through the state pytree.

Semantics parity (golden-tested against the reference formulas):
- observation: ``[cost_aws, cost_azure, lat_aws, lat_azure, cpu_aws,
  cpu_azure]`` — table row at the current step plus two uniform(0.1, 0.8)
  CPU draws (the reference's ``_get_live_cpu``, ``:84-88``, is random noise in
  all modes).
- action: 0 = AWS, 1 = Azure.
- reward: ``sign * scale * (w_c*cost_chosen + w_l*lat_chosen)``; the
  reference uses sign=+1 (its documented intent is -1, SURVEY.md §7.0.1);
  both are supported via config.
- episode: done when step reaches ``max_steps = T - 1`` (reference ``:66,140``).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from rl_scheduler_tpu.config import EnvConfig
from rl_scheduler_tpu.data.loader import CloudTable, load_table
from rl_scheduler_tpu.ops.indexing import select_along_last

OBS_DIM = 6
NUM_ACTIONS = 2


class EnvParams(NamedTuple):
    """Static environment parameters (shared across all vmapped envs)."""

    costs: jnp.ndarray       # [T, C] normalized cost per cloud
    latencies: jnp.ndarray   # [T, C] normalized latency per cloud
    cost_weight: jnp.ndarray
    latency_weight: jnp.ndarray
    reward_scale: jnp.ndarray
    reward_sign: jnp.ndarray  # +1 legacy (reference parity), -1 corrected
    cpu_low: jnp.ndarray
    cpu_high: jnp.ndarray
    max_steps: jnp.ndarray    # scalar int32, == T - 1 by default
    fault_prob: jnp.ndarray
    fault_latency_penalty: jnp.ndarray

    @property
    def num_table_steps(self) -> int:
        return self.costs.shape[0]


class EnvState(NamedTuple):
    """Per-env mutable state: a step index and an RNG key."""

    step_idx: jnp.ndarray  # scalar int32 in [0, max_steps]
    key: jnp.ndarray       # jax PRNG key


class TimeStep(NamedTuple):
    """Result of one env transition (arrays, vmap-friendly)."""

    obs: jnp.ndarray      # [OBS_DIM]
    reward: jnp.ndarray   # scalar f32
    done: jnp.ndarray     # scalar bool
    chosen_cloud: jnp.ndarray  # scalar int32 (the action taken)
    step: jnp.ndarray     # scalar int32 (post-increment, reference info["step"])


def make_params(
    config: EnvConfig | None = None,
    table: CloudTable | None = None,
) -> EnvParams:
    """Build :class:`EnvParams` from a config and a (possibly custom) table."""
    config = config or EnvConfig()
    if table is None:
        table = load_table(config.data_path)
    t = table.costs.shape[0]
    max_steps = config.max_steps if config.max_steps is not None else t - 1
    if not 0 < max_steps <= t - 1:
        raise ValueError(f"max_steps must be in (0, {t - 1}], got {max_steps}")
    f32 = lambda x: jnp.asarray(x, jnp.float32)
    return EnvParams(
        costs=table.costs,
        latencies=table.latencies,
        cost_weight=f32(config.cost_weight),
        latency_weight=f32(config.latency_weight),
        reward_scale=f32(config.reward_scale),
        reward_sign=f32(1.0 if config.legacy_reward_sign else -1.0),
        cpu_low=f32(config.cpu_low),
        cpu_high=f32(config.cpu_high),
        max_steps=jnp.asarray(max_steps, jnp.int32),
        fault_prob=f32(config.fault_prob),
        fault_latency_penalty=f32(config.fault_latency_penalty),
    )


def _observe(params: EnvParams, step_idx: jnp.ndarray, key: jnp.ndarray) -> jnp.ndarray:
    """Observation at ``step_idx``: table row gather + fresh CPU noise."""
    row_costs = jax.lax.dynamic_index_in_dim(params.costs, step_idx, keepdims=False)
    row_lats = jax.lax.dynamic_index_in_dim(params.latencies, step_idx, keepdims=False)
    cpu = jax.random.uniform(
        key, (2,), jnp.float32, minval=params.cpu_low, maxval=params.cpu_high
    )
    return jnp.concatenate([row_costs, row_lats, cpu]).astype(jnp.float32)


def reset(params: EnvParams, key: jnp.ndarray) -> tuple[EnvState, jnp.ndarray]:
    """Start a new episode at table row 0."""
    carry_key, obs_key = jax.random.split(key)
    step_idx = jnp.zeros((), jnp.int32)
    state = EnvState(step_idx=step_idx, key=carry_key)
    return state, _observe(params, step_idx, obs_key)


def reset_random_start(
    params: EnvParams, key: jnp.ndarray
) -> tuple[EnvState, jnp.ndarray]:
    """Scenario-layer reset: start at a uniformly random table row — the
    per-episode phase randomization of :mod:`rl_scheduler_tpu.scenarios`
    (policies cannot latch onto absolute row positions). A SEPARATE
    function, not a params flag: the choice is made at bundle build time
    (``env/bundle.multi_cloud_bundle(random_start=True)``), so the
    legacy reset keeps its exact split count and draw order and the
    params pytree stays all-array (a flag leaf would trace under
    vmap/jit)."""
    carry_key, obs_key, start_key = jax.random.split(key, 3)
    step_idx = jax.random.randint(
        start_key, (), 0, params.max_steps, jnp.int32)
    state = EnvState(step_idx=step_idx, key=carry_key)
    return state, _observe(params, step_idx, obs_key)


def step(
    params: EnvParams, state: EnvState, action: jnp.ndarray
) -> tuple[EnvState, TimeStep]:
    """One transition. Pure; jit/vmap/scan-safe.

    Reward is computed from the row the agent *observed* (the pre-increment
    index), exactly like the reference (``k8s_multi_cloud_env.py:118-122``).
    """
    action = jnp.asarray(action, jnp.int32)
    carry_key, obs_key, fault_key = jax.random.split(state.key, 3)

    row_costs = jax.lax.dynamic_index_in_dim(params.costs, state.step_idx, keepdims=False)
    row_lats = jax.lax.dynamic_index_in_dim(params.latencies, state.step_idx, keepdims=False)
    cost = row_costs[action]
    latency = row_lats[action]

    # Optional fault injection: with prob fault_prob the chosen cloud is
    # unavailable this step and serves at the penalty latency.
    faulted = jax.random.bernoulli(fault_key, params.fault_prob)
    latency = jnp.where(faulted, params.fault_latency_penalty, latency)

    reward = params.reward_sign * params.reward_scale * (
        params.cost_weight * cost + params.latency_weight * latency
    )

    new_step = state.step_idx + 1
    done = new_step >= params.max_steps
    new_state = EnvState(step_idx=new_step, key=carry_key)
    obs = _observe(params, new_step, obs_key)
    ts = TimeStep(
        obs=obs,
        reward=reward.astype(jnp.float32),
        done=done,
        chosen_cloud=action,
        step=new_step,
    )
    return new_state, ts


# ---------------------------------------------------------------------------
# Open-loop horizon: the TPU-native fast path for this env.
#
# The env is OPEN-LOOP — actions never influence transitions (the reference
# replays a CSV row per step regardless of placement,
# ``k8s_multi_cloud_env.py:115-144``); only the reward depends on the
# action. So a T-step rollout needs no sequential scan at all: step indices
# advance deterministically modulo ``max_steps`` (auto-reset included), all
# T+1 observations and all reward ingredients are computable upfront as a
# few large batched ops (table gathers + one batched RNG draw), and the
# policy can run as ONE ``[T+1·N]`` forward — a single MXU-friendly matmul
# batch instead of T tiny ones. Measured on one TPU chip at 4096 envs x 100
# steps this halves rollout time vs the ``lax.scan`` path.
#
# RNG streams differ from the scan path (one batched draw vs per-step
# splits), so trajectories are distributionally identical but not bitwise
# equal; both paths stay available (``PPOTrainConfig.rollout_impl``).
# ---------------------------------------------------------------------------


def open_loop_horizon(
    params: EnvParams,
    state: EnvState,
    cur_obs: jnp.ndarray,
    key: jnp.ndarray,
    num_steps: int,
) -> tuple[jnp.ndarray, dict, EnvState]:
    """Everything a T-step rollout needs, computed without stepping.

    ``state`` is a batched :class:`EnvState` (``step_idx [N]``, per-env
    keys); ``cur_obs [N, OBS_DIM]`` is the observation the caller already
    holds for t=0 (carried through exactly — it is NOT re-drawn).

    Returns ``(obs [T+1, N, OBS_DIM], aux, new_state)`` where ``obs[t]`` is
    the observation at step t (``obs[T]`` bootstraps the value target) and
    ``aux`` feeds :func:`open_loop_rewards` once actions are known.
    """
    t = num_steps
    ms = params.max_steps
    # Observed table index at step t: auto-reset wraps step_idx to 0 when
    # it reaches max_steps, so the sequence is (s0 + t) mod max_steps.
    idx = (
        state.step_idx[None, :] + jnp.arange(t + 1, dtype=jnp.int32)[:, None]
    ) % ms  # [T+1, N]
    rows_c = params.costs[idx]       # [T+1, N, C]
    rows_l = params.latencies[idx]
    cpu_key, fault_key = jax.random.split(key)
    cpu = jax.random.uniform(
        cpu_key, (t + 1, *idx.shape[1:], 2), jnp.float32,
        minval=params.cpu_low, maxval=params.cpu_high,
    )
    obs = jnp.concatenate([rows_c, rows_l, cpu], axis=-1).astype(jnp.float32)
    obs = obs.at[0].set(cur_obs)
    faulted = jax.random.bernoulli(fault_key, params.fault_prob, idx[:t].shape)
    dones = (idx[:t] == ms - 1).astype(jnp.float32)
    # Advance per-env keys once so a later scan-path step sees fresh streams.
    new_keys = jax.vmap(lambda k: jax.random.split(k)[0])(state.key)
    new_state = EnvState(step_idx=idx[t], key=new_keys)
    aux = {
        "rows_costs": rows_c[:t],
        "rows_lats": rows_l[:t],
        "faulted": faulted,
        "dones": dones,
    }
    return obs, aux, new_state


def open_loop_rewards(params: EnvParams, aux: dict, actions: jnp.ndarray) -> jnp.ndarray:
    """Rewards for a horizon once actions are chosen (same formula as
    :func:`step`, vectorized over ``[T, N]``).

    Picks the chosen cloud's column via a one-hot contraction rather than
    ``take_along_axis`` (see :mod:`rl_scheduler_tpu.ops.indexing`).
    """
    cost = select_along_last(aux["rows_costs"], actions)
    latency = select_along_last(aux["rows_lats"], actions)
    latency = jnp.where(aux["faulted"], params.fault_latency_penalty, latency)
    reward = params.reward_sign * params.reward_scale * (
        params.cost_weight * cost + params.latency_weight * latency
    )
    return reward.astype(jnp.float32)
