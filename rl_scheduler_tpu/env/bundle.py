"""Uniform env interface so agents are generic over simulators.

The reference binds its agents to one Gymnasium class by construction
(``agent/train_ppo.py:11`` instantiates ``K8sMultiCloudEnv`` directly).
Here every simulator — multi-cloud table replay, single-cluster
autoscaler, pod/node set, cluster graph — exports the same two batched
pure functions, so PPO/DQN compose with any of them inside one jitted
program:

    reset_batch(key, num_envs)      -> (state, obs)
    step_batch(state, action)       -> (state, TimeStep)   # auto-resetting

Auto-reset is implemented once, generically, for any env whose state
pytree carries a ``key`` field (every env here does — per-env PRNG keys
replace the reference's process-global ``random.seed``, SURVEY.md §5.2).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp


class EnvBundle(NamedTuple):
    """Batched env API plus the static facts agents need to build networks.

    ``obs_shape`` is the per-env observation shape (``(6,)`` for the
    multi-cloud env; structured envs may use higher-rank shapes).
    """

    reset_batch: Callable[[jnp.ndarray, int], tuple[Any, jnp.ndarray]]
    step_batch: Callable[[Any, jnp.ndarray], tuple[Any, Any]]
    obs_shape: tuple
    num_actions: int
    name: str = "env"
    # Optional open-loop fast path (envs whose transitions are
    # action-independent, e.g. table replay): ``horizon_fn(state, cur_obs,
    # key, T) -> (obs [T+1, N, ...], aux, new_state)`` and
    # ``horizon_reward_fn(aux, actions [T, N]) -> rewards [T, N]``. Lets
    # trainers replace the sequential rollout scan with a few large batched
    # ops (see ``env/core.py::open_loop_horizon``). Contract: ``aux`` is
    # otherwise opaque to trainers EXCEPT that it MUST carry
    # ``aux["dones"]`` as a float32 ``[T, N]`` array (1.0 at episode-end
    # steps); set BOTH fns or neither.
    horizon_fn: Callable | None = None
    horizon_reward_fn: Callable | None = None
    # Fixed episode length (steps until done), when the env has one — every
    # env family here replays a finite table/trace, so all do. Lets generic
    # harnesses (in-training greedy evaluation) size a scan so each batch
    # lane completes exactly one episode.
    episode_steps: int | None = None


def make_autoreset(
    reset_fn: Callable, step_fn: Callable, with_final_obs: bool = False
) -> Callable:
    """Lift single-env ``(reset, step)`` into an auto-resetting step.

    The returned TimeStep carries the terminal reward/done of the finishing
    episode while obs/state roll into the next episode — the contract
    scan-collected rollouts need (Gymnasium episode semantics, reference
    ``k8s_multi_cloud_env.py:139-141``, without host round-trips).

    With ``with_final_obs=True`` the step returns ``(state, out_obs,
    raw_timestep)`` instead, where ``raw_timestep.obs`` is the finishing
    episode's terminal observation (discarded otherwise) — the Gymnasium
    vector same-step convention needs it for ``infos["final_obs"]``.
    """

    def step_autoreset(state, action):
        new_state, ts = step_fn(state, action)
        reset_key, carry_key = jax.random.split(new_state.key)
        reset_state, reset_obs = reset_fn(reset_key)
        reset_state = reset_state._replace(key=carry_key)
        out_state = jax.tree.map(
            lambda r, n: jnp.where(ts.done, r, n), reset_state, new_state
        )
        out_obs = jnp.where(ts.done, reset_obs, ts.obs)
        if with_final_obs:
            return out_state, out_obs, ts
        return out_state, ts._replace(obs=out_obs)

    return step_autoreset


def bundle_from_single(
    reset_fn: Callable,
    step_fn: Callable,
    obs_shape: tuple,
    num_actions: int,
    name: str = "env",
    episode_steps: int | None = None,
) -> EnvBundle:
    """Build an :class:`EnvBundle` from single-env pure functions."""
    step_autoreset = make_autoreset(reset_fn, step_fn)
    step_batch = jax.vmap(step_autoreset, in_axes=(0, 0))

    def reset_batch(key, num_envs):
        keys = jax.random.split(key, num_envs)
        return jax.vmap(reset_fn)(keys)

    return EnvBundle(
        reset_batch=reset_batch,
        step_batch=step_batch,
        obs_shape=obs_shape,
        num_actions=num_actions,
        name=name,
        episode_steps=episode_steps,
    )


def multi_cloud_bundle(params=None, random_start: bool = False) -> EnvBundle:
    """The flagship multi-cloud placement env as a bundle (reuses the
    batched steppers from :mod:`rl_scheduler_tpu.env.vector`).

    ``random_start`` (scenario layer, docs/scenarios.md): every episode —
    initial AND auto-reset — begins at a uniformly random table row
    (``core.reset_random_start``). The open-loop horizon fast path is
    withheld then (its auto-reset wraps deterministically to row 0, which
    would diverge from the randomized resets), so ``rollout_impl='auto'``
    falls back to the scan rollout.
    """
    from rl_scheduler_tpu.env import core, vector

    if params is None:
        params = core.make_params()
    if random_start:
        reset_fn = lambda key: core.reset_random_start(params, key)
        return bundle_from_single(
            reset_fn,
            lambda state, action: core.step(params, state, action),
            obs_shape=(core.OBS_DIM,),
            num_actions=core.NUM_ACTIONS,
            name="multi_cloud",
            episode_steps=int(params.max_steps),
        )
    return EnvBundle(
        reset_batch=lambda key, n: vector.reset_batch(params, key, n),
        step_batch=lambda state, action: vector.step_autoreset_batch(
            params, state, action
        ),
        obs_shape=(core.OBS_DIM,),
        num_actions=core.NUM_ACTIONS,
        name="multi_cloud",
        episode_steps=int(params.max_steps),
        horizon_fn=lambda state, cur_obs, key, t: core.open_loop_horizon(
            params, state, cur_obs, key, t
        ),
        horizon_reward_fn=lambda aux, actions: core.open_loop_rewards(
            params, aux, actions
        ),
    )


def single_cluster_bundle(params=None) -> EnvBundle:
    """The single-cluster autoscaling env (BASELINE config 1) as a bundle."""
    from rl_scheduler_tpu.env import single_cluster as sc

    if params is None:
        params = sc.make_params()
    return bundle_from_single(
        lambda key: sc.reset(params, key),
        lambda state, action: sc.step(params, state, action),
        obs_shape=(sc.OBS_DIM,),
        num_actions=sc.NUM_ACTIONS,
        name="single_cluster",
        episode_steps=int(params.max_steps),
    )


def cluster_set_bundle(params=None) -> EnvBundle:
    """The pod/node-set placement env (BASELINE config 4) as a bundle.

    ``obs_shape`` is rank-2: ``(num_nodes, NODE_FEAT)`` — consumed by the
    permutation-invariant set transformer.
    """
    from rl_scheduler_tpu.env import cluster_set as cs

    if params is None:
        params = cs.make_params()
    return bundle_from_single(
        lambda key: cs.reset(params, key),
        lambda state, action: cs.step(params, state, action),
        obs_shape=(params.num_nodes, cs.NODE_FEAT),
        num_actions=params.num_nodes,
        name="cluster_set",
        episode_steps=int(params.max_steps),
    )


def cluster_graph_bundle(params=None) -> EnvBundle:
    """The cluster-topology graph env (BASELINE config 5) as a bundle."""
    from rl_scheduler_tpu.env import cluster_graph as cg

    if params is None:
        params = cg.make_params()
    return bundle_from_single(
        lambda key: cg.reset(params, key),
        lambda state, action: cg.step(params, state, action),
        obs_shape=(params.num_nodes, cg.NODE_FEAT),
        num_actions=params.num_nodes,
        name="cluster_graph",
        episode_steps=int(params.max_steps),
    )
