"""Vectorized env stepping with per-env auto-reset.

The reference scales rollouts with Ray actor processes (6 workers x 4 envs,
``train_final.py:9``); here a batch of envs is a batch *axis*: ``vmap`` over
the :class:`EnvState` pytree steps N simulated clusters as one XLA program.
Auto-reset reproduces Gymnasium episode semantics (done at step ``T-1``
restarts from row 0) as a ``jnp.where`` select, so rollouts scan without
host round-trips.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from rl_scheduler_tpu.env.bundle import make_autoreset
from rl_scheduler_tpu.env.core import EnvParams, EnvState, TimeStep, reset, step


def reset_batch(params: EnvParams, key: jnp.ndarray, num_envs: int):
    """Reset ``num_envs`` independent envs from one key."""
    keys = jax.random.split(key, num_envs)
    return jax.vmap(reset, in_axes=(None, 0))(params, keys)


def step_autoreset(
    params: EnvParams, state: EnvState, action: jnp.ndarray
) -> tuple[EnvState, TimeStep]:
    """Single-env step that restarts the episode when it terminates.

    The returned ``TimeStep`` carries the terminal reward/done of the
    finishing episode, while ``obs``/state roll into the next episode when
    done — the auto-reset contract (implemented once in
    :func:`rl_scheduler_tpu.env.bundle.make_autoreset`).
    """
    fn = make_autoreset(
        lambda key: reset(params, key), lambda st, a: step(params, st, a)
    )
    return fn(state, action)


step_autoreset_batch = jax.vmap(step_autoreset, in_axes=(None, 0, 0))


def rollout_from(
    params: EnvParams,
    state: EnvState,
    obs: jnp.ndarray,
    key: jnp.ndarray,
    policy_fn,
    num_steps: int,
):
    """Scan a batched rollout starting from ``(state, obs)``.

    Returns ``(final_state, final_obs, final_key, traj)`` where ``traj`` is a
    dict of ``[num_steps, N, ...]`` arrays: obs (seen by the policy), action,
    reward, done, next_obs.
    """

    def body(carry, _):
        st, ob, k = carry
        k, act_key = jax.random.split(k)
        action = policy_fn(ob, act_key)
        st, ts = step_autoreset_batch(params, st, action)
        out = {
            "obs": ob,
            "action": ts.chosen_cloud,
            "reward": ts.reward,
            "done": ts.done,
            "next_obs": ts.obs,
        }
        return (st, ts.obs, k), out

    (state, obs, key), traj = jax.lax.scan(body, (state, obs, key), None, length=num_steps)
    return state, obs, key, traj
