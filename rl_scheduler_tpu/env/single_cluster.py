"""Single-cluster autoscaling simulator (BASELINE config 1).

The reference's BASELINE.json names a first config driven by the Locust
load-test export ``data/local_aws_load_stats.csv``: a single simulated
cluster under a replayed load trace. The reference repo itself only ships
the raw CSVs (its env ignores them; see SURVEY.md §2 #11-12) — this module
makes the config real, in the same pure-functional style as
:mod:`rl_scheduler_tpu.env.core` so it jit/vmap/scan-composes with the same
agents.

Dynamics: the agent controls the replica count of a deployment serving the
replayed load (users, req/s, response time per step — the columns of a
Locust ``*_stats_history.csv``). Observation is ``[users, rps,
resp_time, replicas/max_replicas]`` (all in [0,1]); actions are
``{0: scale down, 1: hold, 2: scale up}``. Reward penalizes replica cost
plus effective latency, where latency inflates when offered load exceeds
provisioned capacity — the standard autoscaling trade-off:

    capacity   = replicas / max_replicas
    overload   = relu(load - capacity)
    eff_lat    = resp_time + overload_penalty * overload
    reward     = -(w_cost * capacity + w_lat * eff_lat)
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from rl_scheduler_tpu.config import SingleClusterConfig
from rl_scheduler_tpu.data.loader import load_single_cluster_trace

OBS_DIM = 4
NUM_ACTIONS = 3  # scale down / hold / scale up


class SingleClusterParams(NamedTuple):
    trace: jnp.ndarray        # [T, 3] normalized (users, rps, resp_time)
    max_replicas: jnp.ndarray  # scalar int32
    cost_weight: jnp.ndarray
    latency_weight: jnp.ndarray
    overload_penalty: jnp.ndarray
    max_steps: jnp.ndarray    # scalar int32

    @property
    def num_table_steps(self) -> int:
        return self.trace.shape[0]


class SingleClusterState(NamedTuple):
    step_idx: jnp.ndarray  # scalar int32
    replicas: jnp.ndarray  # scalar int32 in [1, max_replicas]
    key: jnp.ndarray


class TimeStep(NamedTuple):
    obs: jnp.ndarray
    reward: jnp.ndarray
    done: jnp.ndarray
    chosen_cloud: jnp.ndarray  # here: post-action replica count (kept for API symmetry)
    step: jnp.ndarray


def make_params(
    config: SingleClusterConfig | None = None,
    trace: jnp.ndarray | None = None,
) -> SingleClusterParams:
    config = config or SingleClusterConfig()
    if trace is None:
        trace = load_single_cluster_trace(config.trace_path)
    t = trace.shape[0]
    max_steps = config.max_steps if config.max_steps is not None else t - 1
    if not 0 < max_steps <= t - 1:
        raise ValueError(f"max_steps must be in (0, {t - 1}], got {max_steps}")
    f32 = lambda x: jnp.asarray(x, jnp.float32)
    return SingleClusterParams(
        trace=jnp.asarray(trace, jnp.float32),
        max_replicas=jnp.asarray(config.max_replicas, jnp.int32),
        cost_weight=f32(config.replica_cost_weight),
        latency_weight=f32(config.latency_weight),
        overload_penalty=f32(config.overload_penalty),
        max_steps=jnp.asarray(max_steps, jnp.int32),
    )


def _observe(
    params: SingleClusterParams, step_idx: jnp.ndarray, replicas: jnp.ndarray
) -> jnp.ndarray:
    row = jax.lax.dynamic_index_in_dim(params.trace, step_idx, keepdims=False)
    frac = replicas.astype(jnp.float32) / params.max_replicas.astype(jnp.float32)
    return jnp.concatenate([row, frac[None]]).astype(jnp.float32)


def reset(
    params: SingleClusterParams, key: jnp.ndarray
) -> tuple[SingleClusterState, jnp.ndarray]:
    """Start at trace row 0 with half the replica budget provisioned."""
    step_idx = jnp.zeros((), jnp.int32)
    replicas = jnp.maximum(params.max_replicas // 2, 1)
    state = SingleClusterState(step_idx=step_idx, replicas=replicas, key=key)
    return state, _observe(params, step_idx, replicas)


def step(
    params: SingleClusterParams, state: SingleClusterState, action: jnp.ndarray
) -> tuple[SingleClusterState, TimeStep]:
    """One autoscaling decision. Pure; jit/vmap/scan-safe.

    Like the multi-cloud core, reward is computed against the row the agent
    *observed* (pre-increment index).
    """
    action = jnp.asarray(action, jnp.int32)
    delta = action - 1  # {0,1,2} -> {-1,0,+1}
    replicas = jnp.clip(state.replicas + delta, 1, params.max_replicas)

    row = jax.lax.dynamic_index_in_dim(params.trace, state.step_idx, keepdims=False)
    load = row[0]          # normalized user count
    resp_time = row[2]     # normalized response time
    capacity = replicas.astype(jnp.float32) / params.max_replicas.astype(jnp.float32)
    overload = jnp.maximum(load - capacity, 0.0)
    eff_latency = resp_time + params.overload_penalty * overload
    reward = -(params.cost_weight * capacity + params.latency_weight * eff_latency)

    new_step = state.step_idx + 1
    done = new_step >= params.max_steps
    new_state = SingleClusterState(step_idx=new_step, replicas=replicas, key=state.key)
    ts = TimeStep(
        obs=_observe(params, new_step, replicas),
        reward=reward.astype(jnp.float32),
        done=done,
        chosen_cloud=replicas,
        step=new_step,
    )
    return new_state, ts
