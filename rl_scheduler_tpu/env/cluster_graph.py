"""Cluster-topology graph simulator with real-dollar cost reward
(BASELINE config 5).

The set env (:mod:`cluster_set`) treats nodes as an unordered pool; here
the cluster has *topology*: nodes are vertices of a two-cloud network
graph and placement quality depends on where a pod lands **relative to the
service it talks to**. Built for the GNN policy (``models/gnn.py``), whose
message passing runs over the same adjacency the env scores with.

Topology (static, built host-side at ``make_params``):
- ``num_nodes`` vertices, first half aws, second half azure (parity with
  the two kind clusters, reference ``aws/azure-cluster-config.yaml``).
- Intra-cloud: ring + chords (each node links to its cloud's gateway) —
  1-hop cost is low.
- Cross-cloud: a single gateway-to-gateway link — inter-cloud traffic
  pays extra hops, like NodePort hairpins between kind clusters.
- ``hops[i, j]`` = shortest-path hop count (BFS at build time).

Each step, a pod arrives with a cpu request and an *affinity* to a random
existing node (the service it calls). Placing it on node ``a`` costs real
dollars plus a locality penalty:

    price_$    = raw hourly price of a's cloud (real_prices.csv replay)
    locality   = hop_latency * hops[a, affinity]
    overload   = relu(cpu_used'[a] - 1)
    reward     = -(price_scale * price_$ + latency_weight * locality
                   + overload_penalty * overload)

The optimal policy must read the *graph* (place near the affinity node
unless its neighborhood is saturated or its cloud is expensive) — exactly
the inductive bias message passing provides.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np
import jax
import jax.numpy as jnp

from rl_scheduler_tpu.data.loader import load_raw_prices

NODE_FEAT = 7


class ClusterGraphParams(NamedTuple):
    prices: jnp.ndarray        # [T, 2] raw $/hr per cloud
    cloud_of_node: jnp.ndarray  # [N] int32
    adjacency: jnp.ndarray     # [N, N] f32 (0/1, no self loops)
    hops: jnp.ndarray          # [N, N] f32 shortest-path hop counts
    price_scale: jnp.ndarray   # dollars -> reward units
    latency_weight: jnp.ndarray
    hop_latency: jnp.ndarray
    overload_penalty: jnp.ndarray
    pod_cpu_low: jnp.ndarray
    pod_cpu_high: jnp.ndarray
    drain_rate: jnp.ndarray
    max_steps: jnp.ndarray

    @property
    def num_nodes(self) -> int:
        return self.cloud_of_node.shape[0]


class ClusterGraphState(NamedTuple):
    step_idx: jnp.ndarray
    cpu_used: jnp.ndarray      # [N]
    affinity: jnp.ndarray      # scalar int32: node the pod talks to
    pod_cpu: jnp.ndarray       # scalar f32
    key: jnp.ndarray


class TimeStep(NamedTuple):
    obs: jnp.ndarray           # [N, NODE_FEAT]
    reward: jnp.ndarray
    done: jnp.ndarray
    chosen_cloud: jnp.ndarray
    step: jnp.ndarray


def build_topology(num_nodes: int) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(cloud_of_node, adjacency, hops) for the two-cloud gateway graph."""
    if num_nodes < 4:
        raise ValueError("graph env needs >= 4 nodes (2 per cloud)")
    half = num_nodes // 2
    cloud = (np.arange(num_nodes) >= half).astype(np.int32)
    adj = np.zeros((num_nodes, num_nodes), np.float32)
    for lo, hi in ((0, half), (half, num_nodes)):
        members = list(range(lo, hi))
        gateway = members[0]
        for i, u in enumerate(members):
            v = members[(i + 1) % len(members)]  # ring
            if u != v:
                adj[u, v] = adj[v, u] = 1.0
            if u != gateway:                      # chord to gateway
                adj[u, gateway] = adj[gateway, u] = 1.0
    adj[0, half] = adj[half, 0] = 1.0             # gateway <-> gateway
    # BFS all-pairs hop counts (tiny N; host-side, once).
    hops = np.full((num_nodes, num_nodes), np.inf, np.float32)
    for s in range(num_nodes):
        hops[s, s] = 0.0
        frontier = [s]
        d = 0
        while frontier:
            d += 1
            nxt = []
            for u in frontier:
                for v in np.nonzero(adj[u])[0]:
                    if hops[s, v] == np.inf:
                        hops[s, v] = d
                        nxt.append(v)
            frontier = nxt
    if np.isinf(hops).any():
        raise AssertionError("topology is disconnected")
    return cloud, adj, hops


def make_params(
    num_nodes: int = 8,
    price_scale: float = 1000.0,   # $0.01/hr -> ~10 reward units
    latency_weight: float = 1.0,
    hop_latency: float = 2.0,
    overload_penalty: float = 50.0,
    pod_cpu_low: float = 0.1,
    pod_cpu_high: float = 0.4,
    drain_rate: float = 0.85,
    prices_path: str | None = None,
    max_steps: int | None = None,
    prices=None,
) -> ClusterGraphParams:
    """``prices``: a preloaded ``[T, 2]`` raw $/hr array — the scenario
    layer's seam (e.g. the price-spike family's generated regimes,
    ``scenarios/families.py``) — replacing the CSV replay; default loads
    the shipped trace."""
    if prices is None:
        prices = load_raw_prices(prices_path)
    prices = jnp.asarray(prices, jnp.float32)
    cloud, adj, hops = build_topology(num_nodes)
    f32 = lambda x: jnp.asarray(x, jnp.float32)
    t = prices.shape[0]
    return ClusterGraphParams(
        prices=prices,
        cloud_of_node=jnp.asarray(cloud),
        adjacency=jnp.asarray(adj),
        hops=jnp.asarray(hops),
        price_scale=f32(price_scale),
        latency_weight=f32(latency_weight),
        hop_latency=f32(hop_latency),
        overload_penalty=f32(overload_penalty),
        pod_cpu_low=f32(pod_cpu_low),
        pod_cpu_high=f32(pod_cpu_high),
        drain_rate=f32(drain_rate),
        max_steps=jnp.asarray(max_steps if max_steps is not None else t - 1, jnp.int32),
    )


def _observe(params: ClusterGraphParams, state: ClusterGraphState) -> jnp.ndarray:
    n = params.num_nodes
    row_prices = jax.lax.dynamic_index_in_dim(
        params.prices, state.step_idx, keepdims=False
    )
    # scale raw $ into a ~[0,1] feature so the net doesn't see 1e-2 values
    price_feat = row_prices[params.cloud_of_node] * 30.0
    hops_to_affinity = jax.lax.dynamic_index_in_dim(
        params.hops, state.affinity, axis=1, keepdims=False
    )
    degree = params.adjacency.sum(axis=1)
    step_frac = state.step_idx.astype(jnp.float32) / params.max_steps.astype(jnp.float32)
    return jnp.stack(
        [
            price_feat,
            state.cpu_used,
            params.cloud_of_node.astype(jnp.float32),
            hops_to_affinity / jnp.maximum(params.hops.max(), 1.0),
            degree / n,
            jnp.full((n,), state.pod_cpu),
            jnp.full((n,), step_frac),
        ],
        axis=-1,
    ).astype(jnp.float32)


def reset(
    params: ClusterGraphParams, key: jnp.ndarray
) -> tuple[ClusterGraphState, jnp.ndarray]:
    carry_key, aff_key, pod_key = jax.random.split(key, 3)
    state = ClusterGraphState(
        step_idx=jnp.zeros((), jnp.int32),
        cpu_used=jnp.zeros(params.num_nodes, jnp.float32),
        affinity=jax.random.randint(aff_key, (), 0, params.num_nodes, jnp.int32),
        pod_cpu=jax.random.uniform(
            pod_key, (), jnp.float32,
            minval=params.pod_cpu_low, maxval=params.pod_cpu_high,
        ),
        key=carry_key,
    )
    return state, _observe(params, state)


def step(
    params: ClusterGraphParams, state: ClusterGraphState, action: jnp.ndarray
) -> tuple[ClusterGraphState, TimeStep]:
    action = jnp.asarray(action, jnp.int32)
    carry_key, aff_key, pod_key = jax.random.split(state.key, 3)

    row_prices = jax.lax.dynamic_index_in_dim(
        params.prices, state.step_idx, keepdims=False
    )
    price = row_prices[params.cloud_of_node[action]]
    locality = params.hop_latency * params.hops[action, state.affinity]
    new_cpu = state.cpu_used.at[action].add(state.pod_cpu)
    overload = jnp.maximum(new_cpu[action] - 1.0, 0.0)
    reward = -(
        params.price_scale * price
        + params.latency_weight * locality
        + params.overload_penalty * overload
    )

    new_step = state.step_idx + 1
    done = new_step >= params.max_steps
    new_state = ClusterGraphState(
        step_idx=new_step,
        cpu_used=new_cpu * params.drain_rate,
        affinity=jax.random.randint(aff_key, (), 0, params.num_nodes, jnp.int32),
        pod_cpu=jax.random.uniform(
            pod_key, (), jnp.float32,
            minval=params.pod_cpu_low, maxval=params.pod_cpu_high,
        ),
        key=carry_key,
    )
    ts = TimeStep(
        obs=_observe(params, new_state),
        reward=reward.astype(jnp.float32),
        done=done,
        chosen_cloud=params.cloud_of_node[action],
        step=new_step,
    )
    return new_state, ts
