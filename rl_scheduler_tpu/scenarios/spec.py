"""The ``Scenario`` spec and registry: one object names a workload.

A :class:`Scenario` is a frozen, hashable description — family, seed,
knobs — that compiles deterministically into env-ready tables
(``families.py``) and per-episode randomization fields the env layer
draws from its own vmapped ``jax.random`` keys (``env/cluster_set.py``
scenario fields, ``scenarios/het_env.py``). Nothing here holds state:
the same Scenario builds the same params bit-for-bit every time
(``tests/test_scenarios.py`` pins it), and the training/eval/serving
layers pass the *name* around (CLI ``--scenario``, checkpoint meta,
extender conformance) with the seed recorded alongside.

Layer map:

- **env**: :func:`cluster_set_params` / :func:`scenario_bundle` build the
  structured-env params+bundle a scenario trains on;
  :func:`cloud_table` / :func:`raw_prices` feed the flat multi-cloud and
  graph envs the same compiled tables.
- **agent**: ``train_ppo --scenario`` / ``train_dqn --scenario`` train on
  the bundle and record :func:`scenario_meta` in every checkpoint;
  ``agent/evaluate.py --matrix`` sweeps the registry × policy families.
- **serving**: the extender reads the meta back and refuses a serve
  config whose scenario disagrees (``scheduler/extender.py``);
  :func:`baseline_columns` keeps the hand-coded baselines reading the
  right feature columns on widened observations.
"""

from __future__ import annotations

import dataclasses
from typing import Any

FAMILIES = ("bursty_diurnal", "heterogeneous", "churn", "price_spike",
            "domain_random", "trace_replay", "external_trace")

# graftloop (rl_scheduler_tpu/loopback/): a trace_replay scenario is
# named dynamically — ``trace_replay:<snapshot_dir>[?steps=N&mix=F]`` —
# because its tables compile from a recorded trace snapshot on disk, not
# from a registry preset. The NAME alone rebuilds the identical spec
# (get_scenario parses it), so checkpoint-meta round-trips, resume
# guards, and serving conformance all work unchanged.
TRACE_SCENARIO_PREFIX = "trace_replay:"

# graftmix (rl_scheduler_tpu/mixtures/): an external_trace scenario —
# ``external_trace:<dir>?format=google|alibaba[&steps=N]`` — compiles a
# PUBLIC cluster trace (Google ClusterData-style machine-event +
# task-usage CSVs, Alibaba v2018-style machine/container tables) through
# the importer + data/normalize pipeline. Same name-built convention as
# trace_replay: the whole spec lives in the name.
EXTERNAL_SCENARIO_PREFIX = "external_trace:"


@dataclasses.dataclass(frozen=True)
class Scenario:
    """A named, seeded workload-scenario spec (module docstring).

    ``knobs`` is a sorted tuple of ``(name, value)`` pairs so the spec
    stays hashable/frozen; use :meth:`knob` to read one.
    """

    name: str
    family: str
    seed: int = 0
    steps: int = 100
    knobs: tuple = ()

    def __post_init__(self):
        if self.family not in FAMILIES:
            raise ValueError(
                f"unknown scenario family {self.family!r}; choose from "
                f"{list(FAMILIES)}")
        if self.steps < 2:
            raise ValueError(f"steps={self.steps}: a scenario table needs "
                             "at least 2 rows (episode length >= 1)")
        if self.family == "trace_replay":
            if not self.knob("trace_dir"):
                raise ValueError(
                    "trace_replay scenarios compile from a trace snapshot "
                    "— name one via trace_replay:<dir> (get_scenario) or "
                    "a trace_dir knob")
            mix = float(self.knob("mix_frac", 0.0) or 0.0)
            if not 0.0 <= mix < 1.0:
                raise ValueError(
                    f"mix_frac={mix}: the anti-forgetting mixture share "
                    "of base-workload rows must be in [0, 1) — 1.0 would "
                    "leave no trace rows to learn from")
        if self.family == "external_trace":
            if not self.knob("trace_dir"):
                raise ValueError(
                    "external_trace scenarios compile from a public "
                    "cluster-trace directory — name one via "
                    "external_trace:<dir>?format=... (get_scenario) or a "
                    "trace_dir knob")
            from rl_scheduler_tpu.mixtures.importer import FORMATS

            if self.knob("format") not in FORMATS:
                raise ValueError(
                    f"external_trace scenarios need format= one of "
                    f"{list(FORMATS)}; got {self.knob('format')!r}")

    def knob(self, name: str, default: Any = None) -> Any:
        for k, v in self.knobs:
            if k == name:
                return v
        return default

    def with_seed(self, seed: int) -> "Scenario":
        """Same workload shape, different draw — the eval matrix and
        determinism tests re-seed through this."""
        return dataclasses.replace(self, seed=seed)


def _knobs(**kw) -> tuple:
    return tuple(sorted(kw.items()))


# The registry: one production-shaped preset per family (plus
# 'randomized', the domain-randomization-only variant the fleet seed
# studies measure). Knobs are the documented randomization surface
# (docs/scenarios.md); anything not named here keeps the env default.
SCENARIOS = {
    "bursty": Scenario(
        name="bursty", family="bursty_diurnal",
        knobs=_knobs(period=24.0, spike_rate=0.06, spike_mag=0.8,
                     jitter_range=(0.05, 0.2), random_phase=True),
    ),
    "heterogeneous": Scenario(
        name="heterogeneous", family="heterogeneous",
        knobs=_knobs(num_resources=3, acc_node_frac=0.5,
                     acc_request_prob=0.35),
    ),
    "churn": Scenario(
        name="churn", family="churn",
        knobs=_knobs(preempt_rate=0.02, drain_steps=8, churn_penalty=1.0,
                     drain_range=(0.75, 0.95), random_phase=True),
    ),
    "price_spike": Scenario(
        name="price_spike", family="price_spike",
        knobs=_knobs(spike_prob=0.04, spike_mult=4.0, decay=0.7,
                     jitter_range=(0.05, 0.2), overload_range=(1.0, 4.0)),
    ),
    # Domain randomization over the env dynamics ONLY (ROADMAP 3b: the
    # anti-latch intervention the fleet seed studies measure,
    # docs/studies.md): the workload stays the shipped CSV replay —
    # identical to the un-scenarioed control — while every episode
    # redraws node_jitter / drain_rate / overload_penalty from these
    # ranges and starts at a random table phase, so a static per-node
    # premium is no longer a stable thing for the argmax to latch onto.
    "randomized": Scenario(
        name="randomized", family="domain_random",
        knobs=_knobs(jitter_range=(0.05, 0.25), drain_range=(0.7, 0.95),
                     overload_range=(1.0, 3.0), random_phase=True),
    ),
}


def list_scenarios() -> list:
    return sorted(SCENARIOS)


def _parse_trace_name(name: str) -> Scenario:
    """``trace_replay:<snapshot_dir>[?steps=N&mix=F]`` -> Scenario.

    The whole spec lives in the name so it round-trips through checkpoint
    meta, resume guards, and the extender's conformance demand exactly
    like a registry preset's. ``steps`` caps the compiled episode length
    (seeded window into a longer trace, loopback/compile.py); ``mix``
    interleaves that share of base-CSV workload rows (the
    anti-forgetting mixture graftloop retrains on)."""
    spec_part = name[len(TRACE_SCENARIO_PREFIX):]
    path, _, query = spec_part.partition("?")
    if not path:
        raise ValueError(
            f"scenario {name!r}: trace_replay:<snapshot_dir> needs the "
            "snapshot directory (loopback snapshot_trace writes one)")
    steps, mix = 256, 0.0
    if query:
        for item in query.split("&"):
            key, _, value = item.partition("=")
            try:
                if key == "steps":
                    steps = int(value)
                elif key == "mix":
                    mix = float(value)
                else:
                    raise ValueError(
                        f"scenario {name!r}: unknown trace_replay "
                        f"parameter {key!r} (steps, mix)")
            except ValueError as e:
                if "unknown" in str(e):
                    raise
                raise ValueError(
                    f"scenario {name!r}: bad value for {key!r}: {value!r}")
    knobs = _knobs(trace_dir=path, mix_frac=mix)
    return Scenario(name=name, family="trace_replay", steps=steps,
                    knobs=knobs)


def _parse_external_name(name: str) -> Scenario:
    """``external_trace:<dir>?format=google|alibaba[&steps=N]`` ->
    Scenario (graftmix importer, ``mixtures/importer.py``). The same
    name-round-trip contract as :func:`_parse_trace_name`: checkpoint
    meta, resume guards, and the extender's conformance demand carry the
    one string."""
    spec_part = name[len(EXTERNAL_SCENARIO_PREFIX):]
    path, _, query = spec_part.partition("?")
    if not path:
        raise ValueError(
            f"scenario {name!r}: external_trace:<dir>?format=... needs "
            "the trace directory (mixtures/fixtures.py generates "
            "synthetic ones)")
    steps, fmt = 100, None
    if query:
        for item in query.split("&"):
            key, _, value = item.partition("=")
            if key == "steps":
                try:
                    steps = int(value)
                except ValueError:
                    raise ValueError(
                        f"scenario {name!r}: bad value for {key!r}: "
                        f"{value!r}")
            elif key == "format":
                fmt = value
            else:
                raise ValueError(
                    f"scenario {name!r}: unknown external_trace "
                    f"parameter {key!r} (format, steps)")
    if fmt is None:
        raise ValueError(
            f"scenario {name!r}: external_trace needs ?format=google or "
            "?format=alibaba (which parser reads the directory)")
    knobs = _knobs(trace_dir=path, format=fmt)
    return Scenario(name=name, family="external_trace", steps=steps,
                    knobs=knobs)


def get_scenario(name: str, seed: int | None = None) -> Scenario:
    """Registry lookup; ``seed`` re-seeds the preset's table generation.
    Names starting ``trace_replay:`` build graftloop's dynamic
    trace-compiled scenario instead (:func:`_parse_trace_name`); names
    starting ``external_trace:`` build graftmix's imported public-trace
    scenario (:func:`_parse_external_name`)."""
    if name.startswith(TRACE_SCENARIO_PREFIX):
        scn = _parse_trace_name(name)
        return scn if seed is None else scn.with_seed(seed)
    if name.startswith(EXTERNAL_SCENARIO_PREFIX):
        scn = _parse_external_name(name)
        return scn if seed is None else scn.with_seed(seed)
    if name not in SCENARIOS:
        raise ValueError(
            f"unknown scenario {name!r}; registered: {list_scenarios()} "
            f"(or trace_replay:<snapshot_dir> / "
            f"external_trace:<dir>?format=... for a compiled trace)")
    scn = SCENARIOS[name]
    return scn if seed is None else scn.with_seed(seed)


def _compiled(scenario: Scenario) -> dict:
    """Family dispatch: the host-side compiled tables for this spec."""
    from rl_scheduler_tpu.scenarios import families as fam

    if scenario.family == "bursty_diurnal":
        return fam.bursty_diurnal_tables(
            steps=scenario.steps, seed=scenario.seed,
            period=scenario.knob("period", 24.0),
            spike_rate=scenario.knob("spike_rate", 0.06),
            spike_mag=scenario.knob("spike_mag", 0.8),
        )
    if scenario.family == "price_spike":
        return fam.price_spike_tables(
            steps=scenario.steps, seed=scenario.seed,
            spike_prob=scenario.knob("spike_prob", 0.04),
            spike_mult=scenario.knob("spike_mult", 4.0),
            decay=scenario.knob("decay", 0.7),
        )
    if scenario.family == "trace_replay":
        return fam.trace_replay_tables(
            trace_dir=scenario.knob("trace_dir"),
            steps=scenario.steps, seed=scenario.seed,
            mix_frac=float(scenario.knob("mix_frac", 0.0) or 0.0),
        )
    if scenario.family == "external_trace":
        return fam.external_trace_tables(
            trace_dir=scenario.knob("trace_dir"),
            fmt=scenario.knob("format"),
            steps=scenario.steps, seed=scenario.seed,
        )
    raise ValueError(
        f"family {scenario.family!r} compiles no tables (churn compiles a "
        "mask per node count; heterogeneous compiles capacities)")


class _TableView:
    """Duck-typed ``CloudTable`` (costs/latencies) over compiled arrays.

    Leaves are device arrays: ``env/core.make_params`` stores the table
    as-is, and numpy leaves would reject traced gather indices inside
    the open-loop horizon."""

    def __init__(self, costs, latencies):
        import jax.numpy as jnp

        self.costs = jnp.asarray(costs, jnp.float32)
        self.latencies = jnp.asarray(latencies, jnp.float32)


def cloud_table(scenario: Scenario):
    """Compiled cost/latency tables for the FLAT multi-cloud env
    (``env/core.make_params(table=...)``) — the bursty-diurnal and
    price-spike families; the node-level families have no cloud-level
    story to tell a 2-action policy."""
    if scenario.family not in ("bursty_diurnal", "price_spike"):
        raise ValueError(
            f"scenario {scenario.name!r} (family {scenario.family}) has no "
            "cloud-level tables; multi_cloud training takes the "
            "bursty_diurnal and price_spike families")
    t = _compiled(scenario)
    return _TableView(t["costs"], t["latencies"])


def raw_prices(scenario: Scenario):
    """Raw ``[T, 2]`` $/hr for the cluster-graph env's dollar-reward
    replay (price-spike family only — the one with a dollar story)."""
    if scenario.family != "price_spike":
        raise ValueError(
            f"scenario {scenario.name!r} has no raw dollar prices; the "
            "price_spike family drives cluster_graph")
    return _compiled(scenario)["raw_prices"]


def cluster_set_params(scenario: Scenario, num_nodes: int = 8):
    """Env params for the structured set family this scenario shapes:
    :class:`~rl_scheduler_tpu.env.cluster_set.ClusterSetParams` (bursty /
    churn / price_spike) or the heterogeneous env's
    :class:`~rl_scheduler_tpu.scenarios.het_env.HetSetParams`."""
    from rl_scheduler_tpu.env import cluster_set as cs

    randomization = dict(
        jitter_range=scenario.knob("jitter_range"),
        drain_range=scenario.knob("drain_range"),
        overload_range=scenario.knob("overload_range"),
        random_phase=bool(scenario.knob("random_phase", False)),
    )
    if scenario.family == "heterogeneous":
        from rl_scheduler_tpu.scenarios import het_env

        return het_env.make_params(
            num_nodes=num_nodes,
            num_resources=int(scenario.knob("num_resources", 3)),
            seed=scenario.seed,
            acc_node_frac=scenario.knob("acc_node_frac", 0.5),
            acc_request_prob=scenario.knob("acc_request_prob", 0.35),
        )
    if scenario.family == "domain_random":
        # No compiled tables: the shipped CSV replay, shaped only by the
        # per-episode randomization fields — the control workload with
        # the latch target jittered away.
        return cs.make_params(num_nodes=num_nodes, **randomization)
    if scenario.family == "churn":
        from rl_scheduler_tpu.scenarios.families import churn_mask

        # The mask is compiled at the shipped table's length so the
        # episode stays table-shaped; it is node-count-specific.
        table = _default_table()
        mask = churn_mask(
            steps=table.costs.shape[0], num_nodes=num_nodes,
            seed=scenario.seed,
            preempt_rate=scenario.knob("preempt_rate", 0.02),
            drain_steps=int(scenario.knob("drain_steps", 8)),
        )
        return cs.make_params(
            num_nodes=num_nodes, table=table, avail_mask=mask,
            churn_penalty=scenario.knob("churn_penalty", 1.0),
            **randomization)
    if scenario.family == "external_trace":
        # graftmix: an imported public trace carries THREE table kinds at
        # once — demand-priced cost/latency rows, the arrival-size
        # multiplier, and the machine-lifecycle availability mask (the
        # node-count-late compile, like the churn family's mask). ONE
        # import feeds all three: real public traces are multi-GB, and
        # the transfer grid rebuilds params per (scenario, node count).
        from rl_scheduler_tpu.mixtures.importer import (
            import_external_trace,
            node_avail_mask,
        )

        imported = import_external_trace(
            scenario.knob("trace_dir"), scenario.knob("format"),
            steps=scenario.steps, seed=scenario.seed)
        mask = node_avail_mask(imported, num_nodes, seed=scenario.seed)
        return cs.make_params(
            num_nodes=num_nodes,
            table=_TableView(imported.costs, imported.latencies),
            pod_scale=imported.pod_scale,
            avail_mask=mask,
            churn_penalty=scenario.knob("churn_penalty", 1.0),
            **randomization)
    if scenario.family == "trace_replay":
        # graftloop: replay the logged workload exactly — zero static
        # node premium (a serving-side unknown; zero keeps the compiled
        # cost/latency columns bit-exact through _observe, the
        # round-trip pin in loopback/compile.py), and when the trace
        # recorded pod sizes, a degenerate pod draw (low == high == 1.0)
        # so pod_cpu at row t IS pod_scale[t] — the recorded request.
        t = _compiled(scenario)
        pod_kw = ({"pod_cpu_low": 1.0, "pod_cpu_high": 1.0}
                  if t.get("pod_from_trace") else {})
        return cs.make_params(
            num_nodes=num_nodes,
            table=_TableView(t["costs"], t["latencies"]),
            pod_scale=t.get("pod_scale"),
            node_jitter=0.0,
            **pod_kw, **randomization)
    t = _compiled(scenario)
    return cs.make_params(
        num_nodes=num_nodes,
        table=_TableView(t["costs"], t["latencies"]),
        pod_scale=t.get("pod_scale"),
        **randomization)


def _default_table():
    from rl_scheduler_tpu.data.loader import load_table

    return load_table()


def csv_reference_row() -> tuple:
    """The un-scenarioed CSV-replay row every scenario sweep reads its
    scenarios against — ``(bundle_fn, columns, node_feat, family)`` with
    ``bundle_fn(num_nodes)`` building the plain cluster_set bundle. ONE
    definition shared by the eval matrix and the transfer grid
    (``agent/evaluate.py``, ``mixtures/grid.py``) so the two tools'
    ``csv`` rows — including the domain_random family mapping the
    held-out flags key on — can never drift."""
    from rl_scheduler_tpu.env import cluster_set as cs
    from rl_scheduler_tpu.env.bundle import cluster_set_bundle

    def bundle_fn(num_nodes: int):
        return cluster_set_bundle(cs.make_params(num_nodes=num_nodes))

    return bundle_fn, {"cost": 0, "cpu": 2}, cs.NODE_FEAT, "domain_random"


def scenario_bundle(scenario: Scenario, num_nodes: int = 8):
    """The scenario's structured env as an
    :class:`~rl_scheduler_tpu.env.bundle.EnvBundle` — same vmapped
    auto-reset fleet path every other env family trains through."""
    if scenario.family == "heterogeneous":
        from rl_scheduler_tpu.scenarios.het_env import het_bundle

        return het_bundle(cluster_set_params(scenario, num_nodes))
    from rl_scheduler_tpu.env.bundle import cluster_set_bundle

    return cluster_set_bundle(cluster_set_params(scenario, num_nodes))


def node_feat_for(scenario: Scenario) -> int:
    """Observation width the scenario trains (and must serve) with."""
    if scenario.family == "heterogeneous":
        from rl_scheduler_tpu.scenarios.het_env import node_feat

        return node_feat(int(scenario.knob("num_resources", 3)))
    from rl_scheduler_tpu.env.cluster_set import NODE_FEAT

    return NODE_FEAT


def baseline_columns(scenario: Scenario) -> dict:
    """The ``{feature: column}`` map the hand-coded node baselines read on
    this scenario's observation layout (``env/baselines.py``)."""
    # Every current family keeps cost at 0 and the first utilization
    # column at 2 (cluster_set layout; het_env pins the same prefix).
    return {"cost": 0, "cpu": 2}


def scenario_meta(scenario: Scenario) -> dict:
    """The checkpoint-meta record: enough to rebuild the bundle at
    eval/serve time and to refuse a mismatched serve config."""
    return {
        "scenario": scenario.name,
        "scenario_seed": scenario.seed,
        "scenario_family": scenario.family,
        "node_feat": node_feat_for(scenario),
    }
