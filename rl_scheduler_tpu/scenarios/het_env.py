"""Heterogeneous multi-resource pod/node-set simulator (scenario family 2).

The cluster_set env (``env/cluster_set.py``) tracks ONE resource per node;
real pods request cpu AND memory AND accelerators, and real fleets are
heterogeneous — some nodes have no accelerator at all. This env widens the
set simulator to ``R`` resources with per-node capacities, which widens
the observation (and with it the set policy's score inputs): the embed
layer infers its width from the obs, so the SAME
``SetTransformerPolicy`` trains on it unchanged through the existing
vmapped fleet path (it is a training-distribution change, not an
architecture change — but checkpoints bake the width into the embed
kernel, so scenario meta records ``node_feat`` and serving refuses a
mismatch, ``scheduler/extender.py``).

Per-node features (``NODE_FEAT = 4 + 3R`` columns, fixed order):

  0        cost       — cloud cost from the replayed table + static premium
  1        latency    — same construction
  2..2+R   used_r     — utilization of resource r as a FRACTION of this
                        node's capacity (placements add req/cap, completions
                        drain geometrically)
  2+R..2+2R cap_r     — the node's capacity in [0, 1] (static per episode;
                        accelerator-less nodes show ~0, so the policy can
                        see where an accelerator pod cannot fit)
  2+2R     cloud_id   — 0 aws, 1 azure
  3+2R..3+3R req_r    — the arriving pod's per-resource request (broadcast)
  3+3R     step_frac  — episode progress

Reward for placing on node ``a``:
    -reward_scale * (w_c*cost[a] + w_l*lat[a]
                     + overload_penalty * sum_r relu(used'[a, r] - 1))
— the cluster_set trade-off with the overload term summed across
resources: overloading ANY axis (including requesting an accelerator a
node does not have) is punished, so bin-packing over the full request
vector is what the optimal policy must learn.

Pure-functional, seeded, jit/vmap/scan-safe — same contract as every env
in ``env/`` (vmap parity and per-seed determinism pinned in
``tests/test_scenarios.py``).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

RESOURCES = ("cpu", "mem", "acc")


def node_feat(num_resources: int) -> int:
    """Observation width for an R-resource fleet (module docstring)."""
    return 4 + 3 * num_resources


class HetSetParams(NamedTuple):
    costs: jnp.ndarray          # [T, 2] normalized cloud costs
    latencies: jnp.ndarray      # [T, 2]
    cloud_of_node: jnp.ndarray  # [N] int32
    capacity: jnp.ndarray       # [N, R] per-node resource capacities
    cost_weight: jnp.ndarray
    latency_weight: jnp.ndarray
    reward_scale: jnp.ndarray
    overload_penalty: jnp.ndarray
    node_jitter: jnp.ndarray
    req_low: jnp.ndarray        # [R] per-resource request range
    req_high: jnp.ndarray       # [R]
    acc_request_prob: jnp.ndarray  # P(pod requests each accelerator resource)
    drain_rate: jnp.ndarray
    max_steps: jnp.ndarray

    @property
    def num_nodes(self) -> int:
        return self.cloud_of_node.shape[0]

    @property
    def num_resources(self) -> int:
        return self.capacity.shape[1]

    @property
    def node_feat(self) -> int:
        return node_feat(self.num_resources)


class HetSetState(NamedTuple):
    step_idx: jnp.ndarray       # scalar int32
    res_used: jnp.ndarray       # [N, R] fraction-of-capacity utilization
    node_premium: jnp.ndarray   # [N, 2] static per-episode (cost, lat)
    pod_req: jnp.ndarray        # [R] the pod awaiting placement
    key: jnp.ndarray


class TimeStep(NamedTuple):
    obs: jnp.ndarray            # [N, node_feat]
    reward: jnp.ndarray
    done: jnp.ndarray
    chosen_cloud: jnp.ndarray
    step: jnp.ndarray


def make_params(
    num_nodes: int = 8,
    num_resources: int = 3,
    seed: int = 0,
    cost_weight: float = 0.6,
    latency_weight: float = 0.4,
    reward_scale: float = 100.0,
    overload_penalty: float = 2.0,
    node_jitter: float = 0.1,
    acc_node_frac: float = 0.5,
    acc_request_prob: float = 0.35,
    drain_rate: float = 0.85,
    table=None,
    data_path: str | None = None,
    max_steps: int | None = None,
) -> HetSetParams:
    """Build params; capacities come from the seeded heterogeneous-fleet
    generator (``families.heterogeneous_capacities``), tables from the
    shipped CSV or a scenario's compiled tables (``table=``)."""
    from rl_scheduler_tpu.data.loader import load_table
    from rl_scheduler_tpu.scenarios.families import heterogeneous_capacities

    if num_resources < 1:
        raise ValueError(f"num_resources={num_resources}: must be >= 1")
    if table is None:
        table = load_table(data_path)
    t = table.costs.shape[0]
    f32 = lambda x: jnp.asarray(x, jnp.float32)
    cloud = (jnp.arange(num_nodes) >= num_nodes // 2).astype(jnp.int32)
    caps = heterogeneous_capacities(num_nodes, num_resources, seed,
                                    acc_node_frac)
    # Per-resource request ranges: cpu-like, memory-like, accelerator-like
    # (cycled past R=3) — accelerator requests are chunky when they happen.
    base_ranges = [(0.1, 0.4), (0.05, 0.3), (0.2, 0.6)]
    lo, hi = zip(*(base_ranges[min(r, 2)] for r in range(num_resources)))
    return HetSetParams(
        costs=f32(table.costs),
        latencies=f32(table.latencies),
        cloud_of_node=cloud,
        capacity=f32(caps),
        cost_weight=f32(cost_weight),
        latency_weight=f32(latency_weight),
        reward_scale=f32(reward_scale),
        overload_penalty=f32(overload_penalty),
        node_jitter=f32(node_jitter),
        req_low=f32(np.asarray(lo)),
        req_high=f32(np.asarray(hi)),
        acc_request_prob=f32(acc_request_prob),
        drain_rate=f32(drain_rate),
        max_steps=jnp.asarray(
            max_steps if max_steps is not None else t - 1, jnp.int32),
    )


def _draw_req(params: HetSetParams, key: jnp.ndarray) -> jnp.ndarray:
    """One pod's ``[R]`` request vector: continuous draws for cpu/mem,
    Bernoulli-gated for accelerator resources (most pods want none)."""
    r = params.req_low.shape[0]
    ukey, gkey = jax.random.split(key)
    base = jax.random.uniform(ukey, (r,), jnp.float32,
                              minval=params.req_low, maxval=params.req_high)
    gate = jax.random.bernoulli(gkey, params.acc_request_prob, (r,))
    always = jnp.arange(r) < 2          # cpu/mem always requested
    return jnp.where(always | gate, base, 0.0)


def _observe(params: HetSetParams, state: HetSetState) -> jnp.ndarray:
    n, r = params.capacity.shape
    row_costs = jax.lax.dynamic_index_in_dim(
        params.costs, state.step_idx, keepdims=False)
    row_lats = jax.lax.dynamic_index_in_dim(
        params.latencies, state.step_idx, keepdims=False)
    cost = jnp.clip(
        row_costs[params.cloud_of_node] + state.node_premium[:, 0], 0.0, 1.0)
    lat = jnp.clip(
        row_lats[params.cloud_of_node] + state.node_premium[:, 1], 0.0, 1.0)
    step_frac = state.step_idx.astype(jnp.float32) / params.max_steps.astype(
        jnp.float32)
    cols = (
        [cost, lat]
        + [state.res_used[:, i] for i in range(r)]
        + [params.capacity[:, i] for i in range(r)]
        + [params.cloud_of_node.astype(jnp.float32)]
        + [jnp.full((n,), state.pod_req[i]) for i in range(r)]
        + [jnp.full((n,), step_frac)]
    )
    return jnp.stack(cols, axis=-1).astype(jnp.float32)


def reset(params: HetSetParams, key: jnp.ndarray) -> tuple[HetSetState, jnp.ndarray]:
    carry_key, prem_key, req_key = jax.random.split(key, 3)
    premium = params.node_jitter * jax.random.uniform(
        prem_key, (params.num_nodes, 2), jnp.float32)
    state = HetSetState(
        step_idx=jnp.zeros((), jnp.int32),
        res_used=jnp.zeros(params.capacity.shape, jnp.float32),
        node_premium=premium,
        pod_req=_draw_req(params, req_key),
        key=carry_key,
    )
    return state, _observe(params, state)


def step(
    params: HetSetParams, state: HetSetState, action: jnp.ndarray
) -> tuple[HetSetState, TimeStep]:
    """Place the pending pod on node ``action``; pure, jit/vmap/scan-safe."""
    action = jnp.asarray(action, jnp.int32)
    carry_key, req_key = jax.random.split(state.key)

    row_costs = jax.lax.dynamic_index_in_dim(
        params.costs, state.step_idx, keepdims=False)
    row_lats = jax.lax.dynamic_index_in_dim(
        params.latencies, state.step_idx, keepdims=False)
    cost = jnp.clip(
        row_costs[params.cloud_of_node] + state.node_premium[:, 0], 0.0, 1.0)
    lat = jnp.clip(
        row_lats[params.cloud_of_node] + state.node_premium[:, 1], 0.0, 1.0)

    # Utilization is tracked as a fraction of THIS node's capacity, so the
    # same request overloads a small node sooner — and an accelerator pod
    # on an accelerator-less node (capacity ~0) blows up immediately.
    cap_a = params.capacity[action]                       # [R]
    add = state.pod_req / jnp.maximum(cap_a, 1e-3)
    new_used = state.res_used.at[action].add(add)
    overload = jnp.sum(jnp.maximum(new_used[action] - 1.0, 0.0))
    reward = -params.reward_scale * (
        params.cost_weight * cost[action]
        + params.latency_weight * lat[action]
        + params.overload_penalty * overload
    )

    new_step = state.step_idx + 1
    done = new_step >= params.max_steps
    new_state = HetSetState(
        step_idx=new_step,
        res_used=new_used * params.drain_rate,
        node_premium=state.node_premium,
        pod_req=_draw_req(params, req_key),
        key=carry_key,
    )
    ts = TimeStep(
        obs=_observe(params, new_state),
        reward=reward.astype(jnp.float32),
        done=done,
        chosen_cloud=params.cloud_of_node[action],
        step=new_step,
    )
    return new_state, ts


def het_bundle(params: HetSetParams | None = None):
    """The heterogeneous env as an :class:`~rl_scheduler_tpu.env.bundle.
    EnvBundle` — trains through the same vmapped/auto-reset path as every
    other family."""
    from rl_scheduler_tpu.env.bundle import bundle_from_single

    if params is None:
        params = make_params()
    return bundle_from_single(
        lambda key: reset(params, key),
        lambda state, action: step(params, state, action),
        obs_shape=(params.num_nodes, params.node_feat),
        num_actions=params.num_nodes,
        name="cluster_set_het",
        episode_steps=int(params.max_steps),
    )
