"""graftscenario — trace-driven, heterogeneous workload scenarios.

The package the north star's "as many scenarios as you can imagine" axis
lives in (ROADMAP item 5): a :class:`Scenario` is a pure-functional,
seeded spec that compiles into env-ready tables and per-episode
randomized params, vmappable end-to-end so fleet training speed carries
over. Four production-shaped families ship (``spec.SCENARIOS``):

- ``bursty``        — bursty-diurnal arrival/load processes (sinusoid +
                      seeded spike bursts; pod sizes follow the wave)
- ``heterogeneous`` — multi-resource pods (cpu+mem+accelerator) over a
                      heterogeneous fleet (``het_env.py``)
- ``churn``         — node-pool preemptions/drains from graftguard's
                      seeded FaultPlan stream, masked in/out mid-episode
- ``price_spike``   — spot-market price-spike regimes generated through
                      ``data/generate.py``

Two further families are name-built, never registry presets:
``trace_replay:<snapshot>`` (graftloop — served traffic, replayed) and
``external_trace:<dir>?format=google|alibaba`` (graftmix — public
cluster traces imported through ``rl_scheduler_tpu/mixtures/``).

Entry points: ``train_ppo --scenario NAME`` / ``train_dqn --scenario``
(``--mixture`` for graftmix curricula over several families),
``python -m rl_scheduler_tpu.agent.evaluate --matrix`` (the scenario ×
policy-family eval matrix), ``--transfer-grid`` (the zero-shot
generalist grid), ``make eval-matrix`` / ``make transfer-grid``, and
the extender's scenario-conformance check. Design doc:
``docs/scenarios.md``.
"""

from rl_scheduler_tpu.scenarios.spec import (
    FAMILIES,
    SCENARIOS,
    Scenario,
    baseline_columns,
    cloud_table,
    cluster_set_params,
    csv_reference_row,
    get_scenario,
    list_scenarios,
    node_feat_for,
    raw_prices,
    scenario_bundle,
    scenario_meta,
)

__all__ = [
    "FAMILIES",
    "SCENARIOS",
    "Scenario",
    "baseline_columns",
    "cloud_table",
    "cluster_set_params",
    "csv_reference_row",
    "get_scenario",
    "list_scenarios",
    "node_feat_for",
    "raw_prices",
    "scenario_bundle",
    "scenario_meta",
]
