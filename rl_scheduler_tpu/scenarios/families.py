"""Scenario-family table generators: production-shaped workloads, host-side.

Every env family used to replay the same 100-row synthetic CSV
(``data/real_prices.csv``'s flat i.i.d. jitter around two anchors), so no
trained policy ever saw anything shaped like production traffic. The
generators here compile a :class:`~rl_scheduler_tpu.scenarios.spec.Scenario`
into the table space the envs already gather from — costs/latencies
``[T, 2]``, per-step arrival intensity ``[T]``, node availability
``[T, N]`` — once, host-side, seeded; the envs then step them inside the
same jit/vmap programs as the CSV replay (no new per-step host work, so
fleet training speed carries over — measured in ``bench.py
--scenario-bench``).

Determinism contract (pinned by ``tests/test_scenarios.py``): same
``(family, knobs, seed)`` ⇒ bitwise-identical tables. Each generator owns
ONE ``np.random.RandomState(seed)`` with a fixed draw order (the same
discipline as ``data/generate.py``), and the churn generator reuses
graftguard's :class:`~rl_scheduler_tpu.utils.faults.FaultPlan` per-site
stream seeding so a churn schedule is reproducible from ``(seed, rate)``.

Per-EPISODE randomization (phase offsets, node-premium/drain/overload
draws) is NOT generated here — it rides the envs' per-env ``jax.random``
keys at reset (``env/cluster_set.py`` scenario fields), so it stays fully
vmappable and re-draws every episode.
"""

from __future__ import annotations

import numpy as np

TWO_PI = 2.0 * np.pi


def bursty_diurnal_tables(
    steps: int = 100,
    seed: int = 0,
    period: float = 24.0,
    spike_rate: float = 0.06,
    spike_mag: float = 0.8,
    spike_decay: float = 0.6,
    load_latency_coupling: float = 0.5,
    load_cost_coupling: float = 0.25,
    pod_scale_low: float = 0.5,
    pod_scale_high: float = 1.8,
) -> dict:
    """Family 1 — bursty-diurnal arrival/load processes.

    A sinusoidal daily cycle (per-cloud phase offsets drawn from the
    seed) plus seeded spike bursts drives three tables at once, the way
    load actually propagates: latency follows load hardest
    (``load_latency_coupling``), cost follows it weakly (demand pricing),
    and the arriving pods' sizes follow it via ``pod_scale`` — the
    arrival-intensity multiplier the cluster_set env applies to its
    per-step pod draw (``ClusterSetParams.pod_scale``). Peak hours mean
    bigger pods AND slower/costlier nodes, which is exactly when
    bin-packing discipline pays.

    Returns ``{"costs": [T,2], "latencies": [T,2], "pod_scale": [T]}``,
    all float32, costs/latencies in [0, 1].
    """
    from rl_scheduler_tpu.data.generate import decaying_bursts

    rng = np.random.RandomState(seed)
    t = np.arange(steps, dtype=np.float64)
    phases = rng.uniform(0.0, TWO_PI, 2)          # per-cloud diurnal phase
    loads = []
    for c in range(2):
        diurnal = 0.5 + 0.5 * np.sin(TWO_PI * t / period + phases[c])
        events = rng.uniform(size=steps) < spike_rate
        mags = rng.uniform(0.5, 1.0, steps) * spike_mag
        load = diurnal + decaying_bursts(events, mags, spike_decay)
        loads.append(load)
    loads = np.stack(loads, axis=1)               # [T, 2]
    jitter = rng.uniform(-0.03, 0.03, (steps, 2))
    lat = 0.25 + load_latency_coupling * loads + jitter
    cost_base = np.array([0.3, 0.45])             # aws cheaper on average
    cost = cost_base + load_cost_coupling * loads + rng.uniform(
        -0.03, 0.03, (steps, 2))
    mean_load = loads.mean(axis=1)
    span = mean_load.max() - mean_load.min()
    norm_load = (mean_load - mean_load.min()) / (span if span else 1.0)
    pod_scale = pod_scale_low + (pod_scale_high - pod_scale_low) * norm_load
    return {
        "costs": np.clip(cost, 0.0, 1.0).astype(np.float32),
        "latencies": np.clip(lat, 0.0, 1.0).astype(np.float32),
        "pod_scale": pod_scale.astype(np.float32),
    }


def churn_mask(
    steps: int = 100,
    num_nodes: int = 8,
    seed: int = 0,
    preempt_rate: float = 0.02,
    drain_steps: int = 8,
) -> np.ndarray:
    """Family 3 — node-pool churn: a ``[T, N]`` availability mask (1 = up).

    Preemption events come from graftguard's seeded
    :class:`~rl_scheduler_tpu.utils.faults.FaultPlan` (site
    ``scenario.churn``, rates mode) consulted once per (node, step) in
    node-major order — the identical ``(seed, site)`` stream discipline
    the chaos suite runs on, so a churn schedule is byte-reproducible
    from ``(seed, preempt_rate)`` and independent of every other fault
    site. A preempted node stays down (drained) for ``drain_steps``
    steps, then rejoins.

    At least one node is kept up at every step (node 0 revived on
    fully-dark rows): an all-down cluster has no placement decision to
    learn from, only a constant penalty.
    """
    from rl_scheduler_tpu.utils.faults import FaultPlan

    if drain_steps < 1:
        raise ValueError(f"drain_steps={drain_steps}: must be >= 1")
    plan = FaultPlan(seed=seed, rates={"scenario.churn": preempt_rate})
    mask = np.ones((steps, num_nodes), np.float32)
    for n in range(num_nodes):
        down_until = -1
        for t in range(steps):
            if t <= down_until:
                mask[t, n] = 0.0
                continue
            # One consult per up-step per node: the plan's call counter is
            # what makes the schedule deterministic and rate-faithful.
            if plan.fires("scenario.churn"):
                mask[t, n] = 0.0
                down_until = t + drain_steps - 1
    dark = mask.sum(axis=1) == 0
    mask[dark, 0] = 1.0
    return mask


def price_spike_tables(
    steps: int = 100,
    seed: int = 0,
    spike_prob: float = 0.04,
    spike_mult: float = 4.0,
    decay: float = 0.7,
) -> dict:
    """Family 4 — spot-price spike regimes, generated through the repo's
    own data pipeline: :func:`rl_scheduler_tpu.data.generate.
    generate_price_spikes` synthesizes the raw dollar traces (rare
    multiplicative anti-correlated spikes relaxing geometrically) and
    :func:`rl_scheduler_tpu.data.normalize.normalize` MinMax-scales them
    into the [0,1] table space — the exact path the shipped CSV takes, so
    a scenario table is a drop-in replacement, not a parallel format.

    Returns ``{"costs": [T,2], "latencies": [T,2], "raw_prices": [T,2]}``
    (raw $/hr for the cluster-graph env's dollar-reward replay).
    """
    from rl_scheduler_tpu.data.generate import generate_price_spikes
    from rl_scheduler_tpu.data.normalize import normalize

    rng = np.random.RandomState(seed)
    raw = generate_price_spikes(steps, seed=seed, spike_prob=spike_prob,
                                spike_mult=spike_mult, decay=decay)
    # Latency columns: the flat generator's shape (same anchors/jitter as
    # data/generate.py), drawn from THIS family's stream so the whole
    # table set is reproducible from one seed.
    raw["latency_aws"] = 70.0 + rng.uniform(-10.0, 10.0, steps)
    raw["latency_azure"] = 60.0 + rng.uniform(-10.0, 10.0, steps)
    table = normalize(raw)
    return {
        "costs": table[["cost_aws", "cost_azure"]].to_numpy(np.float32),
        "latencies": table[["latency_aws", "latency_azure"]
                           ].to_numpy(np.float32),
        "raw_prices": raw[["cost_aws", "cost_azure"]].to_numpy(np.float32),
    }


def trace_replay_tables(
    trace_dir: str,
    steps: int = 256,
    seed: int = 0,
    mix_frac: float = 0.0,
) -> dict:
    """Family 6 — trace-driven replay of SERVED traffic (graftloop).

    The only family whose tables come from measurement instead of a
    generator: ``trace_dir`` is a graftloop trace snapshot
    (``loopback.compile.snapshot_trace``) of the serving plane's durable
    decision log, and the compiled ``costs``/``latencies``/``pod_scale``
    rows replay the telemetry rows and pod sizes the pool actually
    served, in served order (``loopback/compile.py`` owns the
    reconstruction; this wrapper keeps the family dispatch in one
    place). Same determinism contract as every generator here: bitwise-
    identical tables per (trace snapshot, steps, seed, mix_frac) —
    ``seed`` places the episode window inside a longer trace and draws
    the mixture interleave; ``mix_frac`` blends that share of base-CSV
    workload rows back in (the anti-forgetting mixture a
    fine-tune-from-trace job trains on, docs/serving.md)."""
    from rl_scheduler_tpu.loopback.compile import compiled_tables

    return compiled_tables(trace_dir, steps=steps, seed=seed,
                           mix_frac=mix_frac)


def external_trace_tables(
    trace_dir: str,
    fmt: str,
    steps: int = 100,
    seed: int = 0,
) -> dict:
    """Family 7 — imported PUBLIC cluster traces (graftmix).

    ``trace_dir`` holds a Google ClusterData-style (machine_events +
    task_usage) or Alibaba cluster-trace-v2018-style (machine_usage +
    container_meta) CSV set; ``mixtures/importer.py`` owns the parse
    (schema-validated with counted row rejection) and the compile
    through the shipped ``data/normalize`` pipeline. Same determinism
    contract as every generator here: bitwise-identical tables per
    (trace digest, seed) — this wrapper keeps the family dispatch in
    one place, like :func:`trace_replay_tables` does for graftloop."""
    from rl_scheduler_tpu.mixtures.importer import external_tables

    return external_tables(trace_dir, fmt, steps=steps, seed=seed)


def heterogeneous_capacities(
    num_nodes: int = 8,
    num_resources: int = 3,
    seed: int = 0,
    acc_node_frac: float = 0.5,
    cap_low: float = 0.5,
    accless_cap: float = 0.05,
) -> np.ndarray:
    """Family 2 — per-node multi-resource capacities ``[N, R]``.

    The first two resources (cpu, mem) draw continuous capacities in
    ``[cap_low, 1]`` — a mixed fleet of machine sizes. Resources from
    index 2 up model accelerators: a seeded ``acc_node_frac`` of nodes
    carry full capacity, the rest ``accless_cap`` (effectively none —
    placing an accelerator pod there blows the overload term, the
    bin-packing pressure this family exists to create). At least one
    node always carries each accelerator resource.
    """
    rng = np.random.RandomState(seed)
    caps = rng.uniform(cap_low, 1.0, (num_nodes, num_resources))
    for r in range(2, num_resources):
        has = rng.uniform(size=num_nodes) < acc_node_frac
        if not has.any():
            has[int(rng.randint(num_nodes))] = True
        caps[:, r] = np.where(has, 1.0, accless_cap)
    return caps.astype(np.float32)
