"""Headline benchmark: env-steps/sec/chip at 4096 parallel simulated clusters.

Runs the fused PPO train step (rollout + GAE + minibatch SGD in one XLA
program) on 4096 vmapped envs and reports env-steps/sec on one chip over
the best of three 20-iteration windows. Each window is ONE dispatched
program (``lax.scan`` over the update), so per-dispatch/tunnel overhead is
amortized 20x, and the window is closed by fetching a metric value to the
host — ``jax.device_get`` — because ``jax.block_until_ready`` does NOT
reliably synchronize on tunneled backends (round-3 finding: it returned
before execution finished, making op-level timings meaningless; fetching
a value that depends on the computation is the only trustworthy sync).

Baseline: the reference's Ray RLlib pipeline sustains ~60 env-steps/s on
its documented hardware (SURVEY.md §6: 640k steps in ~3h).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

BASELINE_STEPS_PER_SEC = 60.0


def main() -> None:
    import jax

    from rl_scheduler_tpu.agent.ppo import make_ppo
    from rl_scheduler_tpu.agent.presets import PPO_PRESETS
    from rl_scheduler_tpu.config import EnvConfig
    from rl_scheduler_tpu.env import core as env_core

    cfg = PPO_PRESETS["tpu4096"]
    env_params = env_core.make_params(EnvConfig())
    init_fn, update_fn, _ = make_ppo(env_params, cfg)
    runner = jax.jit(init_fn)(jax.random.PRNGKey(0))

    iters, repeats = 20, 3

    def window(r):
        return jax.lax.scan(lambda rr, _: update_fn(rr), r, None, length=iters)

    update = jax.jit(window, donate_argnums=0)

    def sync(r) -> float:
        # Fetch a parameter value: params depend on EVERY SGD phase of the
        # window including the last iteration's (a metric like reward_mean
        # would not cover the final SGD tail), so this provably waits for
        # the whole window on every backend (see module docstring).
        leaf = jax.tree.leaves(r.params)[0]
        return float(jax.device_get(leaf).ravel()[0])

    # Warmup: compile + one full window.
    runner, metrics = update(runner)
    sync(runner)

    best_elapsed = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        runner, metrics = update(runner)
        sync(runner)
        best_elapsed = min(best_elapsed, time.perf_counter() - t0)

    steps_per_sec = cfg.batch_size * iters / best_elapsed
    print(
        json.dumps(
            {
                "metric": "env-steps/sec/chip (4096 parallel clusters, fused PPO update)",
                "value": round(steps_per_sec, 1),
                "unit": "env-steps/sec/chip",
                "vs_baseline": round(steps_per_sec / BASELINE_STEPS_PER_SEC, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
