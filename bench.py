"""Headline benchmark: env-steps/sec/chip at 4096 parallel simulated clusters,
plus the fleet-scale set_fleet64 steady-state metric.

Runs the fused PPO train step (rollout + GAE + minibatch SGD in one XLA
program) and reports env-steps/sec on one chip over the best of three
20-iteration windows. Each window is ONE dispatched program (``lax.scan``
over the update), so per-dispatch/tunnel overhead is amortized 20x, and the
window is closed by fetching a metric value to the host —
``jax.device_get`` — because ``jax.block_until_ready`` does NOT reliably
synchronize on tunneled backends (round-3 finding: it returned before
execution finished, making op-level timings meaningless; fetching a value
that depends on the computation is the only trustworthy sync).

Baseline: the reference's Ray RLlib pipeline sustains ~60 env-steps/s on
its documented hardware (SURVEY.md §6: 640k steps in ~3h).

Prints FIVE JSON lines:

1. the config-3 headline {"metric", "value", "unit", "vs_baseline"} —
   unchanged schema, always first;
2. the set_fleet64 fleet metric (1024 envs x 64 nodes, the regime where
   perf work remains — docs/roofline.md fleet rows), same window/sync
   methodology, with a "policy_path" key recording which cluster_set
   policy ran: the whole-network fused Pallas kernel on TPU (the fleet
   preset's auto-selected path) or the dense flax bf16 policy elsewhere;
3. the set_fleet64_scenario line (same recipe on a scenario env,
   docs/scenarios.md) — {"metric", "scenario", "value", "unit",
   "policy_path"};
4. the set_fleet64_overlap line (graftpipe, docs/roofline.md): the SAME
   fleet recipe with `--overlap-collect` semantics — pipelined
   collect/learn (1-iteration-stale behavior policy) + the fused update
   prologue — so the driver tracks the pipelined update's steady state
   next to the unpipelined one. Schema matches line 2 plus
   {"overlap_collect": true, "fused_prologue": true} and the same
   "policy_path" key; each 20-update window is ONE lax.scan dispatch,
   which is exactly the program shape where rollout k+1 can overlap
   SGD k;
5. the set_fleet64_mixture line (graftmix, docs/scenarios.md): the SAME
   fleet recipe on the mixture env — stacked per-family tables with a
   per-episode family draw from the vmapped reset key — so
   mixture-training steady state is driver-tracked beside the four
   existing lines. Schema matches line 3 with {"mixture": "<preset>"}
   instead of {"scenario": ...}.
"""

from __future__ import annotations

import json
import time

BASELINE_STEPS_PER_SEC = 60.0
FLEET_NODES = 64


def _window_steps_per_sec(init_fn, update_fn, batch_size: int,
                          iters: int = 20, repeats: int = 3) -> float:
    """Best-of-N fetch-synced window throughput (module docstring)."""
    import jax

    from rl_scheduler_tpu.utils.profiling import fetch_sync

    runner = jax.jit(init_fn)(jax.random.PRNGKey(0))

    def window(r):
        return jax.lax.scan(lambda rr, _: update_fn(rr), r, None, length=iters)

    update = jax.jit(window, donate_argnums=0)

    def sync(r) -> float:
        # Fetch over the PARAMS: they depend on EVERY SGD phase of the
        # window including the last iteration's (a metric like reward_mean
        # would not cover the final SGD tail), so this provably waits for
        # the whole window on every backend. The sync-by-fetching
        # discipline itself lives in utils/profiling.fetch_sync (shared
        # with StepTimer) — see that docstring for why block_until_ready
        # is not trusted here.
        return fetch_sync(r.params)

    # Warmup: compile + one full window.
    runner, metrics = update(runner)
    sync(runner)

    best_elapsed = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        runner, metrics = update(runner)
        sync(runner)
        best_elapsed = min(best_elapsed, time.perf_counter() - t0)
    return batch_size * iters / best_elapsed


def headline_metric() -> dict:
    from rl_scheduler_tpu.agent.ppo import make_ppo
    from rl_scheduler_tpu.agent.presets import PPO_PRESETS
    from rl_scheduler_tpu.config import EnvConfig
    from rl_scheduler_tpu.env import core as env_core

    cfg = PPO_PRESETS["tpu4096"]
    env_params = env_core.make_params(EnvConfig())
    init_fn, update_fn, _ = make_ppo(env_params, cfg)
    steps_per_sec = _window_steps_per_sec(init_fn, update_fn, cfg.batch_size)
    return {
        "metric": "env-steps/sec/chip (4096 parallel clusters, fused PPO update)",
        "value": round(steps_per_sec, 1),
        "unit": "env-steps/sec/chip",
        "vs_baseline": round(steps_per_sec / BASELINE_STEPS_PER_SEC, 1),
    }


def _fleet_window(cfg, scenario=None, mixture=None) -> tuple[float, str]:
    """Shared scaffold for every set_fleet64-family BENCH line:
    ``(steps_per_sec, policy_path)`` under the fetch-synced window
    methodology. Builds the exact policy the preset trains — the
    whole-network fused kernel on TPU (the auto-selected path), the dense
    flax bf16 policy off-chip (there the kernel would run interpret mode,
    correct but meaningless to time) — and on a chip-compile surprise in
    the fused kernel falls back to the dense recipe and says so in
    ``policy_path`` rather than losing the BENCH line."""
    from rl_scheduler_tpu.agent.ppo import make_ppo_bundle
    from rl_scheduler_tpu.agent.train_ppo import make_bundle_and_net
    from rl_scheduler_tpu.ops.gae import default_platform

    def build(fused: bool):
        bundle, net = make_bundle_and_net(
            "cluster_set", cfg, num_nodes=FLEET_NODES,
            fused_set_block=fused, scenario=scenario, mixture=mixture)
        return make_ppo_bundle(bundle, cfg, net=net)

    on_tpu = default_platform() == "tpu"
    policy_path = "fused_block" if on_tpu else "flax_bf16"
    init_fn, update_fn, _ = build(fused=on_tpu)
    try:
        steps_per_sec = _window_steps_per_sec(init_fn, update_fn,
                                              cfg.batch_size)
    except Exception as e:  # noqa: BLE001 — the metric must not vanish
        if not on_tpu:
            raise
        policy_path = f"flax_bf16 (fused_block failed: {type(e).__name__})"
        init_fn, update_fn, _ = build(fused=False)
        steps_per_sec = _window_steps_per_sec(init_fn, update_fn,
                                              cfg.batch_size)
    return steps_per_sec, policy_path


def fleet_metric() -> dict:
    """set_fleet64 steady-state env-steps/s — the axis where perf work
    remains (round-5 VERDICT): same recipe the preset trains (1024 envs x
    64 nodes, 1 epoch, bf16), same fetch-synced window methodology as the
    headline number."""
    from rl_scheduler_tpu.agent.presets import PPO_PRESETS

    steps_per_sec, policy_path = _fleet_window(PPO_PRESETS["set_fleet64"])
    return {
        "metric": "set_fleet64 env-steps/sec/chip "
                  "(1024 envs x 64 nodes, fused PPO update)",
        "value": round(steps_per_sec, 1),
        "unit": "env-steps/sec/chip",
        "policy_path": policy_path,
    }


def fleet_overlap_metric() -> dict:
    """set_fleet64 steady-state with graftpipe on (docs/roofline.md):
    overlapped collect/learn + fused update prologue, same recipe and
    fetch-synced window methodology as :func:`fleet_metric` — the
    driver-tracked line for the pipelined update."""
    import dataclasses

    from rl_scheduler_tpu.agent.presets import PPO_PRESETS

    cfg = dataclasses.replace(PPO_PRESETS["set_fleet64"],
                              overlap_collect=True)
    steps_per_sec, policy_path = _fleet_window(cfg)
    return {
        "metric": "set_fleet64_overlap env-steps/sec/chip "
                  "(1024 envs x 64 nodes, pipelined PPO update)",
        "value": round(steps_per_sec, 1),
        "unit": "env-steps/sec/chip",
        "policy_path": policy_path,
        "overlap_collect": True,
        "fused_prologue": cfg.prologue_enabled,
    }


def fleet_scenario_metric(scenario_name: str = "bursty") -> dict:
    """set_fleet64 steady-state on a SCENARIO env (graftscenario,
    docs/scenarios.md) — the driver-tracked line proving scenario
    workloads ride the same fused fleet path at the same speed: identical
    recipe and window/sync methodology as :func:`fleet_metric`, with the
    CSV replay swapped for the scenario's compiled tables + per-episode
    randomization. The classic-layout families (bursty/churn/price_spike)
    keep the fleet policy path, fused kernel included."""
    from rl_scheduler_tpu.agent.presets import PPO_PRESETS
    from rl_scheduler_tpu.scenarios import get_scenario

    steps_per_sec, policy_path = _fleet_window(
        PPO_PRESETS["set_fleet64"], scenario=get_scenario(scenario_name))
    return {
        "metric": "set_fleet64_scenario env-steps/sec/chip "
                  "(1024 envs x 64 nodes, fused PPO update, scenario env)",
        "scenario": scenario_name,
        "value": round(steps_per_sec, 1),
        "unit": "env-steps/sec/chip",
        "policy_path": policy_path,
    }


def fleet_mixture_metric(mixture_name: str = "generalist") -> dict:
    """set_fleet64 steady-state on the MIXTURE env (graftmix,
    docs/scenarios.md) — the driver-tracked line for mixture-training
    steady state, beside the per-family scenario line: identical recipe
    and window/sync methodology, with the CSV replay swapped for the
    stacked per-family tables + the per-episode family draw. The
    classic-layout stack keeps the fleet policy path, fused kernel
    included — the next chip session's generalist work shows up in the
    driver's numbers."""
    from rl_scheduler_tpu.agent.presets import PPO_PRESETS
    from rl_scheduler_tpu.mixtures import get_mixture

    steps_per_sec, policy_path = _fleet_window(
        PPO_PRESETS["set_fleet64"], mixture=get_mixture(mixture_name))
    return {
        "metric": "set_fleet64_mixture env-steps/sec/chip "
                  "(1024 envs x 64 nodes, fused PPO update, mixture env)",
        "mixture": mixture_name,
        "value": round(steps_per_sec, 1),
        "unit": "env-steps/sec/chip",
        "policy_path": policy_path,
    }


def scenario_train_bench(num_nodes: int = FLEET_NODES,
                         num_envs: int = 32, rollout_steps: int = 25,
                         iters: int = 3, repeats: int = 6) -> dict:
    """Training-path throughput A/B: env-steps/s of the FULL vmapped PPO
    update (rollout + GAE + SGD — the unit every BENCH line tracks) on
    each scenario family vs the CSV replay, at a container-CPU-tractable
    slice of the fleet recipe (N=64 nodes, flax bf16 set policy, one
    epoch — set_fleet64's shape with the env batch/rollout scaled down so
    six full update compiles fit a container run; the per-update program
    structure, which is what the scenario swap could perturb, is
    unchanged).

    This is the acceptance number for "fleet training speed carries
    over": in the real training program the env's stepping is a small
    slice of the update, so scenario table gathers/masks must show up as
    noise here even where the isolated env-step microbench (also
    reported, as ``env_step``) sees them. Pin BLAS to one thread on the
    container before trusting small deltas.
    """
    import dataclasses

    import jax

    from rl_scheduler_tpu.agent.ppo import make_ppo_bundle
    from rl_scheduler_tpu.agent.presets import PPO_PRESETS
    from rl_scheduler_tpu.agent.train_ppo import make_bundle_and_net
    from rl_scheduler_tpu.scenarios import get_scenario, list_scenarios
    from rl_scheduler_tpu.utils.profiling import fetch_sync

    cfg = dataclasses.replace(
        PPO_PRESETS["set_fleet64"], num_envs=num_envs,
        rollout_steps=rollout_steps, minibatch_size=num_envs * rollout_steps)

    # Build + warm EVERY variant up front, then time them INTERLEAVED
    # round-robin (best-of per variant): per-variant sequential timing is
    # drift-dominated on the container — same-order reruns measured the
    # same code anywhere from 0.5x to 1.35x, while cache/frequency drift
    # hits interleaved variants equally (the repo's measurement
    # discipline, e.g. the preset-note A/Bs and the graftserve rounds).
    from rl_scheduler_tpu.mixtures import get_mixture

    variants = {"csv": None}
    variants.update({name: get_scenario(name) for name in list_scenarios()})
    # graftmix: the mixture row — same interleaved methodology, same
    # 10% acceptance bar as the per-family rows (the per-episode family
    # draw + stacked-table gathers must amortize to noise in the full
    # update, like every other scenario's table work).
    variants["mixture"] = get_mixture("generalist")
    runners, updates = {}, {}
    for name, scenario in variants.items():
        if name == "mixture":
            bundle, net = make_bundle_and_net(
                "cluster_set", cfg, num_nodes=num_nodes, mixture=scenario)
        else:
            bundle, net = make_bundle_and_net(
                "cluster_set", cfg, num_nodes=num_nodes, scenario=scenario)
        init_fn, update_fn, _ = make_ppo_bundle(bundle, cfg, net=net)
        runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
        update = jax.jit(
            lambda r, _u=update_fn: jax.lax.scan(
                lambda rr, _: _u(rr), r, None, length=iters),
            donate_argnums=0)
        runner, _ = update(runner)          # compile + one warm window
        fetch_sync(runner.params)
        runners[name], updates[name] = runner, update
    best = {name: float("inf") for name in variants}
    for _ in range(repeats):
        for name in variants:
            t0 = time.perf_counter()
            runners[name], _ = updates[name](runners[name])
            fetch_sync(runners[name].params)
            best[name] = min(best[name], time.perf_counter() - t0)
    sps = {name: cfg.batch_size * iters / b for name, b in best.items()}
    out = {
        "schema_version": 1,
        "metric": "scenario_train_throughput",
        "num_nodes": num_nodes,
        "num_envs": num_envs,
        "rollout_steps": rollout_steps,
        "interleaved_repeats": repeats,
        "baseline_csv_steps_per_sec": round(sps["csv"], 1),
        "scenarios": {
            name: {"steps_per_sec": round(sps[name], 1),
                   "vs_csv": round(sps[name] / sps["csv"], 3)}
            for name in variants if name != "csv"
        },
        "backend": jax.devices()[0].platform,
    }
    return out


def scenario_env_step_bench(num_nodes: int = FLEET_NODES,
                            num_envs: int = 64, steps: int = 400,
                            repeats: int = 10) -> dict:
    """Isolated env-step microbench (random actions, no policy): the
    scenario families' own stepping cost vs the CSV replay — a
    diagnostic companion to :func:`scenario_train_bench`, NOT the
    acceptance number (an env paying an extra table gather is visible
    here and invisible in the training program). Same fetch-synced,
    INTERLEAVED best-of-N methodology as :func:`scenario_train_bench`.
    """
    import jax
    import jax.numpy as jnp

    from rl_scheduler_tpu.env import cluster_set as cs
    from rl_scheduler_tpu.env.bundle import cluster_set_bundle
    from rl_scheduler_tpu.scenarios import (
        get_scenario,
        list_scenarios,
        scenario_bundle,
    )
    from rl_scheduler_tpu.utils.profiling import fetch_sync

    def build(bundle):
        def body(carry, _):
            st, k = carry
            k, ak = jax.random.split(k)
            actions = jax.random.randint(
                ak, (num_envs,), 0, bundle.num_actions, jnp.int32)
            st, ts = bundle.step_batch(st, actions)
            return (st, k), ts.reward

        @jax.jit
        def run(st, k):
            (st, k), rewards = jax.lax.scan(body, (st, k), None,
                                            length=steps)
            return st, k, rewards.sum()

        state, _ = bundle.reset_batch(jax.random.PRNGKey(0), num_envs)
        key = jax.random.PRNGKey(1)
        state, key, total = run(state, key)   # warmup: compile + window
        fetch_sync(total)
        return [run, state, key]

    from rl_scheduler_tpu.mixtures import (
        get_mixture,
        mixture_bundle,
        mixture_set_params,
    )

    variants = {
        "csv": cluster_set_bundle(cs.make_params(num_nodes=num_nodes))}
    variants.update({name: scenario_bundle(get_scenario(name), num_nodes)
                     for name in list_scenarios()})
    variants["mixture"] = mixture_bundle(
        mixture_set_params(get_mixture("generalist"), num_nodes))
    built = {name: build(b) for name, b in variants.items()}
    best = {name: float("inf") for name in variants}
    for _ in range(repeats):
        for name, slot in built.items():
            run, state, key = slot
            t0 = time.perf_counter()
            state, key, total = run(state, key)
            fetch_sync(total)
            best[name] = min(best[name], time.perf_counter() - t0)
            slot[1], slot[2] = state, key
    sps = {name: num_envs * steps / b for name, b in best.items()}
    return {
        "schema_version": 1,
        "metric": "scenario_env_step_throughput",
        "num_nodes": num_nodes,
        "num_envs": num_envs,
        "steps_per_window": steps,
        "interleaved_repeats": repeats,
        "baseline_csv_steps_per_sec": round(sps["csv"], 1),
        "scenarios": {
            name: {"steps_per_sec": round(sps[name], 1),
                   "vs_csv": round(sps[name] / sps["csv"], 3)}
            for name in variants if name != "csv"
        },
        "backend": jax.devices()[0].platform,
    }


def overlap_train_bench(num_nodes: int = FLEET_NODES,
                        num_envs: int = 32, rollout_steps: int = 25,
                        iters: int = 2, repeats: int = 6,
                        epochs_list: tuple = (1, 4)) -> dict:
    """graftpipe CPU A/B (the `make overlap-bench` acceptance number):
    end-to-end update time of the two prongs — pipelined collect
    (`pipeline`), fused prologue (`prologue`), both (`overlap`) — against
    the unpipelined `baseline`, at a container-CPU-tractable slice of the
    set_fleet64 recipe (flax bf16 set policy at N=64, minibatch = B/4 so
    the epoch shuffle is a real multi-minibatch path, window of ``iters``
    updates in ONE `lax.scan` dispatch — the program shape where the
    pipeline's broken dependency is visible to the scheduler). Interleaved
    best-of-N timing, fetch-synced (the repo's measurement discipline).

    ``epochs_list`` with two points also fits the intercept decomposition
    per variant: per-update time = sgd_ms_per_epoch * epochs +
    intercept_ms — the intercept (rollout + GAE + shuffle + fixed work)
    is the term graftpipe exists to erase, so the A/B reports it
    directly. Read the CPU result for what it is: XLA:CPU has no
    latency-hiding scheduler, so the `pipeline` prong's win is a CHIP
    claim (one-command recipe in docs/roofline.md); the CPU line pins
    composition and the prologue's op-count delta honestly.
    """
    import dataclasses

    import jax

    from rl_scheduler_tpu.agent.ppo import make_ppo_bundle
    from rl_scheduler_tpu.agent.presets import PPO_PRESETS
    from rl_scheduler_tpu.agent.train_ppo import make_bundle_and_net
    from rl_scheduler_tpu.utils.profiling import fetch_sync

    variants = {
        "baseline": dict(overlap_collect=False, fused_prologue="off"),
        "pipeline": dict(overlap_collect=True, fused_prologue="off"),
        "prologue": dict(overlap_collect=False, fused_prologue="on"),
        "overlap": dict(overlap_collect=True, fused_prologue="auto"),
    }
    cells = {}
    for epochs in epochs_list:
        cfg0 = dataclasses.replace(
            PPO_PRESETS["set_fleet64"], num_envs=num_envs,
            rollout_steps=rollout_steps,
            minibatch_size=max(1, num_envs * rollout_steps // 4),
            num_epochs=epochs)
        for name, overlay in variants.items():
            cfg = dataclasses.replace(cfg0, **overlay)
            bundle, net = make_bundle_and_net("cluster_set", cfg,
                                              num_nodes=num_nodes)
            init_fn, update_fn, _ = make_ppo_bundle(bundle, cfg, net=net)
            runner = jax.jit(init_fn)(jax.random.PRNGKey(0))
            update = jax.jit(
                lambda r, _u=update_fn: jax.lax.scan(
                    lambda rr, _: _u(rr), r, None, length=iters),
                donate_argnums=0)
            runner, _ = update(runner)      # compile + one warm window
            fetch_sync(runner.params)
            cells[(name, epochs)] = [runner, update, float("inf")]
    for _ in range(repeats):
        for key, cell in cells.items():
            runner, update, best = cell
            t0 = time.perf_counter()
            runner, _ = update(runner)
            fetch_sync(runner.params)
            cell[0] = runner
            cell[2] = min(best, time.perf_counter() - t0)
    per_update = {k: cell[2] / iters * 1e3 for k, cell in cells.items()}
    e_lo, e_hi = min(epochs_list), max(epochs_list)
    out_variants = {}
    for name in variants:
        row = {f"per_update_ms_{e}ep": round(per_update[(name, e)], 1)
               for e in epochs_list}
        row["vs_baseline_1ep"] = round(
            per_update[("baseline", e_lo)] / per_update[(name, e_lo)], 3)
        if e_hi > e_lo:
            slope = (per_update[(name, e_hi)] - per_update[(name, e_lo)]) \
                / (e_hi - e_lo)
            row["sgd_ms_per_epoch"] = round(slope, 1)
            row["intercept_ms"] = round(
                per_update[(name, e_lo)] - slope * e_lo, 1)
        out_variants[name] = row
    return {
        "schema_version": 1,
        "metric": "overlap_train_bench",
        "num_nodes": num_nodes,
        "num_envs": num_envs,
        "rollout_steps": rollout_steps,
        "epochs_list": list(epochs_list),
        "window_iters": iters,
        "interleaved_repeats": repeats,
        "variants": out_variants,
        "backend": jax.devices()[0].platform,
    }


def graftscope_ab(preset: str = "tpu4096") -> dict:
    """Same-process A/B (ISSUE 4 acceptance): the graftscope-instrumented
    train window vs the uninstrumented one, identical fetch-synced window
    methodology. The instrumented update compiles the full PPO scope spec
    in (Welford stats, grad-norm/ratio/advantage/action histograms); the
    scan window stacks its per-iteration MetricsState exactly as a fused
    dispatch does. Acceptance: overhead_pct within 2 at config 3
    (``preset="tpu4096"``, the default — run it on the chip; the config-3
    windows do not finish in tractable time on the CPU container, where
    ``--ab-preset tpu64`` is the same-methodology stand-in)."""
    import jax

    from rl_scheduler_tpu.agent.ppo import make_ppo
    from rl_scheduler_tpu.agent.presets import PPO_PRESETS
    from rl_scheduler_tpu.config import EnvConfig
    from rl_scheduler_tpu.env import core as env_core
    from rl_scheduler_tpu.utils.metrics import ppo_scope_spec

    cfg = PPO_PRESETS[preset]
    env_params = env_core.make_params(EnvConfig())

    init_fn, update_fn, _ = make_ppo(env_params, cfg)
    plain = _window_steps_per_sec(init_fn, update_fn, cfg.batch_size)

    spec = ppo_scope_spec(env_core.NUM_ACTIONS)
    init_fn, update_fn, _ = make_ppo(env_params, cfg, scope=spec)
    scoped = _window_steps_per_sec(init_fn, update_fn, cfg.batch_size)

    overhead_pct = (plain - scoped) / plain * 100.0
    return {
        "metric": f"graftscope A/B overhead ({preset}, fetch-synced windows)",
        "preset": preset,
        "plain_steps_per_sec": round(plain, 1),
        "instrumented_steps_per_sec": round(scoped, 1),
        "overhead_pct": round(overhead_pct, 2),
        "backend": jax.devices()[0].platform,
        "within_2pct": bool(overhead_pct <= 2.0),
    }


def main(argv: list | None = None) -> None:
    import argparse

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--graftscope-ab", action="store_true",
                   help="print ONE JSON line instead: instrumented-vs-"
                        "plain window throughput "
                        "(docs/observability.md A/B)")
    p.add_argument("--ab-preset", default="tpu4096",
                   help="PPO preset for the A/B (default tpu4096 = "
                        "config 3, the acceptance config — chip-sized; "
                        "use tpu64 on the CPU container)")
    p.add_argument("--scenario-bench", action="store_true",
                   help="print TWO JSON lines instead: training-path "
                        "env-steps/s of every scenario family vs the "
                        "CSV-replay baseline (the acceptance A/B) plus "
                        "the isolated env-step microbench, both at fleet "
                        "N (CPU-container-tractable; docs/scenarios.md)")
    p.add_argument("--overlap-bench", action="store_true",
                   help="print ONE JSON line instead: the graftpipe "
                        "baseline/pipeline/prologue/overlap update-time "
                        "A/B with per-variant intercept decomposition, "
                        "at a CPU-container-tractable slice of the "
                        "set_fleet64 recipe (docs/roofline.md; "
                        "`make overlap-bench` runs this BLAS-pinned)")
    args = p.parse_args(argv)
    if args.graftscope_ab:
        print(json.dumps(graftscope_ab(args.ab_preset)), flush=True)
        return
    if args.scenario_bench:
        print(json.dumps(scenario_train_bench()), flush=True)
        print(json.dumps(scenario_env_step_bench()), flush=True)
        return
    if args.overlap_bench:
        print(json.dumps(overlap_train_bench()), flush=True)
        return
    print(json.dumps(headline_metric()), flush=True)
    print(json.dumps(fleet_metric()), flush=True)
    print(json.dumps(fleet_scenario_metric()), flush=True)
    print(json.dumps(fleet_overlap_metric()), flush=True)
    print(json.dumps(fleet_mixture_metric()), flush=True)


if __name__ == "__main__":
    main()
