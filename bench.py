"""Headline benchmark: env-steps/sec/chip at 4096 parallel simulated clusters,
plus the fleet-scale set_fleet64 steady-state metric.

Runs the fused PPO train step (rollout + GAE + minibatch SGD in one XLA
program) and reports env-steps/sec on one chip over the best of three
20-iteration windows. Each window is ONE dispatched program (``lax.scan``
over the update), so per-dispatch/tunnel overhead is amortized 20x, and the
window is closed by fetching a metric value to the host —
``jax.device_get`` — because ``jax.block_until_ready`` does NOT reliably
synchronize on tunneled backends (round-3 finding: it returned before
execution finished, making op-level timings meaningless; fetching a value
that depends on the computation is the only trustworthy sync).

Baseline: the reference's Ray RLlib pipeline sustains ~60 env-steps/s on
its documented hardware (SURVEY.md §6: 640k steps in ~3h).

Prints TWO JSON lines:

1. the config-3 headline {"metric", "value", "unit", "vs_baseline"} —
   unchanged schema, always first;
2. the set_fleet64 fleet metric (1024 envs x 64 nodes, the regime where
   perf work remains — docs/roofline.md fleet rows), same window/sync
   methodology, with a "policy_path" key recording which cluster_set
   policy ran: the whole-network fused Pallas kernel on TPU (the fleet
   preset's auto-selected path) or the dense flax bf16 policy elsewhere.
"""

from __future__ import annotations

import json
import time

BASELINE_STEPS_PER_SEC = 60.0
FLEET_NODES = 64


def _window_steps_per_sec(init_fn, update_fn, batch_size: int,
                          iters: int = 20, repeats: int = 3) -> float:
    """Best-of-N fetch-synced window throughput (module docstring)."""
    import jax

    from rl_scheduler_tpu.utils.profiling import fetch_sync

    runner = jax.jit(init_fn)(jax.random.PRNGKey(0))

    def window(r):
        return jax.lax.scan(lambda rr, _: update_fn(rr), r, None, length=iters)

    update = jax.jit(window, donate_argnums=0)

    def sync(r) -> float:
        # Fetch over the PARAMS: they depend on EVERY SGD phase of the
        # window including the last iteration's (a metric like reward_mean
        # would not cover the final SGD tail), so this provably waits for
        # the whole window on every backend. The sync-by-fetching
        # discipline itself lives in utils/profiling.fetch_sync (shared
        # with StepTimer) — see that docstring for why block_until_ready
        # is not trusted here.
        return fetch_sync(r.params)

    # Warmup: compile + one full window.
    runner, metrics = update(runner)
    sync(runner)

    best_elapsed = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        runner, metrics = update(runner)
        sync(runner)
        best_elapsed = min(best_elapsed, time.perf_counter() - t0)
    return batch_size * iters / best_elapsed


def headline_metric() -> dict:
    from rl_scheduler_tpu.agent.ppo import make_ppo
    from rl_scheduler_tpu.agent.presets import PPO_PRESETS
    from rl_scheduler_tpu.config import EnvConfig
    from rl_scheduler_tpu.env import core as env_core

    cfg = PPO_PRESETS["tpu4096"]
    env_params = env_core.make_params(EnvConfig())
    init_fn, update_fn, _ = make_ppo(env_params, cfg)
    steps_per_sec = _window_steps_per_sec(init_fn, update_fn, cfg.batch_size)
    return {
        "metric": "env-steps/sec/chip (4096 parallel clusters, fused PPO update)",
        "value": round(steps_per_sec, 1),
        "unit": "env-steps/sec/chip",
        "vs_baseline": round(steps_per_sec / BASELINE_STEPS_PER_SEC, 1),
    }


def fleet_metric() -> dict:
    """set_fleet64 steady-state env-steps/s — the axis where perf work
    remains (round-5 VERDICT): same recipe the preset trains (1024 envs x
    64 nodes, 1 epoch, bf16), same fetch-synced window methodology as the
    headline number."""
    from rl_scheduler_tpu.agent.ppo import make_ppo_bundle
    from rl_scheduler_tpu.agent.presets import PPO_PRESETS
    from rl_scheduler_tpu.agent.train_ppo import make_bundle_and_net
    from rl_scheduler_tpu.ops.gae import default_platform

    cfg = PPO_PRESETS["set_fleet64"]

    def build(fused: bool):
        # The exact policy the preset trains (agent/train_ppo.py builds
        # it from the same cfg): the whole-network fused kernel on TPU
        # (the auto-selected path), the dense flax bf16 policy off-chip —
        # there the kernel would run interpret mode, correct but
        # meaningless to time.
        bundle, net = make_bundle_and_net(
            "cluster_set", cfg, num_nodes=FLEET_NODES,
            fused_set_block=fused)
        return make_ppo_bundle(bundle, cfg, net=net)

    on_tpu = default_platform() == "tpu"
    policy_path = "fused_block" if on_tpu else "flax_bf16"
    init_fn, update_fn, _ = build(fused=on_tpu)
    try:
        steps_per_sec = _window_steps_per_sec(init_fn, update_fn,
                                              cfg.batch_size)
    except Exception as e:  # noqa: BLE001 — the metric must not vanish
        if not on_tpu:
            raise
        # A chip-compile surprise in the fused kernel must not cost the
        # BENCH line: fall back to the dense recipe and say so.
        policy_path = f"flax_bf16 (fused_block failed: {type(e).__name__})"
        init_fn, update_fn, _ = build(fused=False)
        steps_per_sec = _window_steps_per_sec(init_fn, update_fn,
                                              cfg.batch_size)
    return {
        "metric": "set_fleet64 env-steps/sec/chip "
                  "(1024 envs x 64 nodes, fused PPO update)",
        "value": round(steps_per_sec, 1),
        "unit": "env-steps/sec/chip",
        "policy_path": policy_path,
    }


def graftscope_ab(preset: str = "tpu4096") -> dict:
    """Same-process A/B (ISSUE 4 acceptance): the graftscope-instrumented
    train window vs the uninstrumented one, identical fetch-synced window
    methodology. The instrumented update compiles the full PPO scope spec
    in (Welford stats, grad-norm/ratio/advantage/action histograms); the
    scan window stacks its per-iteration MetricsState exactly as a fused
    dispatch does. Acceptance: overhead_pct within 2 at config 3
    (``preset="tpu4096"``, the default — run it on the chip; the config-3
    windows do not finish in tractable time on the CPU container, where
    ``--ab-preset tpu64`` is the same-methodology stand-in)."""
    import jax

    from rl_scheduler_tpu.agent.ppo import make_ppo
    from rl_scheduler_tpu.agent.presets import PPO_PRESETS
    from rl_scheduler_tpu.config import EnvConfig
    from rl_scheduler_tpu.env import core as env_core
    from rl_scheduler_tpu.utils.metrics import ppo_scope_spec

    cfg = PPO_PRESETS[preset]
    env_params = env_core.make_params(EnvConfig())

    init_fn, update_fn, _ = make_ppo(env_params, cfg)
    plain = _window_steps_per_sec(init_fn, update_fn, cfg.batch_size)

    spec = ppo_scope_spec(env_core.NUM_ACTIONS)
    init_fn, update_fn, _ = make_ppo(env_params, cfg, scope=spec)
    scoped = _window_steps_per_sec(init_fn, update_fn, cfg.batch_size)

    overhead_pct = (plain - scoped) / plain * 100.0
    return {
        "metric": f"graftscope A/B overhead ({preset}, fetch-synced windows)",
        "preset": preset,
        "plain_steps_per_sec": round(plain, 1),
        "instrumented_steps_per_sec": round(scoped, 1),
        "overhead_pct": round(overhead_pct, 2),
        "backend": jax.devices()[0].platform,
        "within_2pct": bool(overhead_pct <= 2.0),
    }


def main(argv: list | None = None) -> None:
    import argparse

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--graftscope-ab", action="store_true",
                   help="print ONE JSON line instead: instrumented-vs-"
                        "plain window throughput "
                        "(docs/observability.md A/B)")
    p.add_argument("--ab-preset", default="tpu4096",
                   help="PPO preset for the A/B (default tpu4096 = "
                        "config 3, the acceptance config — chip-sized; "
                        "use tpu64 on the CPU container)")
    args = p.parse_args(argv)
    if args.graftscope_ab:
        print(json.dumps(graftscope_ab(args.ab_preset)), flush=True)
        return
    print(json.dumps(headline_metric()), flush=True)
    print(json.dumps(fleet_metric()), flush=True)


if __name__ == "__main__":
    main()
