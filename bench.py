"""Headline benchmark: env-steps/sec/chip at 4096 parallel simulated clusters,
plus the fleet-scale set_fleet64 steady-state metric.

Runs the fused PPO train step (rollout + GAE + minibatch SGD in one XLA
program) and reports env-steps/sec on one chip over the best of three
20-iteration windows. Each window is ONE dispatched program (``lax.scan``
over the update), so per-dispatch/tunnel overhead is amortized 20x, and the
window is closed by fetching a metric value to the host —
``jax.device_get`` — because ``jax.block_until_ready`` does NOT reliably
synchronize on tunneled backends (round-3 finding: it returned before
execution finished, making op-level timings meaningless; fetching a value
that depends on the computation is the only trustworthy sync).

Baseline: the reference's Ray RLlib pipeline sustains ~60 env-steps/s on
its documented hardware (SURVEY.md §6: 640k steps in ~3h).

Prints TWO JSON lines:

1. the config-3 headline {"metric", "value", "unit", "vs_baseline"} —
   unchanged schema, always first;
2. the set_fleet64 fleet metric (1024 envs x 64 nodes, the regime where
   perf work remains — docs/roofline.md fleet rows), same window/sync
   methodology, with a "policy_path" key recording which cluster_set
   policy ran: the whole-network fused Pallas kernel on TPU (the fleet
   preset's auto-selected path) or the dense flax bf16 policy elsewhere.
"""

from __future__ import annotations

import json
import time

BASELINE_STEPS_PER_SEC = 60.0
FLEET_NODES = 64


def _window_steps_per_sec(init_fn, update_fn, batch_size: int,
                          iters: int = 20, repeats: int = 3) -> float:
    """Best-of-N fetch-synced window throughput (module docstring)."""
    import jax

    runner = jax.jit(init_fn)(jax.random.PRNGKey(0))

    def window(r):
        return jax.lax.scan(lambda rr, _: update_fn(rr), r, None, length=iters)

    update = jax.jit(window, donate_argnums=0)

    def sync(r) -> float:
        # Fetch a parameter value: params depend on EVERY SGD phase of the
        # window including the last iteration's (a metric like reward_mean
        # would not cover the final SGD tail), so this provably waits for
        # the whole window on every backend (see module docstring).
        leaf = jax.tree.leaves(r.params)[0]
        return float(jax.device_get(leaf).ravel()[0])

    # Warmup: compile + one full window.
    runner, metrics = update(runner)
    sync(runner)

    best_elapsed = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        runner, metrics = update(runner)
        sync(runner)
        best_elapsed = min(best_elapsed, time.perf_counter() - t0)
    return batch_size * iters / best_elapsed


def headline_metric() -> dict:
    from rl_scheduler_tpu.agent.ppo import make_ppo
    from rl_scheduler_tpu.agent.presets import PPO_PRESETS
    from rl_scheduler_tpu.config import EnvConfig
    from rl_scheduler_tpu.env import core as env_core

    cfg = PPO_PRESETS["tpu4096"]
    env_params = env_core.make_params(EnvConfig())
    init_fn, update_fn, _ = make_ppo(env_params, cfg)
    steps_per_sec = _window_steps_per_sec(init_fn, update_fn, cfg.batch_size)
    return {
        "metric": "env-steps/sec/chip (4096 parallel clusters, fused PPO update)",
        "value": round(steps_per_sec, 1),
        "unit": "env-steps/sec/chip",
        "vs_baseline": round(steps_per_sec / BASELINE_STEPS_PER_SEC, 1),
    }


def fleet_metric() -> dict:
    """set_fleet64 steady-state env-steps/s — the axis where perf work
    remains (round-5 VERDICT): same recipe the preset trains (1024 envs x
    64 nodes, 1 epoch, bf16), same fetch-synced window methodology as the
    headline number."""
    from rl_scheduler_tpu.agent.ppo import make_ppo_bundle
    from rl_scheduler_tpu.agent.presets import PPO_PRESETS
    from rl_scheduler_tpu.agent.train_ppo import make_bundle_and_net
    from rl_scheduler_tpu.ops.gae import default_platform

    cfg = PPO_PRESETS["set_fleet64"]

    def build(fused: bool):
        # The exact policy the preset trains (agent/train_ppo.py builds
        # it from the same cfg): the whole-network fused kernel on TPU
        # (the auto-selected path), the dense flax bf16 policy off-chip —
        # there the kernel would run interpret mode, correct but
        # meaningless to time.
        bundle, net = make_bundle_and_net(
            "cluster_set", cfg, num_nodes=FLEET_NODES,
            fused_set_block=fused)
        return make_ppo_bundle(bundle, cfg, net=net)

    on_tpu = default_platform() == "tpu"
    policy_path = "fused_block" if on_tpu else "flax_bf16"
    init_fn, update_fn, _ = build(fused=on_tpu)
    try:
        steps_per_sec = _window_steps_per_sec(init_fn, update_fn,
                                              cfg.batch_size)
    except Exception as e:  # noqa: BLE001 — the metric must not vanish
        if not on_tpu:
            raise
        # A chip-compile surprise in the fused kernel must not cost the
        # BENCH line: fall back to the dense recipe and say so.
        policy_path = f"flax_bf16 (fused_block failed: {type(e).__name__})"
        init_fn, update_fn, _ = build(fused=False)
        steps_per_sec = _window_steps_per_sec(init_fn, update_fn,
                                              cfg.batch_size)
    return {
        "metric": "set_fleet64 env-steps/sec/chip "
                  "(1024 envs x 64 nodes, fused PPO update)",
        "value": round(steps_per_sec, 1),
        "unit": "env-steps/sec/chip",
        "policy_path": policy_path,
    }


def main() -> None:
    print(json.dumps(headline_metric()), flush=True)
    print(json.dumps(fleet_metric()), flush=True)


if __name__ == "__main__":
    main()
