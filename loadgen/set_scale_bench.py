"""Same-process A/B of config-4 policy paths across node counts.

VERDICT r4 items 1/3/4: every measured structured-policy number was at
N=8, while the domain's real scaling axis is the node set. This tool
measures the per-update device time of the cluster_set PPO update at
fleet node counts (N=64, 256, ...) for the candidate policy paths, in
ONE process with variants interleaved — the only honest comparison on
the shared TPU pool (absolute numbers swing 2-50x between processes;
ratios within a process hold — docs/status.md methodology note).

Timing is window-slope + fetch sync: each sample jits a ``lax.scan``
window of K updates and fetches a param leaf (``jax.device_get``) to
close it — ``block_until_ready`` does NOT synchronize on tunneled
backends. The slope between a K=1 and a K=5 window is the per-update
device time, net of the fixed dispatch/tunnel overhead.

Usage::

    python loadgen/set_scale_bench.py --nodes 64 --envs 1024 \
        --minibatch 8192 --variants flax_bf16,fused
    python loadgen/set_scale_bench.py --nodes 8,16,32,64,128,256 \
        --scale-envs 65536 --variants flax_bf16   # scaling curve
    python loadgen/set_scale_bench.py --nodes 64 --envs 1024 \
        --minibatch 12800 --variants flax_bf16,fused_block
        # the fused-block A/B at the set_fleet64 recipe (run ON TPU:
        # off-chip the kernel interprets and the timing is meaningless)
    python loadgen/set_scale_bench.py --nodes 64 --envs 1024 \
        --minibatch 12800 --epochs 1,4 \
        --variants flax_bf16,pipeline,prologue,overlap
        # the graftpipe chip decomposition (docs/roofline.md): per-prong
        # update time AND, via the epochs sweep's slope/intercept fit,
        # how much of the non-SGD intercept each prong erased. The
        # update-path variants: overlap (both prongs), pipeline
        # (1-stale collect only), prologue (fused prologue only),
        # fused_block_overlap (pipeline composed with the fused kernel)

Prints one JSON line per (nodes, variant): per-update ms, env-steps/s,
and the window times it derives from.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

# Runnable as `python loadgen/set_scale_bench.py` from the repo root
# without installing the package (same pattern as extender_bench.py).
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def build_update(nodes: int, envs: int, minibatch: int, epochs: int,
                 variant: str, rollout_steps: int = 100):
    import jax
    import jax.numpy as jnp

    from rl_scheduler_tpu.agent.ppo import PPOTrainConfig, make_ppo_bundle
    from rl_scheduler_tpu.env import cluster_set as cs
    from rl_scheduler_tpu.env.bundle import cluster_set_bundle

    # graftpipe update-path variants (docs/roofline.md): `overlap` = both
    # prongs on the flax bf16 policy, `pipeline`/`prologue` pin one prong
    # each for the per-prong decomposition, `fused_block_overlap`
    # composes the pipeline with the whole-network fused kernel (the
    # fleet presets' TPU path). The policy is orthogonal to the update
    # pipeline, so these reuse the policy variants below; an --epochs
    # 1,4 sweep then separates each variant's SGD slope from the
    # intercept graftpipe attacks.
    graftpipe = {
        "overlap": ("flax_bf16", dict(overlap_collect=True)),
        "pipeline": ("flax_bf16",
                     dict(overlap_collect=True, fused_prologue="off")),
        "prologue": ("flax_bf16", dict(fused_prologue="on")),
        "fused_block_overlap": ("fused_block", dict(overlap_collect=True)),
    }
    cfg_overlay = {}
    if variant in graftpipe:
        variant, cfg_overlay = graftpipe[variant]
    # NOTE: every variant below passes an explicit net, so
    # cfg.compute_dtype is inert (it only shapes the default ActorCritic
    # — agent/ppo.py:191-206); the net's own dtype field carries the
    # precision. Kept in sync anyway so the printed config is honest.
    cfg = PPOTrainConfig(
        num_envs=envs, rollout_steps=rollout_steps,
        minibatch_size=minibatch, num_epochs=epochs, lr=1e-3, gamma=0.99,
        compute_dtype="float32" if variant == "flax_f32" else "bfloat16",
        **cfg_overlay,
    )
    bundle = cluster_set_bundle(cs.make_params(num_nodes=nodes))
    fused_impls = {"fused": None, "fused_chunked": "chunked",
                   "fused_matmul": "matmul"}
    if variant == "fused_block":
        # The whole-network fused Pallas kernel (ops/pallas_set_block.py)
        # — the --fused-set-block path the fleet presets auto-select on
        # TPU. Off-chip this runs interpret mode: numerically the same
        # path, but its timing measures the interpreter, not the chip.
        from rl_scheduler_tpu.models.set_fast import FusedBlockSetPolicy

        net = FusedBlockSetPolicy(num_nodes=nodes, dim=64, depth=2,
                                  dtype=jnp.bfloat16)
    elif variant in fused_impls:
        from rl_scheduler_tpu.models.set_fast import BatchMinorSetPolicy

        # "fused" = auto attention formulation (by node count);
        # "fused_chunked" / "fused_matmul" pin one (A/B the threshold).
        net = BatchMinorSetPolicy(dim=64, depth=2, dtype=jnp.bfloat16,
                                  attn_impl=fused_impls[variant])
    elif variant in ("flax_f32", "flax_bf16", "flax_bf16_h4"):
        from rl_scheduler_tpu.models import SetTransformerPolicy

        net = SetTransformerPolicy(
            dim=64, depth=2,
            num_heads=4 if variant.endswith("_h4") else 1,
            dtype=None if variant == "flax_f32" else jnp.bfloat16,
        )
    else:
        raise SystemExit(f"unknown variant {variant!r}")
    init_fn, update_fn, _ = make_ppo_bundle(bundle, cfg, net=net)
    runner = jax.jit(init_fn)(jax.random.PRNGKey(0))

    def window(k):
        def body(r):
            return jax.lax.scan(lambda rr, _: update_fn(rr), r, None,
                                length=k)[0]
        return jax.jit(body, donate_argnums=0)

    return runner, window


def sync(runner) -> float:
    import jax

    leaf = jax.tree.leaves(runner.params)[0]
    return float(jax.device_get(leaf).ravel()[0])


def measure(nodes: int, envs: int, minibatch: int, epochs: int,
            variants: list[str], k_small: int, k_big: int,
            repeats: int, rollout_steps: int) -> list[dict]:
    setups = {}
    for v in variants:
        runner, window = build_update(nodes, envs, minibatch, epochs, v,
                                      rollout_steps)
        w_small, w_big = window(k_small), window(k_big)
        # Warm both executables (compile + one run each).
        runner = w_small(runner)
        runner = w_big(runner)
        sync(runner)
        setups[v] = dict(runner=runner, w_small=w_small, w_big=w_big,
                         t_small=[], t_big=[])

    # Interleave variants within each repeat round (pool-noise fairness).
    for _ in range(repeats):
        for v in variants:
            s = setups[v]
            for key, w in (("t_small", s["w_small"]), ("t_big", s["w_big"])):
                t0 = time.perf_counter()
                s["runner"] = w(s["runner"])
                sync(s["runner"])
                s[key].append(time.perf_counter() - t0)

    rows = []
    for v in variants:
        s = setups[v]
        best_small, best_big = min(s["t_small"]), min(s["t_big"])
        per_update = (best_big - best_small) / (k_big - k_small)
        if per_update <= 0:
            # Shared-pool noise inverted the windows: flag loudly rather
            # than emit a garbage row (raise --repeats / --k-big).
            rows.append({
                "nodes": nodes, "variant": v, "envs": envs,
                "minibatch": minibatch, "epochs": epochs,
                "unreliable": "non-positive window slope",
                "window_s": {f"k{k_small}": round(best_small, 4),
                             f"k{k_big}": round(best_big, 4)},
            })
            continue
        rows.append({
            "nodes": nodes, "variant": v, "envs": envs,
            "minibatch": minibatch, "epochs": epochs,
            "per_update_ms": round(per_update * 1e3, 2),
            "env_steps_per_sec": round(envs * rollout_steps / per_update, 0),
            "window_s": {f"k{k_small}": round(best_small, 4),
                         f"k{k_big}": round(best_big, 4)},
        })
    return rows


def main(argv: list[str] | None = None) -> list[dict]:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--nodes", default="64",
                   help="comma-separated node counts")
    p.add_argument("--envs", type=int, default=None,
                   help="parallel env count (default: --scale-envs / nodes)")
    p.add_argument("--scale-envs", type=int, default=65536,
                   help="when --envs is unset, envs = scale_envs / nodes "
                        "(holds the per-update node-feature volume roughly "
                        "constant across the curve)")
    p.add_argument("--minibatch", type=int, default=None,
                   help="minibatch size (default: envs*rollout/8, the "
                        "fleet-preset ratio)")
    p.add_argument("--epochs", default="1",
                   help="comma-separated SGD epoch counts; >1 value turns "
                        "the run into a same-process epochs sweep (the "
                        "slope separates SGD cost/epoch from the "
                        "rollout+fixed intercept)")
    p.add_argument("--rollout-steps", type=int, default=100)
    p.add_argument("--variants", default="flax_bf16,fused")
    p.add_argument("--k-small", type=int, default=1)
    p.add_argument("--k-big", type=int, default=5)
    p.add_argument("--repeats", type=int, default=3)
    args = p.parse_args(argv)

    all_rows = []
    for nodes in (int(n) for n in args.nodes.split(",")):
        envs = args.envs or max(args.scale_envs // nodes, 64)
        minibatch = args.minibatch or envs * args.rollout_steps // 8
        for epochs in (int(e) for e in args.epochs.split(",")):
            rows = measure(nodes, envs, minibatch, epochs,
                           args.variants.split(","), args.k_small,
                           args.k_big, args.repeats, args.rollout_steps)
            for r in rows:
                print(json.dumps(r), flush=True)
            all_rows.extend(rows)
    return all_rows


if __name__ == "__main__":
    main()
