"""Locust load generator against the nginx workload.

Reference parity: rl-k8s-scheduler ``locustfile.py:1-9`` — each simulated
user GETs ``/`` every 1-3 s. Its CSV exports feed the data pipeline
(``rl_scheduler_tpu/data/normalize.py`` consumes the stats files the same
way the reference's ``normalize_data.py:9-15`` does).

Run (against the aws cluster's NodePort):
    locust -f loadgen/locustfile.py --host http://localhost:30000 \
        --headless -u 20 -r 5 --run-time 2m --csv data/local_aws_load
"""

try:
    from locust import HttpUser, between, task
except ImportError:  # locust is optional; the pipeline falls back to
    HttpUser = object  # synthetic load history (data/loader.py).

    def task(f):
        return f

    def between(a, b):
        return None


class NginxUser(HttpUser):
    wait_time = between(1, 3)

    @task
    def fetch_root(self):
        self.client.get("/")
