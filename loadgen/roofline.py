"""First-principles roofline floors for the BASELINE training configs.

VERDICT r3 item 4: every perf claim so far was *relative* (Nx the
reference, Nx the flax path); this tool computes what the chip could do
at best — matmul-FLOP and HBM-bandwidth floors for one PPO update of
configs 3-5, from batch sizes, layer widths, and chip peaks — and states
measured device time against them. The arithmetic is all here (and
walked through in ``docs/roofline.md``); run it to regenerate the
"% of roofline" table in ``docs/status.md``.

Chip peaks default to the bench chip (TPU v5e, public spec sheet):
197 TFLOP/s bf16 MXU, 819 GB/s HBM. Backward passes are counted as 2x
the forward matmul FLOPs (dL/dW and dL/dx each re-do a same-shape
matmul); elementwise/VPU work, layout changes, and reductions are NOT in
the floor — that is the point: the floor is what an ideal execution
would leave.

Usage::

    python loadgen/roofline.py            # the table
    python loadgen/roofline.py --tflops 197 --gbs 819
"""

from __future__ import annotations

import argparse


def mlp_matmul_flops(samples: float, obs_dim: int = 6,
                     hidden: tuple = (256, 256), heads: int = 3) -> float:
    """Forward matmul FLOPs for the flat actor-critic (policy 2 + value 1
    output units share the torso)."""
    dims = (obs_dim,) + tuple(hidden) + (heads,)
    return 2.0 * samples * sum(a * b for a, b in zip(dims[:-1], dims[1:]))


def set_matmul_flops(samples: float, nodes: int = 8, feat: int = 6,
                     dim: int = 64, depth: int = 2) -> float:
    """Forward matmul FLOPs for SetTransformerPolicy (single head).

    Per node per block: qkv (3*dim^2), attention scores+context
    (2*nodes*dim), out (dim^2), MLP (dim*2dim + 2dim*dim). Embed feat->dim;
    head: score dim->1 per node, value pool dim->dim->1.
    """
    per_node_block = 2.0 * (3 * dim * dim + 2 * nodes * dim + dim * dim
                            + dim * 2 * dim + 2 * dim * dim)
    embed = 2.0 * feat * dim * nodes
    head = 2.0 * (dim * nodes + dim * dim + dim)
    return samples * (embed + depth * nodes * per_node_block + head)


def gnn_kron_matmul_flops(samples: float, nodes: int = 8, feat: int = 7,
                          dim: int = 64, depth: int = 3) -> float:
    """Forward matmul FLOPs for the kron-flattened GNN (ops/pallas_gnn.py):
    obs [B, N*feat] @ We [N*feat, N*dim], then depth layers of
    [B, N*dim] @ [N*dim, N*dim], then score [N*dim, N] + value pool.
    The kron construction deliberately spends 4x the structural GCN FLOPs
    to keep everything one MXU-shaped matmul chain."""
    nd = nodes * dim
    embed = 2.0 * (nodes * feat) * nd
    layers = depth * 2.0 * nd * nd
    head = 2.0 * (nd * nodes + nd * dim + dim)
    return samples * (embed + layers + head)


def update_floor_ms(fwd_flops_epoch: float, fwd_flops_rollout: float,
                    epochs: int, tflops: float) -> float:
    """Matmul-time floor for one update: rollout is forward-only; each SGD
    epoch re-does forward + ~2x backward over the whole batch."""
    total = fwd_flops_rollout + epochs * 3.0 * fwd_flops_epoch
    return total / (tflops * 1e12) * 1e3


def config3_bandwidth_floor_ms(batch: float, epochs: int, hidden=(256, 256),
                               gbs: float = 819.0) -> float:
    """HBM floor for config 3's SGD phase — the flat MLP is so narrow that
    activation traffic, not FLOPs, is its binding constraint in f32.

    Per sample per epoch: forward writes h1+h2 (+tiny heads), backward
    reads them back and mirrors the traffic for gradients; obs/targets are
    a few tens of bytes. Counted as 3x the (h1+h2) f32 footprint per
    sample per epoch (write fwd, read bwd, grad traffic) — a lower bound
    that ignores optimizer state and the shuffle gather (both measured
    small)."""
    act_bytes = sum(hidden) * 4.0
    per_epoch = batch * act_bytes * 3.0
    return epochs * per_epoch / (gbs * 1e9) * 1e3


def set_bandwidth_floor_ms(batch: float, rollout_samples: float, epochs: int,
                           nodes: int = 8, dim: int = 64,
                           gbs: float = 819.0) -> float:
    """HBM floor for config 4 — this body is elementwise/traffic-bound
    (docs/status.md row 4), so the binding floor is residual-stream
    movement, not FLOPs.

    Lower bound: even with perfect elementwise fusion, the residual
    stream materializes ~6 times per forward (embed out, 2 residual adds
    per block x 2 blocks, final norm), each a write + a read of the
    ``[nodes, dim]`` bf16 activation; backward mirrors it. Counted as
    6 tensors x 2 bytes x (write+read) x (fwd+bwd) per sample per epoch,
    fwd-only for rollout samples. Attention scores ([N,N] per sample),
    optimizer state, and the shuffle gather are excluded — this is a
    floor, not an estimate."""
    tensor_bytes = nodes * dim * 2.0
    per_pass = 6 * tensor_bytes * 2.0          # materialize + consume
    sgd = epochs * batch * per_pass * 2.0      # fwd + bwd
    rollout = rollout_samples * per_pass       # fwd only
    return (sgd + rollout) / (gbs * 1e9) * 1e3


# Config 5's fused Pallas kernel holds the whole layer chain VMEM-resident
# per row block (ops/pallas_gnn.py): HBM traffic is obs in + logits out +
# the kron weights per block — orders of magnitude below its matmul time.
# Its binding floor IS the matmul floor (the kron construction deliberately
# trades 4x structural FLOPs for MXU-shaped execution).


CONFIGS = {
    # measured_ms: steady-state device/effective time per update from
    # docs/status.md (round 3-4 honest sync): config-3 22 ms device slope;
    # config-4/5 steady-state throughput converted at their headline
    # recipes (1 epoch) and at 6 epochs.
    "3 (MLP tpu4096, f32)": dict(
        envs=4096, steps=100, epochs=6,
        fwd=lambda s: mlp_matmul_flops(s),
        measured_ms=22.0,
    ),
    "4 (set_fast, bf16, 1 epoch)": dict(
        envs=4096, steps=100, epochs=1,
        fwd=lambda s: set_matmul_flops(s),
        measured_ms=178.0,
    ),
    "4 (set, bf16, 6 epochs)": dict(
        envs=4096, steps=100, epochs=6,
        fwd=lambda s: set_matmul_flops(s),
        measured_ms=516.0,
    ),
    # Config-5 recipes run the kron kernel at its f32 default (the
    # recorded headline command set no --compute-dtype); a round-4
    # same-process check measured bf16 dtype-neutral at the 1-epoch
    # recipe (~140 ms both ways) — the update is rollout-bound there.
    # The 197-TFLOP bf16 peak is still the correct FLOOR (best possible).
    # vmem_resident: the fused Pallas kernel holds the whole chain in
    # VMEM per row block (ops/pallas_gnn.py), so the matmul floor binds.
    "5 (gnn_fast, 1 epoch)": dict(
        envs=8192, steps=100, epochs=1, vmem_resident=True,
        fwd=lambda s: gnn_kron_matmul_flops(s),
        measured_ms=182.0,
    ),
    "5 (gnn, 6 epochs)": dict(
        envs=8192, steps=100, epochs=6, vmem_resident=True,
        fwd=lambda s: gnn_kron_matmul_flops(s),
        measured_ms=341.0,
    ),
    # Fleet-scale node sets (round 5, VERDICT r4 items 1/4): the same
    # set-transformer update at N=64/256 (flax policy, bf16 — at fleet N
    # the batch-minor path's advantage vanishes, docs/scaling.md).
    # measured_ms: round-5 same-process window-slope A/B
    # (loadgen/set_scale_bench.py).
    "4 (set_fleet64, N=64, 1 epoch)": dict(
        envs=1024, steps=100, epochs=1, nodes=64,
        fwd=lambda s: set_matmul_flops(s, nodes=64),
        measured_ms=417.0,
    ),
    "4 (set fleet, N=256, 1 epoch)": dict(
        envs=256, steps=100, epochs=1, nodes=256,
        fwd=lambda s: set_matmul_flops(s, nodes=256),
        measured_ms=299.0,
    ),
    # Fleet-N fused whole-network kernel (round 6, ops/pallas_set_block.py,
    # --fused-set-block): like the config-5 kron kernel, the forward and
    # remat-backward are VMEM-resident per row block, so the per-op HBM
    # traffic term (the measured 8.9-12.4% binding reality above) drops
    # out and the binding floor is the matmul floor. measured_ms is None
    # until a chip session runs the same-process A/B
    # (loadgen/set_scale_bench.py --nodes 64 --envs 1024 --minibatch 12800
    # --variants flax_bf16,fused_block); the row exists so the floor
    # arithmetic is already in the table the A/B fills.
    "4 (set_fleet64, fused block, 1 epoch)": dict(
        envs=1024, steps=100, epochs=1, nodes=64, vmem_resident=True,
        fwd=lambda s: set_matmul_flops(s, nodes=64),
        measured_ms=None,
    ),
    "4 (set fleet, N=256, fused block, 1 epoch)": dict(
        envs=256, steps=100, epochs=1, nodes=256, vmem_resident=True,
        fwd=lambda s: set_matmul_flops(s, nodes=256),
        measured_ms=None,
    ),
    # graftpipe (--overlap-collect, agent/ppo.py): pipelined collect/learn
    # + fused update prologue. Overlap does NOT move the floor — the same
    # FLOPs and traffic happen; it closes the measured gap by hiding the
    # ~83 ms non-SGD intercept (rollout + GAE + shuffle, the set_scale_
    # bench --epochs 1,4 decomposition) behind the SGD body of the
    # neighboring iteration inside a scan-over-updates dispatch. The rows
    # exist so the chip A/B (set_scale_bench.py --variants
    # flax_bf16,pipeline,prologue,overlap / fused_block,
    # fused_block_overlap --epochs 1,4) fills a table whose floor
    # arithmetic is already stated; the acceptance bar is the measured
    # INTERCEPT shrinking >= 1.5x, not a floor change.
    "4 (set_fleet64, overlap, 1 epoch)": dict(
        envs=1024, steps=100, epochs=1, nodes=64,
        fwd=lambda s: set_matmul_flops(s, nodes=64),
        measured_ms=None,
    ),
    "4 (set_fleet64, fused block + overlap, 1 epoch)": dict(
        envs=1024, steps=100, epochs=1, nodes=64, vmem_resident=True,
        fwd=lambda s: set_matmul_flops(s, nodes=64),
        measured_ms=None,
    ),
}


def main(argv: list[str] | None = None) -> list[dict]:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--tflops", type=float, default=197.0,
                   help="chip peak matmul TFLOP/s (v5e bf16: 197)")
    p.add_argument("--gbs", type=float, default=819.0,
                   help="chip HBM bandwidth GB/s (v5e: 819)")
    args = p.parse_args(argv)

    rows = []
    for name, c in CONFIGS.items():
        batch = c["envs"] * c["steps"]
        rollout_samples = (c["steps"] + 1) * c["envs"]
        rollout_fwd = c["fwd"](rollout_samples)
        epoch_fwd = c["fwd"](batch)
        flop_ms = update_floor_ms(epoch_fwd, rollout_fwd, c["epochs"],
                                  args.tflops)
        if c.get("vmem_resident"):
            # Fused whole-network kernels (config 5; fleet fused block):
            # activations never round-trip HBM, matmul floor binds.
            bw_ms = 0.0
        elif name.startswith("3"):
            bw_ms = config3_bandwidth_floor_ms(batch, c["epochs"],
                                               gbs=args.gbs)
        else:
            bw_ms = set_bandwidth_floor_ms(batch, rollout_samples,
                                           c["epochs"],
                                           nodes=c.get("nodes", 8),
                                           gbs=args.gbs)
        floor = max(flop_ms, bw_ms)
        measured = c["measured_ms"]
        rows.append({
            "config": name,
            "matmul_floor_ms": round(flop_ms, 1),
            "hbm_floor_ms": round(bw_ms, 1) if bw_ms else None,
            "floor_ms": round(floor, 1),
            "measured_ms": measured,
            "pct_of_roofline": (round(100.0 * floor / measured, 1)
                                if measured else None),
        })
    w = max(len(r["config"]) for r in rows)
    print(f"{'config':{w}}  matmul_floor  hbm_floor  floor   measured  %roofline")
    for r in rows:
        hbm = (f"{r['hbm_floor_ms']:>7.1f}ms" if r["hbm_floor_ms"]
               else "      -  ")
        if r["measured_ms"] is None:
            meas, pct = "  (A/B)  ", "      -  "
        else:
            meas = f"{r['measured_ms']:>6.1f}ms"
            pct = f"{r['pct_of_roofline']:>7.1f}%"
        print(f"{r['config']:{w}}  {r['matmul_floor_ms']:>10.1f}ms  {hbm}  "
              f"{r['floor_ms']:>5.1f}ms  {meas}  {pct}")
    return rows


if __name__ == "__main__":
    main()
