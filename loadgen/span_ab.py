"""graftlens span-overhead A/B: spans-on vs spans-off on a live pool.

The per-phase decision spans (scheduler/extender.py PHASES) ride the
serving hot path, so they carry a measured-overhead obligation: at the
ROADMAP-item-2 regime (8-way concurrency, N=1024 candidates) spans-on
must stay within 2% of spans-off req/s and p50 (docs/serving.md). This
driver measures exactly that, interleaved:

- one pool per variant per round (``--workers`` numpy-set workers on a
  fresh port, BLAS pinned by the pool's cores//workers default), the
  variants alternating inside every round so host drift lands on both
  sides — the same interleaving discipline as ``bench.py
  --scenario-bench`` (sequential per-variant runs measured 0.5-1.35x
  drift on identical code);
- the policy is a randomly-initialized ``cluster_set`` transformer
  served by the numpy backend — the A/B needs the real forward COST,
  not a trained argmax — driven by ``extender_bench``'s soak loop;
- best-of-rounds per variant, plus the on/off ratios and the 2% verdict
  in ONE ``schema_version: 1`` JSON line.

One command (the recipe docs/serving.md quotes)::

    make span-ab            # 8-way, N=1024, 2 rounds x 10 s per variant
    python loadgen/span_ab.py --nodes 1024 --threads 8 --workers 2 \
        --rounds 2 --duration 10
"""

from __future__ import annotations

import argparse
import json
import pathlib
import statistics
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import extender_bench

SCHEMA_VERSION = 1


def _make_factory(np_tree: dict, spans: bool):
    """Pool worker factory: numpy set backend over the pre-converted
    params tree (pure numpy crosses fork cleanly; workers never touch
    jax), table telemetry on the shared counter, spans per variant."""

    def factory(worker_id, shared):
        from rl_scheduler_tpu.scheduler.extender import ExtenderPolicy
        from rl_scheduler_tpu.scheduler.set_backend import NumpySetBackend
        from rl_scheduler_tpu.scheduler.telemetry import (
            RandomCpu,
            TableTelemetry,
        )

        telemetry = TableTelemetry.from_table(
            cpu_source=RandomCpu(seed=worker_id),
            counter=shared.table_counter)
        return ExtenderPolicy(NumpySetBackend(np_tree), telemetry,
                              spans=spans)

    return factory


def _run_variant(np_tree: dict, spans: bool, workers: int, nodes: int,
                 threads: int, duration_s: float) -> dict:
    from rl_scheduler_tpu.scheduler.pool import ServingPool

    pool = ServingPool(_make_factory(np_tree, spans), workers=workers,
                       host="127.0.0.1", port=0, control_port=0)
    pool.start(ready_timeout_s=120.0)
    try:
        base = f"http://127.0.0.1:{pool.port}"
        for i in range(2 * workers + 4):  # warm every worker's caches
            extender_bench.one_request(base, i, nodes)
        latencies, wall, failures, _, _ = extender_bench._soak(
            base, duration_s, threads, nodes)
    finally:
        pool.shutdown()
    latencies.sort()
    p50 = latencies[len(latencies) // 2] if latencies else float("nan")
    return {
        "spans": spans,
        "requests": len(latencies),
        "failures": failures,
        "req_per_sec": round(len(latencies) / wall, 2),
        "p50_ms": round(p50, 3),
    }


def main(argv: list | None = None) -> dict:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--nodes", type=int, default=1024)
    p.add_argument("--threads", type=int, default=8)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--rounds", type=int, default=2)
    p.add_argument("--duration", type=float, default=10.0,
                   help="seconds per variant per round")
    p.add_argument("--dim", type=int, default=64)
    args = p.parse_args(argv)

    # Init the set transformer ONCE in the parent and hand workers a
    # pure-numpy tree (same params both variants — the A/B compares the
    # instrumentation, nothing else).
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rl_scheduler_tpu.models.transformer import SetTransformerPolicy

    net = SetTransformerPolicy(dim=args.dim, depth=2)
    tree = net.init(jax.random.PRNGKey(0), jnp.zeros((8, 6), jnp.float32))
    np_tree = jax.tree_util.tree_map(np.asarray, tree)

    rows = {True: [], False: []}
    for r in range(args.rounds):
        # Alternate which variant goes first per round so warm-host bias
        # lands on both sides of the comparison.
        order = (True, False) if r % 2 == 0 else (False, True)
        for spans in order:
            row = _run_variant(np_tree, spans, args.workers, args.nodes,
                               args.threads, args.duration)
            rows[spans].append(row)
            print(f"round {r} spans={'on' if spans else 'off'}: "
                  f"{row['req_per_sec']} req/s p50 {row['p50_ms']} ms "
                  f"({row['requests']} reqs, {row['failures']} failures)",
                  file=sys.stderr)

    def best(variant_rows, key, lo_is_better):
        vals = [row[key] for row in variant_rows]
        return min(vals) if lo_is_better else max(vals)

    on_rps = best(rows[True], "req_per_sec", False)
    off_rps = best(rows[False], "req_per_sec", False)
    on_p50 = best(rows[True], "p50_ms", True)
    off_p50 = best(rows[False], "p50_ms", True)
    rps_ratio = round(on_rps / off_rps, 4) if off_rps else None
    p50_ratio = round(on_p50 / off_p50, 4) if off_p50 else None
    out = {
        "schema_version": SCHEMA_VERSION,
        "bench": "span_ab",
        "nodes": args.nodes,
        "workers": args.workers,
        "concurrency": args.threads,
        "rounds": args.rounds,
        "duration_s": args.duration,
        "spans_on": {"req_per_sec": on_rps, "p50_ms": on_p50,
                     "rounds_rps": [row["req_per_sec"]
                                    for row in rows[True]]},
        "spans_off": {"req_per_sec": off_rps, "p50_ms": off_p50,
                      "rounds_rps": [row["req_per_sec"]
                                     for row in rows[False]]},
        "rps_ratio_on_over_off": rps_ratio,
        "p50_ratio_on_over_off": p50_ratio,
        "median_rps_ratio": round(
            statistics.median(r["req_per_sec"] for r in rows[True])
            / statistics.median(r["req_per_sec"] for r in rows[False]), 4),
        # The acceptance bound: spans-on within 2% of spans-off on both
        # axes (best-of-rounds — the noise floor estimator the repo's
        # interleaved benches use).
        "within_2pct": bool(rps_ratio is not None and rps_ratio >= 0.98
                            and p50_ratio is not None and p50_ratio <= 1.02),
    }
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    main()
