"""Concurrent latency benchmark against a running scheduler extender.

Measures the serving contract (<1 ms p50, BASELINE.json) under load the
way a kube-scheduler would exercise it: many concurrent ``/filter`` +
``/prioritize`` POSTs with realistic node lists, client-side latency
percentiles, then the server's own ``/stats`` for cross-checking.

Usage::

    python -m rl_scheduler_tpu.scheduler.extender --backend native --port 8787 &
    python loadgen/extender_bench.py --port 8787 --requests 2000 --threads 8

Prints one JSON line with client p50/p90/p99 (ms) and achieved req/s.
Stdlib-only (no locust dependency) so it runs anywhere the extender does.
"""

from __future__ import annotations

import argparse
import concurrent.futures
import json
import sys
import time
import urllib.error
import urllib.request


def make_payload(i: int, num_nodes: int = 2) -> bytes:
    # First half aws, second half azure — mirrors the cluster_set env's
    # node layout so the same payload exercises both serving families.
    items = [
        {"metadata": {"name": f"node-{j}",
                      "labels": {"cloud": "aws" if j < num_nodes // 2 else "azure"}}}
        for j in range(num_nodes)
    ]
    return json.dumps(
        {
            "pod": {"metadata": {"name": f"bench-pod-{i}"}},
            "nodes": {"items": items},
        }
    ).encode()


def one_request(base: str, i: int, num_nodes: int = 2) -> float:
    path = "/filter" if i % 2 == 0 else "/prioritize"
    req = urllib.request.Request(
        base + path, data=make_payload(i, num_nodes),
        headers={"Content-Type": "application/json"},
    )
    t0 = time.perf_counter()
    with urllib.request.urlopen(req, timeout=10) as resp:
        resp.read()
    return (time.perf_counter() - t0) * 1000.0


def main(argv: list[str] | None = None) -> dict:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8787)
    p.add_argument("--requests", type=int, default=2000)
    p.add_argument("--threads", type=int, default=8)
    p.add_argument("--warmup", type=int, default=50)
    p.add_argument("--nodes", type=int, default=2,
                   help="candidate nodes per request (set-family serving "
                        "scores each one; 2 matches the two-cloud MLP)")
    args = p.parse_args(argv)
    if args.requests < 1:
        p.error("--requests must be >= 1")
    base = f"http://{args.host}:{args.port}"

    for i in range(args.warmup):
        one_request(base, i, args.nodes)
    # Scope the server-side percentiles to THIS run: the latency ring
    # holds 4096 entries, so without a reset the reported p50/p99 mix in
    # the preceding run's traffic (a round-4 measurement bug). Older
    # extender builds lack the endpoint — warn and report un-scoped
    # stats rather than aborting the bench.
    reset_req = urllib.request.Request(base + "/stats/reset", data=b"{}",
                                       headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(reset_req, timeout=10) as resp:
            resp.read()
    except urllib.error.HTTPError:
        print("warning: server has no /stats/reset; server-side "
              "percentiles may include pre-run traffic", file=sys.stderr)

    t_start = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(args.threads) as pool:
        latencies = sorted(pool.map(
            lambda i: one_request(base, i, args.nodes), range(args.requests)))
    wall = time.perf_counter() - t_start

    def pct(p_):
        return latencies[min(len(latencies) - 1, int(p_ * len(latencies)))]

    with urllib.request.urlopen(base + "/stats", timeout=10) as resp:
        server_stats = json.loads(resp.read())

    out = {
        "requests": args.requests,
        "threads": args.threads,
        "client_p50_ms": round(pct(0.50), 3),
        "client_p90_ms": round(pct(0.90), 3),
        "client_p99_ms": round(pct(0.99), 3),
        "req_per_sec": round(args.requests / wall, 1),
        "server_p50_ms": server_stats["latency"]["p50_ms"],
        "server_p99_ms": server_stats["latency"]["p99_ms"],
        "backend": server_stats["backend"],
    }
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    main()
