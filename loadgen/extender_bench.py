"""Concurrent latency benchmark against a running scheduler extender.

Measures the serving contract (<1 ms p50, BASELINE.json) under load the
way a kube-scheduler would exercise it: many concurrent ``/filter`` +
``/prioritize`` POSTs with realistic node lists, client-side latency
percentiles, then the server's own ``/stats`` for cross-checking.

Usage::

    python -m rl_scheduler_tpu.scheduler.extender --backend native --port 8787 &
    python loadgen/extender_bench.py --port 8787 --requests 2000 --threads 8

    # graftserve pool soak: fixed wall-clock duration, pool-wide reset
    # and stats via the supervisor's control plane (docs/serving.md)
    python -m rl_scheduler_tpu.scheduler.extender --workers 2 --port 8787 &
    python loadgen/extender_bench.py --port 8787 --duration 60 --threads 8 \
        --nodes 1024 --control-port 8788

Prints ONE JSON result line (``schema_version`` 1) carrying ``workers``,
``nodes``, ``concurrency`` and achieved ``req_per_sec`` alongside the
client/server percentiles, so the driver can track serving performance
across rounds the way ``BENCH_r*`` tracks training. Two modes:

- ``--requests N`` (default): a fixed request count, as before.
- ``--duration S``: a soak — every thread issues requests until the
  wall-clock deadline; failures are counted instead of aborting the run
  (a soak's job is to report errors, not die on the first one).

``--promote-at T --promote-checkpoint DIR`` (graftroll, soak mode only)
fires ``POST /promote`` at the pool control plane T seconds into the
soak, then polls ``GET /rollout`` until the rollout lands. Failures and
requests are counted PER PHASE (before vs from the promote instant), so
the zero-failed-requests acceptance criterion of the rollback drill is
one command: a phase with failures > 0 means the rolling restart dropped
traffic (docs/serving.md).

``--replay-trace DIR`` (graftloop) swaps the synthetic payloads for the
recorded ones: one request per logged decision, candidate clouds and pod
requests rebuilt from the trace's schema-2 fields, probes excluded — so
a serving A/B measures the traffic the pool actually served. The result
line carries a ``replay`` tag.

``--keepalive`` (graftfront, soak mode) reuses each bench thread's
connection across requests; connection setup is timed apart from
request latency either way (``connect_p50_ms``/``connections``).
``--fronts threading,asyncio`` self-hosts an interleaved front A/B at
each ``--front-threads`` concurrency, keep-alive compact-wire traffic
on the cache lever — ``make front-ab`` is the one-command recipe.

Stdlib-only for the synthetic modes (no locust dependency) so it runs
anywhere the extender does; ``--replay-trace`` imports the repo's
trace-log reader.
"""

from __future__ import annotations

import argparse
import concurrent.futures
import json
import sys
import threading
import time
import urllib.error
import urllib.request

SCHEMA_VERSION = 1


def make_payload(i: int, num_nodes: int = 2) -> bytes:
    # First half aws, second half azure — mirrors the cluster_set env's
    # node layout so the same payload exercises both serving families.
    items = [
        {"metadata": {"name": f"node-{j}",
                      "labels": {"cloud": "aws" if j < num_nodes // 2 else "azure"}}}
        for j in range(num_nodes)
    ]
    return json.dumps(
        {
            "pod": {"metadata": {"name": f"bench-pod-{i}"}},
            "nodes": {"items": items},
        }
    ).encode()


def load_replay_payloads(trace_dir: str, node_capacity_cores: float = 4.0,
                         limit: int | None = None) -> tuple:
    """graftloop replay mode: ``(payloads, report)`` — one prebuilt
    request body per RECORDED decision, rebuilt from the trace's
    schema-2 replay fields (``clouds`` candidate layout + ``pod_cpu``
    request fraction), probes excluded, in the merged timestamp order
    the pool actually served them. Serving A/Bs then run against real
    logged traffic instead of synthetic payloads. Records without the
    ``clouds`` field (schema-1, flat-family fail-opens) are skipped and
    counted — a replay must tolerate a mixed-era trace dir."""
    from rl_scheduler_tpu.scheduler.tracelog import (
        clouds_from_token,
        is_synthetic_endpoint,
        iter_trace_merged,
    )

    payloads = []
    skipped = probes = 0
    counts: dict = {}
    for record in iter_trace_merged(trace_dir):
        if is_synthetic_endpoint(record.get("endpoint")):
            # Probes AND shadow scores: synthetic records never answered
            # a real request, so a replay must not re-issue them.
            probes += 1
            continue
        clouds = clouds_from_token(record.get("clouds"))
        if not clouds:
            skipped += 1
            continue
        items = [
            {"metadata": {"name": f"{cloud or 'node'}-r{j}",
                          **({"labels": {"cloud": cloud}} if cloud
                             else {})}}
            for j, cloud in enumerate(clouds)
        ]
        pod: dict = {"metadata": {"name": f"replay-pod-{len(payloads)}"}}
        pod_cpu = record.get("pod_cpu")
        if pod_cpu is not None:
            # Reissue the recorded request fraction as the k8s quantity
            # the extender will parse back to it (millicores of the
            # serve config's node capacity).
            millis = max(int(round(pod_cpu * node_capacity_cores * 1e3)), 1)
            pod["spec"] = {"containers": [{"resources": {
                "requests": {"cpu": f"{millis}m"}}}]}
        payloads.append(json.dumps(
            {"pod": pod, "nodes": {"items": items}}).encode())
        counts[len(clouds)] = counts.get(len(clouds), 0) + 1
        if limit is not None and len(payloads) >= limit:
            break
    if not payloads:
        raise SystemExit(
            f"--replay-trace {trace_dir}: no replayable decision records "
            f"({skipped} without candidate-cloud fields, {probes} "
            "probes) — the trace must carry schema-2 records "
            "(clouds/pod_cpu; serve with a current extender)")
    modal_nodes = max(counts, key=lambda k: counts[k])
    report = {"trace_records": len(payloads), "skipped": skipped,
              "probes_excluded": probes, "nodes": modal_nodes,
              "capacity_cores": node_capacity_cores}
    return payloads, report


def make_wire_payload(i: int, num_nodes: int = 2) -> bytes:
    """The compact-wire twin of :func:`make_payload` (graftfront,
    ``scheduler/wire.py``): same first-half-aws/second-half-azure
    candidate layout, ~num_nodes bytes instead of ~100 bytes per node of
    JSON. The fronts A/B sends these so the transport comparison runs on
    the codec the sub-millisecond target is specified against."""
    from rl_scheduler_tpu.scheduler.wire import encode_request

    clouds = ["aws" if j < num_nodes // 2 else "azure"
              for j in range(num_nodes)]
    return encode_request(clouds, 500)


def one_request(base: str, i: int, num_nodes: int = 2,
                payload: bytes | None = None) -> float:
    path = "/filter" if i % 2 == 0 else "/prioritize"
    req = urllib.request.Request(
        base + path, data=payload or make_payload(i, num_nodes),
        headers={"Content-Type": "application/json"},
    )
    t0 = time.perf_counter()
    with urllib.request.urlopen(req, timeout=10) as resp:
        resp.read()
    return (time.perf_counter() - t0) * 1000.0


class BenchClient:
    """One bench thread's HTTP client, with connection-setup and request
    latency measured SEPARATELY (satellite of graftfront: the old
    connection-per-request urllib path folded TCP setup into every
    latency sample, which confounds any transport A/B).

    ``keepalive=True`` reuses one ``http.client.HTTPConnection`` across
    requests (reconnecting — and counting the reconnect — whenever the
    server closes or errors); ``keepalive=False`` reproduces the classic
    connection-per-request behaviour, still timing the setup apart.
    ``connects_ms`` accumulates one sample per TCP connect; request
    latencies EXCLUDE it either way."""

    def __init__(self, host: str, port: int, keepalive: bool = False,
                 content_type: str = "application/json",
                 timeout: float = 10.0):
        self.host, self.port = host, port
        self.keepalive = keepalive
        self.content_type = content_type
        self.timeout = timeout
        self.conn = None
        self.connects_ms: list = []

    def _connect(self) -> None:
        import http.client

        t0 = time.perf_counter()
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)
        conn.connect()
        self.connects_ms.append((time.perf_counter() - t0) * 1000.0)
        self.conn = conn

    def request(self, i: int, num_nodes: int = 2,
                payload: bytes | None = None) -> float:
        path = "/filter" if i % 2 == 0 else "/prioritize"
        body = payload if payload is not None \
            else make_payload(i, num_nodes)
        if self.conn is None:
            self._connect()
        t0 = time.perf_counter()
        try:
            self.conn.request("POST", path, body,
                              {"Content-Type": self.content_type})
            resp = self.conn.getresponse()
            data = resp.read()
            will_close = resp.will_close
        except Exception:
            # Whatever broke, the connection state is unknown: drop it so
            # a retry (or the next request) reconnects cleanly.
            self.close()
            raise
        ms = (time.perf_counter() - t0) * 1000.0
        if resp.status >= 400:
            self.close()
            raise RuntimeError(
                f"HTTP {resp.status} on {path}: {data[:200]!r}")
        if not self.keepalive or will_close:
            self.close()
        return ms

    def close(self) -> None:
        if self.conn is not None:
            self.conn.close()
            self.conn = None


def _is_connection_error(exc: Exception) -> bool:
    """Connection-LEVEL failure (refused / reset before a response):
    during a rolling worker restart a SYN can land in a dying listener's
    accept queue and get RST on close. The decision endpoints are
    idempotent, so these — and only these — are safe to retry; an HTTP
    error is a real answer and never retries."""
    if isinstance(exc, urllib.error.HTTPError):
        return False
    if isinstance(exc, urllib.error.URLError):
        exc = exc.reason if isinstance(exc.reason, Exception) else exc
    import http.client

    return isinstance(exc, (ConnectionError, http.client.RemoteDisconnected))


def _request_with_retry(client: BenchClient, i: int, num_nodes: int,
                        payload: bytes,
                        connect_retries: int) -> tuple[float, int]:
    """``(latency_ms, retries_used)``; only connection-level errors
    retry (against a fresh connection the kernel re-hashes to a live
    worker — the client dropped the broken one). Anything else — and a
    retry budget exhausted — propagates as a soak failure."""
    for attempt in range(connect_retries + 1):
        try:
            return client.request(i, num_nodes, payload), attempt
        except Exception as exc:  # noqa: BLE001 - classified below
            if attempt >= connect_retries or not _is_connection_error(exc):
                raise
            time.sleep(0.01 * (attempt + 1))
    raise AssertionError("unreachable")


def _soak(base: str, duration_s: float, threads: int, num_nodes: int,
          promote_at: float | None = None, payloads: list | None = None,
          keepalive: bool = False,
          content_type: str = "application/json",
          targets: list | None = None,
          connect_retries: int | None = None,
          flip_at: float | None = None):
    """Duration-based load: each thread loops until the deadline.

    Payloads are prebuilt once (at N=1024 a node list is ~100 KB of
    JSON; rebuilding per request would bench the CLIENT's json.dumps)
    and reused round-robin so /filter and /prioritize both stay hot.
    With ``promote_at`` set, requests and failures are additionally
    split into pre/post-promote phases by the request's START time — the
    drill's zero-failed-requests bar is judged per phase — and
    connection-level errors retry up to 3 times (``_request_with_retry``:
    a dying worker's accept queue RSTs on close; the retry's fresh
    connection re-hashes to a live worker; retries are reported, HTTP
    errors never retry). ``flip_at`` (graftdrift) adds a second mark of
    the SAME mechanism: every request is phased independently against
    every mark, so a promote + flip soak reports all four phase counts
    (``pre_promote``/``post_promote``/``pre_flip``/``post_flip``).
    Returns ``(sorted_latencies_ms, wall_s, failures, phases, retries,
    sorted_connects_ms, per_pool)`` — ``retries`` is counted (and
    reported) UNCONDITIONALLY and ONCE per request (never once per
    mark), so lever A/B lines stay field-comparable with rollout-drill
    lines; ``phases`` is ``None`` without any mark, ``per_pool`` is
    ``None`` without ``targets``.

    graftfront: every soak thread now runs a :class:`BenchClient`, so
    connection setup is timed apart from request latency in BOTH
    connection modes; ``keepalive=True`` reuses each thread's connection
    across requests (``--keepalive``), which is what makes a transport
    A/B measure the transport rather than the TCP handshake rate.

    graftfleet: ``targets`` (a ``host:port`` list) switches the soak to
    multi-pool mode — each thread holds one :class:`BenchClient` per
    target and round-robins its OWN requests across them (so every
    thread exercises every pool, not a per-thread pinning), and the
    return gains a ``per_pool`` ``{target: {"requests", "failures"}}``
    map so the fleet drill judges zero-failures per pool from one
    invocation. ``connect_retries`` overrides the promote-derived
    default (fleet drills retry connections in every phase: a pool
    replacing a worker mid-roll RSTs exactly like the single-pool
    promote drill).
    """
    if payloads is None:
        payloads = [make_payload(i, num_nodes) for i in range(16)]
    if targets:
        endpoints = []
        for target in targets:
            t_host, _, t_port = target.rpartition(":")
            endpoints.append((target, t_host, int(t_port)))
    else:
        host, _, port_s = base.rpartition("//")[2].partition(":")
        endpoints = [(None, host, int(port_s))]
    if connect_retries is None:
        connect_retries = 3 if promote_at is not None else 0
    t_start = time.perf_counter()
    deadline = t_start + duration_s
    # Phase marks: each named instant splits every request (by START
    # time) into its own pre/post pair, independently of other marks.
    marks = {}
    if promote_at is not None:
        marks["promote"] = t_start + promote_at
    if flip_at is not None:
        marks["flip"] = t_start + flip_at
    latencies: list = []
    connects: list = []
    failures = [0]
    retries_total = [0]
    phases = {f"{side}_{name}": {"requests": 0, "failures": 0, "retries": 0}
              for name in marks for side in ("pre", "post")}
    per_pool = {name: {"requests": 0, "failures": 0}
                for name, _, _ in endpoints if name is not None}
    lock = threading.Lock()

    def run(thread_id: int) -> None:
        clients = [BenchClient(c_host, c_port, keepalive=keepalive,
                               content_type=content_type)
                   for _, c_host, c_port in endpoints]
        local: list = []
        failed = 0
        local_retries = 0
        counts = {key: [0, 0, 0] for key in phases}
        pool_counts = {name: [0, 0] for name, _, _ in endpoints
                       if name is not None}
        i = thread_id
        k = thread_id  # stagger the starting pool across threads
        while True:
            now = time.perf_counter()
            if now >= deadline:
                break
            keys = [("post_" if now >= t_mark else "pre_") + mark
                    for mark, t_mark in marks.items()]
            idx = k % len(clients)
            k += 1
            name = endpoints[idx][0]
            try:
                ms, retried = _request_with_retry(
                    clients[idx], i, num_nodes,
                    payloads[i % len(payloads)], connect_retries)
                local.append(ms)
                local_retries += retried
                for key in keys:
                    counts[key][0] += 1
                    counts[key][2] += retried
                if name is not None:
                    pool_counts[name][0] += 1
            except Exception:  # noqa: BLE001 - soak counts, never aborts
                failed += 1
                for key in keys:
                    counts[key][0] += 1
                    counts[key][1] += 1
                if name is not None:
                    pool_counts[name][0] += 1
                    pool_counts[name][1] += 1
            i += threads
        for client in clients:
            client.close()
        with lock:
            latencies.extend(local)
            for client in clients:
                connects.extend(client.connects_ms)
            failures[0] += failed
            # Retries merge ONCE per thread — merging them per phase row
            # double-counted the total whenever two marks were active.
            retries_total[0] += local_retries
            for key, (reqs, fails, retries) in counts.items():
                phases[key]["requests"] += reqs
                phases[key]["failures"] += fails
                phases[key]["retries"] += retries
            for name, (reqs, fails) in pool_counts.items():
                per_pool[name]["requests"] += reqs
                per_pool[name]["failures"] += fails

    workers = [threading.Thread(target=run, args=(t,)) for t in range(threads)]
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    return (sorted(latencies), time.perf_counter() - t_start, failures[0],
            phases if marks else None, retries_total[0],
            sorted(connects), per_pool if targets else None)


def _fire_promote(control: str, checkpoint: str, delay_s: float,
                  deadline_s: float) -> dict:
    """Sleep ``delay_s``, POST the promote, then poll ``GET /rollout``
    until the rollout leaves the in-flight states (or the soak deadline
    passes). Returns what happened for the result line — the drill
    asserts on ``rollout.promotions_total``/``rollbacks_total``."""
    time.sleep(delay_s)
    out: dict = {"requested": True, "checkpoint": checkpoint}
    req = urllib.request.Request(
        control + "/promote",
        data=json.dumps({"checkpoint": checkpoint}).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            out["response_code"] = resp.status
            out["response"] = json.loads(resp.read())
    except urllib.error.HTTPError as e:
        out["response_code"] = e.code
        try:
            out["response"] = json.loads(e.read())
        except Exception:  # noqa: BLE001 - body is advisory
            out["response"] = None
        return out  # refused: nothing to poll
    except Exception as e:  # noqa: BLE001 - soak reports, never aborts
        out["error"] = str(e)
        return out
    poll_deadline = time.perf_counter() + deadline_s
    while time.perf_counter() < poll_deadline:
        try:
            status = _get_json(control + "/rollout")
        except Exception:  # noqa: BLE001 - transient; keep polling
            time.sleep(0.2)
            continue
        if not status.get("active"):
            out["rollout"] = status
            return out
        time.sleep(0.2)
    out["error"] = "rollout still in flight at the soak deadline"
    return out


def _fire_flip(control: str, tables: str, delay_s: float) -> dict:
    """graftdrift regime flip: sleep ``delay_s``, then POST
    ``/telemetry/flip`` so every pool worker swaps its price-replay
    table mid-soak. Returns what happened for the result line — the
    drift drill asserts the ``*_drifting`` transition downstream, this
    only reports whether the flip was accepted."""
    time.sleep(delay_s)
    out: dict = {"requested": True, "tables": tables}
    req = urllib.request.Request(
        control + "/telemetry/flip",
        data=json.dumps({"path": tables}).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            out["response_code"] = resp.status
            out["response"] = json.loads(resp.read())
    except urllib.error.HTTPError as e:
        out["response_code"] = e.code
        try:
            out["response"] = json.loads(e.read())
        except Exception:  # noqa: BLE001 - body is advisory
            out["response"] = None
    except Exception as e:  # noqa: BLE001 - soak reports, never aborts
        out["error"] = str(e)
    return out


def _get_json(url: str) -> dict:
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read())


# --------------------------------------------------------- graftfwd levers

LEVERS = ("off", "batch", "int8", "cache", "all")


def _lever_factory(np_tree: dict, lever: str, batch_window_ms: float,
                   cache_epoch_s: float, nodes: int = 8):
    """Pool worker factory for one lever configuration (the span_ab
    pattern: a pure-numpy tree crosses fork cleanly; each worker builds
    its own backend/levers). ``off`` is the PR-12 baseline — the plain
    numpy set backend; ``int8`` goes through make_set_backend's
    agreement gate, so an int8 row in the matrix IS a gated row."""

    def factory(worker_id, shared):
        from rl_scheduler_tpu.scheduler import set_backend as sb
        from rl_scheduler_tpu.scheduler.extender import ExtenderPolicy
        from rl_scheduler_tpu.scheduler.fastpath import (
            MicroBatcher,
            ScoreCache,
        )
        from rl_scheduler_tpu.scheduler.telemetry import (
            RandomCpu,
            TableTelemetry,
        )

        telemetry = TableTelemetry.from_table(
            cpu_source=RandomCpu(seed=worker_id),
            counter=shared.table_counter)
        if lever in ("int8", "all"):
            # warm_counts carries the N this bench serves, so the int8
            # agreement gate measures the distribution the lever row
            # claims (not just the small-set floor).
            backend, _ = sb.make_set_backend("native-int8", np_tree,
                                             warm_counts=(nodes,))
        else:
            backend = sb.NumpySetBackend(np_tree)
        policy = ExtenderPolicy(backend, telemetry)
        if lever in ("batch", "all"):
            policy.batcher = MicroBatcher(
                backend, window_s=batch_window_ms / 1e3)
        if lever in ("cache", "all"):
            policy.score_cache = ScoreCache(epoch_s=cache_epoch_s)
        return policy

    return factory


def _run_lever_round(np_tree: dict, lever: str, args) -> dict:
    """One lever x one round: fresh pool, warm-up, reset, soak, server
    stats off the control plane. Raises on a pool that cannot start
    (e.g. the int8 agreement gate refusing) — the matrix reports it as
    a skipped lever."""
    from rl_scheduler_tpu.scheduler.pool import ServingPool

    pool = ServingPool(
        _lever_factory(np_tree, lever, args.batch_window_ms,
                       args.cache_epoch_s, nodes=args.nodes),
        workers=args.workers, host="127.0.0.1", port=0, control_port=0)
    pool.start(ready_timeout_s=120.0)
    try:
        base = f"http://127.0.0.1:{pool.port}"
        control = "http://127.0.0.1:%d" % pool.control_address[1]
        for i in range(2 * args.workers + 4):
            one_request(base, i, args.nodes)
        _get_json(control + "/healthz")
        reset_req = urllib.request.Request(
            control + "/stats/reset", data=b"{}",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(reset_req, timeout=10) as resp:
            resp.read()
        latencies, wall, failures, _, retries, _, _ = _soak(
            base, args.duration, args.threads, args.nodes)
        server_stats = _get_json(control + "/stats")
    finally:
        pool.shutdown()
    if not latencies:
        raise RuntimeError(f"lever {lever!r}: soak completed zero requests")
    p50 = latencies[len(latencies) // 2]
    out = {
        "req_per_sec": round(len(latencies) / wall, 1),
        "client_p50_ms": round(p50, 3),
        "client_p99_ms": round(
            latencies[min(len(latencies) - 1,
                          int(0.99 * len(latencies)))], 3),
        "requests": len(latencies),
        "failures": failures,
        "retries": retries,
        "server_p50_ms": (server_stats.get("latency") or {}).get("p50_ms"),
        "backend": server_stats.get("backend"),
        "fastpath": server_stats.get("fastpath"),
    }
    return out


def run_levers_matrix(args) -> list:
    """The ``--levers`` matrix (graftfwd): one pool per lever per round,
    levers INTERLEAVED inside every round (the bench.py/span_ab
    discipline — sequential per-variant runs measured 0.5-1.35x host
    drift on identical code), best-of-rounds per lever, ONE
    ``schema_version`` JSON line per lever. With ``--history`` each
    lever's line appends to the durable ledger carrying a ``lever``
    field, so `tools/decisionview --check-history` gates every lever's
    trajectory separately (shape = workers x nodes x concurrency x
    lever)."""
    import pathlib
    import sys as _sys

    _sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rl_scheduler_tpu.models.transformer import SetTransformerPolicy

    levers = [lv.strip() for lv in args.levers.split(",") if lv.strip()]
    unknown = [lv for lv in levers if lv not in LEVERS]
    if unknown:
        raise SystemExit(f"--levers: unknown lever(s) {unknown}; "
                         f"choose from {list(LEVERS)}")
    net = SetTransformerPolicy(dim=64, depth=2)
    tree = net.init(jax.random.PRNGKey(0), jnp.zeros((8, 6), jnp.float32))
    np_tree = jax.tree_util.tree_map(np.asarray, tree)

    rows: dict = {lever: [] for lever in levers}
    skipped: dict = {}
    for r in range(args.rounds):
        order = levers if r % 2 == 0 else list(reversed(levers))
        for lever in order:
            if lever in skipped:
                continue
            try:
                row = _run_lever_round(np_tree, lever, args)
            except Exception as e:  # noqa: BLE001 — a refused lever
                # (int8 gate, missing toolchain) skips, never aborts
                # the rest of the matrix
                print(f"lever {lever!r} skipped: {e}", file=sys.stderr)
                skipped[lever] = str(e)
                continue
            rows[lever].append(row)
            print(f"round {r} lever={lever}: {row['req_per_sec']} req/s "
                  f"p50 {row['client_p50_ms']} ms "
                  f"({row['requests']} reqs, {row['failures']} failures)",
                  file=sys.stderr)

    lines = []
    for lever in levers:
        if not rows[lever]:
            continue
        best = max(rows[lever], key=lambda row: row["req_per_sec"])
        line = {
            "schema_version": SCHEMA_VERSION,
            "bench": "extender_serving",
            "mode": "levers",
            "lever": lever,
            # Constant on the levers matrix: lever pools serve the
            # incumbent threading front over per-request connections, so
            # these rows stay shape-comparable with `mode: fronts` rows.
            "front": "threading",
            "keepalive": False,
            "workers": args.workers,
            "nodes": args.nodes,
            "concurrency": args.threads,
            "threads": args.threads,
            "rounds": len(rows[lever]),
            "duration_s": args.duration,
            "rounds_rps": [row["req_per_sec"] for row in rows[lever]],
            **best,
        }
        lines.append(line)
        print(json.dumps(line))
        if args.history is not None:
            with open(args.history, "a", encoding="utf-8") as fh:
                fh.write(json.dumps(line) + "\n")
    off_rps = next((ln["req_per_sec"] for ln in lines
                    if ln["lever"] == "off"), None)
    for line in lines:
        if off_rps and line["lever"] != "off":
            print(f"{line['lever']}: {line['req_per_sec'] / off_rps:.2f}x "
                  "off-lever req/s", file=sys.stderr)
    return lines


def _run_front_round(np_tree: dict, front: str, threads_n: int,
                     args) -> dict:
    """One front x one concurrency x one round: fresh pool serving the
    cache lever (the sub-millisecond target is specified against cache
    hits), keep-alive wire-codec soak, pool-wide stats. The SAME payload
    set, lever and client drive both fronts, so the row isolates the
    transport."""
    from rl_scheduler_tpu.scheduler.pool import ServingPool
    from rl_scheduler_tpu.scheduler.wire import WIRE_CONTENT_TYPE

    pool = ServingPool(
        _lever_factory(np_tree, "cache", args.batch_window_ms,
                       args.cache_epoch_s, nodes=args.nodes),
        workers=args.workers, host="127.0.0.1", port=0, control_port=0,
        front=front)
    pool.start(ready_timeout_s=120.0)
    try:
        base = f"http://127.0.0.1:{pool.port}"
        control = "http://127.0.0.1:%d" % pool.control_address[1]
        payloads = [make_wire_payload(i, args.nodes) for i in range(16)]
        warm = BenchClient("127.0.0.1", pool.port, keepalive=True,
                           content_type=WIRE_CONTENT_TYPE)
        try:
            for i in range(2 * args.workers + 4):
                warm.request(i, args.nodes, payloads[i % len(payloads)])
        finally:
            warm.close()
        _get_json(control + "/healthz")
        reset_req = urllib.request.Request(
            control + "/stats/reset", data=b"{}",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(reset_req, timeout=10) as resp:
            resp.read()
        latencies, wall, failures, _, retries, connects, _ = _soak(
            base, args.duration, threads_n, args.nodes,
            payloads=payloads, keepalive=True,
            content_type=WIRE_CONTENT_TYPE)
        server_stats = _get_json(control + "/stats")
    finally:
        pool.shutdown()
    if not latencies:
        raise RuntimeError(
            f"front {front!r} x{threads_n}: soak completed zero requests")
    p50 = latencies[len(latencies) // 2]
    out = {
        "req_per_sec": round(len(latencies) / wall, 1),
        "client_p50_ms": round(p50, 3),
        "client_p99_ms": round(
            latencies[min(len(latencies) - 1,
                          int(0.99 * len(latencies)))], 3),
        "requests": len(latencies),
        "failures": failures,
        "retries": retries,
        "connections": len(connects),
        "connect_p50_ms": round(connects[len(connects) // 2], 3)
        if connects else None,
        "connect_p99_ms": round(
            connects[min(len(connects) - 1, int(0.99 * len(connects)))], 3)
        if connects else None,
        "server_p50_ms": (server_stats.get("latency") or {}).get("p50_ms"),
        "backend": server_stats.get("backend"),
        "fastpath": server_stats.get("fastpath"),
    }
    return out


def run_fronts_matrix(args) -> list:
    """The ``--fronts`` A/B (graftfront): one pool per front per
    concurrency per round, fronts INTERLEAVED inside every round (the
    levers-matrix discipline — sequential per-variant runs drift with
    the host), keep-alive compact-wire traffic on the cache lever for
    EVERY cell, best-of-rounds per (front, concurrency), ONE
    ``schema_version`` JSON line per cell carrying ``front`` +
    ``keepalive`` + ``codec`` fields. `make front-ab` is the
    one-command recipe; with ``--history`` the lines append to the
    serving ledger and `tools/decisionview --check-history` gates each
    (front x concurrency) shape separately."""
    import pathlib
    import sys as _sys

    _sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rl_scheduler_tpu.models.transformer import SetTransformerPolicy
    from rl_scheduler_tpu.scheduler.extender import FRONTS

    fronts = [f.strip() for f in args.fronts.split(",") if f.strip()]
    unknown = [f for f in fronts if f not in FRONTS]
    if unknown:
        raise SystemExit(f"--fronts: unknown front(s) {unknown}; "
                         f"choose from {list(FRONTS)}")
    try:
        thread_grid = [int(t) for t in args.front_threads.split(",") if t]
    except ValueError:
        raise SystemExit(f"--front-threads {args.front_threads!r}: "
                         "expected a csv of ints (e.g. 8,64)")
    net = SetTransformerPolicy(dim=64, depth=2)
    tree = net.init(jax.random.PRNGKey(0), jnp.zeros((8, 6), jnp.float32))
    np_tree = jax.tree_util.tree_map(np.asarray, tree)

    cells = [(front, tn) for tn in thread_grid for front in fronts]
    rows: dict = {cell: [] for cell in cells}
    for r in range(args.rounds):
        order = cells if r % 2 == 0 else list(reversed(cells))
        for front, tn in order:
            row = _run_front_round(np_tree, front, tn, args)
            rows[(front, tn)].append(row)
            print(f"round {r} front={front} x{tn}: "
                  f"{row['req_per_sec']} req/s "
                  f"p50 {row['client_p50_ms']} ms "
                  f"({row['requests']} reqs, {row['failures']} failures, "
                  f"{row['connections']} conns)", file=sys.stderr)

    lines = []
    for front, tn in cells:
        if not rows[(front, tn)]:
            continue
        best = max(rows[(front, tn)], key=lambda row: row["req_per_sec"])
        line = {
            "schema_version": SCHEMA_VERSION,
            "bench": "extender_serving",
            "mode": "fronts",
            "front": front,
            "keepalive": True,
            "codec": "wire",
            "workers": args.workers,
            "nodes": args.nodes,
            "concurrency": tn,
            "threads": tn,
            "rounds": len(rows[(front, tn)]),
            "duration_s": args.duration,
            "rounds_rps": [row["req_per_sec"]
                           for row in rows[(front, tn)]],
            **best,
        }
        lines.append(line)
        print(json.dumps(line))
        if args.history is not None:
            with open(args.history, "a", encoding="utf-8") as fh:
                fh.write(json.dumps(line) + "\n")
    for tn in thread_grid:
        base_rps = next((ln["req_per_sec"] for ln in lines
                         if ln["front"] == "threading"
                         and ln["concurrency"] == tn), None)
        for line in lines:
            if base_rps and line["concurrency"] == tn \
                    and line["front"] != "threading":
                print(f"x{tn} {line['front']}: "
                      f"{line['req_per_sec'] / base_rps:.2f}x threading "
                      "req/s", file=sys.stderr)
    return lines


def main(argv: list[str] | None = None) -> dict:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8787)
    p.add_argument("--requests", type=int, default=2000)
    p.add_argument("--duration", type=float, default=None, metavar="S",
                   help="soak mode: run for S wall-clock seconds instead "
                        "of a fixed --requests count (failures are "
                        "counted, not fatal)")
    p.add_argument("--threads", type=int, default=8)
    p.add_argument("--warmup", type=int, default=50)
    p.add_argument("--nodes", type=int, default=2,
                   help="candidate nodes per request (set-family serving "
                        "scores each one; 2 matches the two-cloud MLP)")
    p.add_argument("--control-port", type=int, default=None,
                   help="graftserve pool: the supervisor's control-plane "
                        "port — /stats/reset fans out to EVERY worker "
                        "(the data port resets only whichever worker the "
                        "kernel hands that connection) and the reported "
                        "server stats/worker count are pool-wide")
    p.add_argument("--promote-at", type=float, default=None, metavar="T",
                   help="graftroll drill hook (soak mode): POST /promote "
                        "to the control plane T seconds into the soak and "
                        "report per-phase failure counts — zero failures "
                        "in BOTH phases is the rolling-restart acceptance "
                        "bar (docs/serving.md)")
    p.add_argument("--promote-checkpoint", default=None, metavar="DIR",
                   help="checkpoint run dir to promote at --promote-at")
    p.add_argument("--flip-at", type=float, default=None, metavar="T",
                   help="graftdrift drill hook (soak mode): POST "
                        "/telemetry/flip to the control plane T seconds "
                        "into the soak, swapping every worker's price-"
                        "replay table to --flip-tables (a real mid-soak "
                        "regime change, off-network), and report per-"
                        "phase (pre/post-flip) request counts — the "
                        "drift drill then asserts *_drifting flips "
                        "within the short window (docs/serving.md)")
    p.add_argument("--flip-tables", default=None, metavar="PATH",
                   help="normalized telemetry table CSV to swap in at "
                        "--flip-at (same loader + validation as the "
                        "server's --telemetry table)")
    p.add_argument("--history", default=None, metavar="FILE",
                   help="graftlens serving bench ledger: append this "
                        "run's schema_version:1 JSON line to FILE "
                        "(convention: BENCH_serving.jsonl at the repo "
                        "root) so rounds accumulate a durable "
                        "trajectory; `tools/decisionview --check-history`"
                        " gates the newest round against the priors")
    p.add_argument("--replay-trace", default=None, metavar="DIR",
                   help="graftloop replay mode: drive the bench from a "
                        "recorded trace dir — one request per logged "
                        "decision (candidate-cloud layout + pod request "
                        "rebuilt from the schema-2 fields, probes "
                        "excluded, merged timestamp order), cycled round-"
                        "robin. Serving A/Bs run against real logged "
                        "traffic instead of synthetic payloads; the "
                        "result line carries a `replay` tag and ignores "
                        "--nodes (the trace defines the node sets)")
    p.add_argument("--replay-capacity-cores", type=float, default=None,
                   metavar="CORES",
                   help="replay mode: node capacity the SERVER was "
                        "started with (--node-capacity-cores; default = "
                        "the extender's default). Recorded pod fractions "
                        "re-issue as millicore quantities of this "
                        "capacity, so a mismatch silently distorts every "
                        "replayed pod request")
    p.add_argument("--replay-limit", type=int, default=0, metavar="N",
                   help="replay mode: prebuild at most N payloads from "
                        "the trace (0 = all). A long-serving pool's "
                        "trace dir can hold millions of records; the "
                        "bench cycles whatever is loaded round-robin")
    p.add_argument("--levers", default=None, metavar="L1,L2,...",
                   help="graftfwd matrix mode: self-host one pool per "
                        "lever per round (off/batch/int8/cache/all, "
                        "interleaved — the span_ab discipline), soak "
                        "each, and print/append ONE JSON line per lever "
                        "carrying a `lever` field. Ignores --host/--port "
                        "(pools bind ephemeral localhost ports); "
                        "`make fastpath-ab` is the one-command recipe")
    p.add_argument("--rounds", type=int, default=2,
                   help="levers mode: interleaved rounds per lever "
                        "(default 2)")
    p.add_argument("--workers", type=int, default=2,
                   help="levers mode: pool workers per lever pool "
                        "(default 2)")
    p.add_argument("--batch-window-ms", type=float, default=1.5,
                   help="levers mode: admission window for the batch/"
                        "all levers (default 1.5)")
    p.add_argument("--cache-epoch-s", type=float, default=3600.0,
                   help="levers mode: telemetry epoch for the cache/all "
                        "levers (default 3600 — the bench's request "
                        "stream repeats node sets, so one epoch shows "
                        "the hit path; live serving uses ~15)")
    p.add_argument("--keepalive", action="store_true",
                   help="soak mode (graftfront): reuse each bench "
                        "thread's HTTP connection across requests "
                        "instead of reconnecting per request. "
                        "Connection setup is timed SEPARATELY either "
                        "way (connect_p50_ms/connect_p99_ms/"
                        "connections in the result line); against the "
                        "threading front (HTTP/1.0 — the server closes "
                        "after every response) this degrades to "
                        "reconnect-per-request and the connect counts "
                        "show it")
    p.add_argument("--front", default="threading",
                   help="label for the result line: which --front the "
                        "TARGET server was started with (the bench "
                        "cannot detect it; default threading). History "
                        "gating treats front as part of the row shape")
    p.add_argument("--fronts", default=None, metavar="F1,F2",
                   help="graftfront A/B mode: self-host one pool per "
                        "front per concurrency per round (threading/"
                        "asyncio, interleaved — the levers-matrix "
                        "discipline), soak each with keep-alive "
                        "compact-wire traffic on the cache lever, and "
                        "print/append ONE JSON line per (front x "
                        "concurrency) cell. Ignores --host/--port; "
                        "`make front-ab` is the one-command recipe")
    p.add_argument("--front-threads", default="8,64", metavar="T1,T2",
                   help="fronts mode: csv concurrency grid (default "
                        "8,64 — the serving contract's low-load latency "
                        "point and the saturation point)")
    p.add_argument("--targets", default=None, metavar="H:P,H:P,...",
                   help="graftfleet multi-pool soak: round-robin each "
                        "thread's requests across these data planes and "
                        "report per-pool request/failure counts; point "
                        "--host/--control-port at the FLEET control "
                        "plane so the server-side stats on the line are "
                        "fleet-merged (needs --duration)")
    args = p.parse_args(argv)
    if args.fronts is not None:
        if args.duration is None:
            args.duration = 10.0
        if args.levers is not None:
            p.error("--fronts and --levers are separate matrices; run "
                    "them as separate invocations")
        if args.promote_at is not None:
            p.error("--fronts and --promote-at are separate drills")
        if args.flip_at is not None:
            p.error("--fronts and --flip-at are separate drills")
        if args.replay_trace is not None:
            p.error("--fronts self-hosts synthetic pools; --replay-trace "
                    "drives an existing server — separate modes")
        return run_fronts_matrix(args)
    if args.keepalive and args.duration is None:
        p.error("--keepalive applies to soak mode; add --duration")
    if args.levers is not None:
        if args.duration is None:
            args.duration = 10.0
        if args.promote_at is not None:
            p.error("--levers and --promote-at are separate drills")
        if args.flip_at is not None:
            p.error("--levers and --flip-at are separate drills")
        if args.replay_trace is not None:
            p.error("--levers self-hosts synthetic pools; --replay-trace "
                    "drives an existing server from a recorded trace — "
                    "separate modes")
        return run_levers_matrix(args)
    replay_payloads = replay_report = None
    if args.replay_trace is not None:
        import pathlib
        import sys as _sys

        _sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
        capacity = args.replay_capacity_cores
        if capacity is None:
            from rl_scheduler_tpu.scheduler.extender import (
                DEFAULT_NODE_CAPACITY_CORES,
            )

            capacity = DEFAULT_NODE_CAPACITY_CORES
        replay_payloads, replay_report = load_replay_payloads(
            args.replay_trace, node_capacity_cores=capacity,
            limit=args.replay_limit or None)
        args.nodes = replay_report["nodes"]
        print(f"replay: {replay_report['trace_records']} recorded "
              f"decisions from {args.replay_trace} "
              f"(modal N={args.nodes}; {replay_report['skipped']} "
              "skipped)", file=sys.stderr)
        if args.replay_limit and \
                replay_report["trace_records"] >= args.replay_limit:
            print(f"replay: capped at --replay-limit {args.replay_limit} "
                  "payloads; later trace records were not loaded",
                  file=sys.stderr)
    if args.requests < 1:
        p.error("--requests must be >= 1")
    if args.duration is not None and args.duration <= 0:
        p.error("--duration must be a positive number of seconds")
    if args.promote_at is not None:
        if args.duration is None:
            p.error("--promote-at needs --duration (the soak is the drill)")
        if args.promote_checkpoint is None:
            p.error("--promote-at needs --promote-checkpoint")
        if not 0 <= args.promote_at < args.duration:
            p.error("--promote-at must land inside the soak window "
                    f"[0, {args.duration})")
    elif args.promote_checkpoint is not None:
        p.error("--promote-checkpoint only applies with --promote-at")
    if args.flip_at is not None:
        if args.duration is None:
            p.error("--flip-at needs --duration (the soak is the drill)")
        if args.flip_tables is None:
            p.error("--flip-at needs --flip-tables")
        if not 0 <= args.flip_at < args.duration:
            p.error("--flip-at must land inside the soak window "
                    f"[0, {args.duration})")
    elif args.flip_tables is not None:
        p.error("--flip-tables only applies with --flip-at")
    targets = None
    if args.targets is not None:
        targets = [t.strip() for t in args.targets.split(",") if t.strip()]
        if not targets:
            p.error("--targets: at least one host:port entry")
        if args.duration is None:
            p.error("--targets is a soak mode; add --duration")
        if args.promote_at is not None:
            p.error("--targets and --promote-at are separate drills: "
                    "fleet promotes run through the fleet CLI "
                    "(python -m rl_scheduler_tpu.scheduler.fleet)")
        if args.replay_trace is not None:
            p.error("--targets and --replay-trace are separate modes")
    base = f"http://{args.host}:{args.port}"
    control = (f"http://{args.host}:{args.control_port}"
               if args.control_port is not None else base)

    warm_bases = ([f"http://{t}" for t in targets] if targets else [base])
    for i in range(args.warmup):
        one_request(warm_bases[i % len(warm_bases)], i, args.nodes,
                    payload=replay_payloads[i % len(replay_payloads)]
                    if replay_payloads else None)
    # Scope the server-side percentiles to THIS run: the latency ring
    # holds 4096 entries, so without a reset the reported p50/p99 mix in
    # the preceding run's traffic (a round-4 measurement bug). Against a
    # pool, reset through the control plane so it fans out. Older
    # extender builds lack the endpoint — warn and report un-scoped
    # stats rather than aborting the bench.
    reset_req = urllib.request.Request(control + "/stats/reset", data=b"{}",
                                       headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(reset_req, timeout=10) as resp:
            resp.read()
    except urllib.error.HTTPError:
        print("warning: server has no /stats/reset; server-side "
              "percentiles may include pre-run traffic", file=sys.stderr)

    failures = retries = 0
    connects: list = []
    phases = promote = per_pool = flip = None
    if args.duration is not None:
        promote_thread = result_box = None
        flip_thread = flip_box = None
        if args.promote_at is not None:
            result_box = {}
            remaining = args.duration - args.promote_at

            def _promote_then_record():
                result_box.update(_fire_promote(
                    control, args.promote_checkpoint, args.promote_at,
                    deadline_s=max(remaining, 1.0) + 30.0))

            promote_thread = threading.Thread(target=_promote_then_record,
                                              daemon=True)
            promote_thread.start()
        if args.flip_at is not None:
            flip_box = {}

            def _flip_then_record():
                flip_box.update(_fire_flip(
                    control, args.flip_tables, args.flip_at))

            flip_thread = threading.Thread(target=_flip_then_record,
                                           daemon=True)
            flip_thread.start()
        latencies, wall, failures, phases, retries, connects, per_pool = \
            _soak(base, args.duration, args.threads, args.nodes,
                  promote_at=args.promote_at, payloads=replay_payloads,
                  keepalive=args.keepalive, targets=targets,
                  connect_retries=3 if targets else None,
                  flip_at=args.flip_at)
        if promote_thread is not None:
            promote_thread.join(timeout=60.0)
            promote = result_box
        if flip_thread is not None:
            flip_thread.join(timeout=30.0)
            flip = flip_box
        if not latencies:
            raise SystemExit(
                f"soak completed zero requests in {args.duration}s "
                f"({failures} failures) — is the server up?"
            )
    else:
        t_start = time.perf_counter()
        with concurrent.futures.ThreadPoolExecutor(args.threads) as pool:
            latencies = sorted(pool.map(
                lambda i: one_request(
                    base, i, args.nodes,
                    payload=replay_payloads[i % len(replay_payloads)]
                    if replay_payloads else None),
                range(args.requests)))
        wall = time.perf_counter() - t_start

    def pct(p_):
        return latencies[min(len(latencies) - 1, int(p_ * len(latencies)))]

    # Worker count: the pool control plane knows it authoritatively;
    # a pool WORKER's /healthz reports its pool size too; the classic
    # single-process server reports neither -> 1.
    try:
        health = _get_json(control + "/healthz")
    except Exception:  # noqa: BLE001 - health is advisory for the line
        health = {}
    workers = int(health.get("workers", 1))

    server_stats = _get_json(control + "/stats")
    server_latency = server_stats.get("latency", {})

    out = {
        "schema_version": SCHEMA_VERSION,
        "bench": "extender_serving",
        "mode": "soak" if args.duration is not None else "count",
        # graftfront: the target's front is a bench LABEL (--front); the
        # connection mode is the bench's own. Both join the history
        # shape so fronts never gate against each other's priors.
        "front": args.front,
        "keepalive": bool(args.keepalive),
        "workers": workers,
        "nodes": args.nodes,
        "concurrency": args.threads,
        "requests": len(latencies),
        "threads": args.threads,
        "duration_s": round(wall, 3),
        "failures": failures,
        # Unconditional (round-13 fix): the retry counter used to ride
        # only the --promote-at phase split, so lever A/B lines were not
        # field-comparable with rollout-drill lines.
        "retries": retries,
        "client_p50_ms": round(pct(0.50), 3),
        "client_p90_ms": round(pct(0.90), 3),
        "client_p99_ms": round(pct(0.99), 3),
        "req_per_sec": round(len(latencies) / wall, 1),
        "server_p50_ms": server_latency.get("p50_ms"),
        "server_p99_ms": server_latency.get("p99_ms"),
        "backend": server_stats.get("backend"),
        # graftpilot: the policy generation the target served at line-
        # emit time (pool body nests it under "pool"; the single-process
        # server carries it at top level). A multi-hour soak under the
        # retrain daemon joins its latency history against generation
        # flips through this one field.
        "daemon_generation": (server_stats.get("pool") or {}).get(
            "generation", server_stats.get("generation", 0)),
    }
    if connects:
        # Connection setup, reported apart from request latency: under
        # --keepalive this approaches one sample per thread; without it
        # (or against an HTTP/1.0 server) one per request.
        out["connections"] = len(connects)
        out["connect_p50_ms"] = round(connects[len(connects) // 2], 3)
        out["connect_p99_ms"] = round(
            connects[min(len(connects) - 1, int(0.99 * len(connects)))], 3)
    if replay_report is not None:
        # The `replay` tag: this round's traffic was recorded, not
        # synthetic — history gating treats it as its own shape via the
        # modal `nodes` it already carries.
        out["replay"] = replay_report
    if phases is not None:
        if args.promote_at is not None:
            out["promote_at_s"] = args.promote_at
        if args.flip_at is not None:
            out["flip_at_s"] = args.flip_at
        out["phases"] = phases
    if promote is not None:
        out["promote"] = promote
    if flip is not None:
        out["flip"] = flip
    if per_pool is not None:
        # graftfleet: the drill's zero-failures bar is judged per pool
        # from this one line.
        out["targets"] = targets
        out["per_pool"] = per_pool
    print(json.dumps(out))
    if args.history is not None:
        # Durable append-only ledger (one JSON line per round). Plain
        # append: a torn final line from a killed bench is tolerated by
        # the decisionview reader, like the trace log's torn-line rule.
        with open(args.history, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(out) + "\n")
    return out


if __name__ == "__main__":
    main()
