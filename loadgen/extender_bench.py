"""Concurrent latency benchmark against a running scheduler extender.

Measures the serving contract (<1 ms p50, BASELINE.json) under load the
way a kube-scheduler would exercise it: many concurrent ``/filter`` +
``/prioritize`` POSTs with realistic node lists, client-side latency
percentiles, then the server's own ``/stats`` for cross-checking.

Usage::

    python -m rl_scheduler_tpu.scheduler.extender --backend native --port 8787 &
    python loadgen/extender_bench.py --port 8787 --requests 2000 --threads 8

    # graftserve pool soak: fixed wall-clock duration, pool-wide reset
    # and stats via the supervisor's control plane (docs/serving.md)
    python -m rl_scheduler_tpu.scheduler.extender --workers 2 --port 8787 &
    python loadgen/extender_bench.py --port 8787 --duration 60 --threads 8 \
        --nodes 1024 --control-port 8788

Prints ONE JSON result line (``schema_version`` 1) carrying ``workers``,
``nodes``, ``concurrency`` and achieved ``req_per_sec`` alongside the
client/server percentiles, so the driver can track serving performance
across rounds the way ``BENCH_r*`` tracks training. Two modes:

- ``--requests N`` (default): a fixed request count, as before.
- ``--duration S``: a soak — every thread issues requests until the
  wall-clock deadline; failures are counted instead of aborting the run
  (a soak's job is to report errors, not die on the first one).

``--promote-at T --promote-checkpoint DIR`` (graftroll, soak mode only)
fires ``POST /promote`` at the pool control plane T seconds into the
soak, then polls ``GET /rollout`` until the rollout lands. Failures and
requests are counted PER PHASE (before vs from the promote instant), so
the zero-failed-requests acceptance criterion of the rollback drill is
one command: a phase with failures > 0 means the rolling restart dropped
traffic (docs/serving.md).

Stdlib-only (no locust dependency) so it runs anywhere the extender does.
"""

from __future__ import annotations

import argparse
import concurrent.futures
import json
import sys
import threading
import time
import urllib.error
import urllib.request

SCHEMA_VERSION = 1


def make_payload(i: int, num_nodes: int = 2) -> bytes:
    # First half aws, second half azure — mirrors the cluster_set env's
    # node layout so the same payload exercises both serving families.
    items = [
        {"metadata": {"name": f"node-{j}",
                      "labels": {"cloud": "aws" if j < num_nodes // 2 else "azure"}}}
        for j in range(num_nodes)
    ]
    return json.dumps(
        {
            "pod": {"metadata": {"name": f"bench-pod-{i}"}},
            "nodes": {"items": items},
        }
    ).encode()


def one_request(base: str, i: int, num_nodes: int = 2,
                payload: bytes | None = None) -> float:
    path = "/filter" if i % 2 == 0 else "/prioritize"
    req = urllib.request.Request(
        base + path, data=payload or make_payload(i, num_nodes),
        headers={"Content-Type": "application/json"},
    )
    t0 = time.perf_counter()
    with urllib.request.urlopen(req, timeout=10) as resp:
        resp.read()
    return (time.perf_counter() - t0) * 1000.0


def _is_connection_error(exc: Exception) -> bool:
    """Connection-LEVEL failure (refused / reset before a response):
    during a rolling worker restart a SYN can land in a dying listener's
    accept queue and get RST on close. The decision endpoints are
    idempotent, so these — and only these — are safe to retry; an HTTP
    error is a real answer and never retries."""
    if isinstance(exc, urllib.error.HTTPError):
        return False
    if isinstance(exc, urllib.error.URLError):
        exc = exc.reason if isinstance(exc.reason, Exception) else exc
    import http.client

    return isinstance(exc, (ConnectionError, http.client.RemoteDisconnected))


def _request_with_retry(base: str, i: int, num_nodes: int, payload: bytes,
                        connect_retries: int) -> tuple[float, int]:
    """``(latency_ms, retries_used)``; only connection-level errors
    retry (against a fresh connection the kernel re-hashes to a live
    worker). Anything else — and a retry budget exhausted — propagates
    as a soak failure."""
    for attempt in range(connect_retries + 1):
        try:
            return one_request(base, i, num_nodes, payload), attempt
        except Exception as exc:  # noqa: BLE001 - classified below
            if attempt >= connect_retries or not _is_connection_error(exc):
                raise
            time.sleep(0.01 * (attempt + 1))
    raise AssertionError("unreachable")


def _soak(base: str, duration_s: float, threads: int, num_nodes: int,
          promote_at: float | None = None):
    """Duration-based load: each thread loops until the deadline.

    Payloads are prebuilt once (at N=1024 a node list is ~100 KB of
    JSON; rebuilding per request would bench the CLIENT's json.dumps)
    and reused round-robin so /filter and /prioritize both stay hot.
    With ``promote_at`` set, requests and failures are additionally
    split into pre/post-promote phases by the request's START time — the
    drill's zero-failed-requests bar is judged per phase — and
    connection-level errors retry up to 3 times (``_request_with_retry``:
    a dying worker's accept queue RSTs on close; the retry's fresh
    connection re-hashes to a live worker; retries are reported, HTTP
    errors never retry).
    Returns ``(sorted_latencies_ms, wall_s, failures, phases)``.
    """
    payloads = [make_payload(i, num_nodes) for i in range(16)]
    connect_retries = 3 if promote_at is not None else 0
    t_start = time.perf_counter()
    deadline = t_start + duration_s
    t_promote = None if promote_at is None else t_start + promote_at
    latencies: list = []
    failures = [0]
    phases = {"pre_promote": {"requests": 0, "failures": 0, "retries": 0},
              "post_promote": {"requests": 0, "failures": 0, "retries": 0}}
    lock = threading.Lock()

    def run(thread_id: int) -> None:
        local: list = []
        failed = 0
        counts = {"pre_promote": [0, 0, 0], "post_promote": [0, 0, 0]}
        i = thread_id
        while True:
            now = time.perf_counter()
            if now >= deadline:
                break
            phase = ("post_promote"
                     if t_promote is not None and now >= t_promote
                     else "pre_promote")
            try:
                ms, retried = _request_with_retry(
                    base, i, num_nodes, payloads[i % len(payloads)],
                    connect_retries)
                local.append(ms)
                counts[phase][0] += 1
                counts[phase][2] += retried
            except Exception:  # noqa: BLE001 - soak counts, never aborts
                failed += 1
                counts[phase][0] += 1
                counts[phase][1] += 1
            i += threads
        with lock:
            latencies.extend(local)
            failures[0] += failed
            for phase, (reqs, fails, retries) in counts.items():
                phases[phase]["requests"] += reqs
                phases[phase]["failures"] += fails
                phases[phase]["retries"] += retries

    workers = [threading.Thread(target=run, args=(t,)) for t in range(threads)]
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    return (sorted(latencies), time.perf_counter() - t_start, failures[0],
            phases if t_promote is not None else None)


def _fire_promote(control: str, checkpoint: str, delay_s: float,
                  deadline_s: float) -> dict:
    """Sleep ``delay_s``, POST the promote, then poll ``GET /rollout``
    until the rollout leaves the in-flight states (or the soak deadline
    passes). Returns what happened for the result line — the drill
    asserts on ``rollout.promotions_total``/``rollbacks_total``."""
    time.sleep(delay_s)
    out: dict = {"requested": True, "checkpoint": checkpoint}
    req = urllib.request.Request(
        control + "/promote",
        data=json.dumps({"checkpoint": checkpoint}).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            out["response_code"] = resp.status
            out["response"] = json.loads(resp.read())
    except urllib.error.HTTPError as e:
        out["response_code"] = e.code
        try:
            out["response"] = json.loads(e.read())
        except Exception:  # noqa: BLE001 - body is advisory
            out["response"] = None
        return out  # refused: nothing to poll
    except Exception as e:  # noqa: BLE001 - soak reports, never aborts
        out["error"] = str(e)
        return out
    poll_deadline = time.perf_counter() + deadline_s
    while time.perf_counter() < poll_deadline:
        try:
            status = _get_json(control + "/rollout")
        except Exception:  # noqa: BLE001 - transient; keep polling
            time.sleep(0.2)
            continue
        if not status.get("active"):
            out["rollout"] = status
            return out
        time.sleep(0.2)
    out["error"] = "rollout still in flight at the soak deadline"
    return out


def _get_json(url: str) -> dict:
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read())


def main(argv: list[str] | None = None) -> dict:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8787)
    p.add_argument("--requests", type=int, default=2000)
    p.add_argument("--duration", type=float, default=None, metavar="S",
                   help="soak mode: run for S wall-clock seconds instead "
                        "of a fixed --requests count (failures are "
                        "counted, not fatal)")
    p.add_argument("--threads", type=int, default=8)
    p.add_argument("--warmup", type=int, default=50)
    p.add_argument("--nodes", type=int, default=2,
                   help="candidate nodes per request (set-family serving "
                        "scores each one; 2 matches the two-cloud MLP)")
    p.add_argument("--control-port", type=int, default=None,
                   help="graftserve pool: the supervisor's control-plane "
                        "port — /stats/reset fans out to EVERY worker "
                        "(the data port resets only whichever worker the "
                        "kernel hands that connection) and the reported "
                        "server stats/worker count are pool-wide")
    p.add_argument("--promote-at", type=float, default=None, metavar="T",
                   help="graftroll drill hook (soak mode): POST /promote "
                        "to the control plane T seconds into the soak and "
                        "report per-phase failure counts — zero failures "
                        "in BOTH phases is the rolling-restart acceptance "
                        "bar (docs/serving.md)")
    p.add_argument("--promote-checkpoint", default=None, metavar="DIR",
                   help="checkpoint run dir to promote at --promote-at")
    p.add_argument("--history", default=None, metavar="FILE",
                   help="graftlens serving bench ledger: append this "
                        "run's schema_version:1 JSON line to FILE "
                        "(convention: BENCH_serving.jsonl at the repo "
                        "root) so rounds accumulate a durable "
                        "trajectory; `tools/decisionview --check-history`"
                        " gates the newest round against the priors")
    args = p.parse_args(argv)
    if args.requests < 1:
        p.error("--requests must be >= 1")
    if args.duration is not None and args.duration <= 0:
        p.error("--duration must be a positive number of seconds")
    if args.promote_at is not None:
        if args.duration is None:
            p.error("--promote-at needs --duration (the soak is the drill)")
        if args.promote_checkpoint is None:
            p.error("--promote-at needs --promote-checkpoint")
        if not 0 <= args.promote_at < args.duration:
            p.error("--promote-at must land inside the soak window "
                    f"[0, {args.duration})")
    elif args.promote_checkpoint is not None:
        p.error("--promote-checkpoint only applies with --promote-at")
    base = f"http://{args.host}:{args.port}"
    control = (f"http://{args.host}:{args.control_port}"
               if args.control_port is not None else base)

    for i in range(args.warmup):
        one_request(base, i, args.nodes)
    # Scope the server-side percentiles to THIS run: the latency ring
    # holds 4096 entries, so without a reset the reported p50/p99 mix in
    # the preceding run's traffic (a round-4 measurement bug). Against a
    # pool, reset through the control plane so it fans out. Older
    # extender builds lack the endpoint — warn and report un-scoped
    # stats rather than aborting the bench.
    reset_req = urllib.request.Request(control + "/stats/reset", data=b"{}",
                                       headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(reset_req, timeout=10) as resp:
            resp.read()
    except urllib.error.HTTPError:
        print("warning: server has no /stats/reset; server-side "
              "percentiles may include pre-run traffic", file=sys.stderr)

    failures = 0
    phases = promote = None
    if args.duration is not None:
        promote_thread = result_box = None
        if args.promote_at is not None:
            result_box = {}
            remaining = args.duration - args.promote_at

            def _promote_then_record():
                result_box.update(_fire_promote(
                    control, args.promote_checkpoint, args.promote_at,
                    deadline_s=max(remaining, 1.0) + 30.0))

            promote_thread = threading.Thread(target=_promote_then_record,
                                              daemon=True)
            promote_thread.start()
        latencies, wall, failures, phases = _soak(
            base, args.duration, args.threads, args.nodes,
            promote_at=args.promote_at)
        if promote_thread is not None:
            promote_thread.join(timeout=60.0)
            promote = result_box
        if not latencies:
            raise SystemExit(
                f"soak completed zero requests in {args.duration}s "
                f"({failures} failures) — is the server up?"
            )
    else:
        t_start = time.perf_counter()
        with concurrent.futures.ThreadPoolExecutor(args.threads) as pool:
            latencies = sorted(pool.map(
                lambda i: one_request(base, i, args.nodes),
                range(args.requests)))
        wall = time.perf_counter() - t_start

    def pct(p_):
        return latencies[min(len(latencies) - 1, int(p_ * len(latencies)))]

    # Worker count: the pool control plane knows it authoritatively;
    # a pool WORKER's /healthz reports its pool size too; the classic
    # single-process server reports neither -> 1.
    try:
        health = _get_json(control + "/healthz")
    except Exception:  # noqa: BLE001 - health is advisory for the line
        health = {}
    workers = int(health.get("workers", 1))

    server_stats = _get_json(control + "/stats")
    server_latency = server_stats.get("latency", {})

    out = {
        "schema_version": SCHEMA_VERSION,
        "bench": "extender_serving",
        "mode": "soak" if args.duration is not None else "count",
        "workers": workers,
        "nodes": args.nodes,
        "concurrency": args.threads,
        "requests": len(latencies),
        "threads": args.threads,
        "duration_s": round(wall, 3),
        "failures": failures,
        "client_p50_ms": round(pct(0.50), 3),
        "client_p90_ms": round(pct(0.90), 3),
        "client_p99_ms": round(pct(0.99), 3),
        "req_per_sec": round(len(latencies) / wall, 1),
        "server_p50_ms": server_latency.get("p50_ms"),
        "server_p99_ms": server_latency.get("p99_ms"),
        "backend": server_stats.get("backend"),
    }
    if phases is not None:
        out["promote_at_s"] = args.promote_at
        out["phases"] = phases
    if promote is not None:
        out["promote"] = promote
    print(json.dumps(out))
    if args.history is not None:
        # Durable append-only ledger (one JSON line per round). Plain
        # append: a torn final line from a killed bench is tolerated by
        # the decisionview reader, like the trace log's torn-line rule.
        with open(args.history, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(out) + "\n")
    return out


if __name__ == "__main__":
    main()
