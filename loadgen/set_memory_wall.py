"""Where single-chip set attention hits the memory wall (sp crossover).

VERDICT r4 item 3: ``parallel/ring_attention.py`` motivates sequence
parallelism with "tens of thousands of nodes" but no number. This tool
finds the number on the real chip: for each node count N it runs one
set-transformer minibatch fwd+bwd at descending minibatch sizes B and
reports the largest B that fits in HBM (the flax policy materializes
the ``[B, heads, N, N]`` attention scores; ring attention never
materializes the N x N matrix, so its per-chip score memory is
``B x N x N/sp`` — the crossover argument in docs/scaling.md).

Usage::

    python loadgen/set_memory_wall.py --nodes 1024,2048,4096,8192

Prints one JSON line per N: the largest fitting B, the fwd+bwd time at
that B (window-slope, fetch-synced), and the per-sample device time.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def probe(nodes: int, batches: list[int]) -> dict:
    import jax
    import jax.numpy as jnp
    import optax

    from rl_scheduler_tpu.env.cluster_set import NODE_FEAT
    from rl_scheduler_tpu.models import SetTransformerPolicy

    net = SetTransformerPolicy(dim=64, depth=2, dtype=jnp.bfloat16)
    params = net.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, nodes, NODE_FEAT), jnp.float32))
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    def loss_fn(p, obs, act):
        logits, value = net.apply(p, obs)
        logp = jax.nn.log_softmax(logits)
        pick = jnp.take_along_axis(logp, act[:, None], axis=1)
        return pick.mean() + (value ** 2).mean()

    def window(k):
        def body(p, o, obs, act):
            def step(carry, _):
                p, o = carry
                return sgd_body(p, o, obs, act), None
            return jax.lax.scan(step, (p, o), None, length=k)[0]
        return jax.jit(body)

    def sgd_body(p, o, obs, act):
        grads = jax.grad(loss_fn)(p, obs, act)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o

    def timed(fn, obs, act) -> float:
        t0 = time.perf_counter()
        p2, _ = fn(params, opt_state, obs, act)
        # fetch-sync (block_until_ready lies on tunneled backends)
        float(jax.device_get(jax.tree.leaves(p2)[0]).ravel()[0])
        return time.perf_counter() - t0

    k_small, k_big = 1, 5
    last_err = "no batch size attempted"
    for b in batches:
        try:
            obs = jnp.zeros((b, nodes, NODE_FEAT), jnp.float32)
            act = jnp.zeros((b,), jnp.int32)
            w1, w5 = window(k_small), window(k_big)
            timed(w1, obs, act)  # warm both executables
            timed(w5, obs, act)
            # Window slope nets out the fixed dispatch/tunnel overhead
            # (~70-110 ms on this backend) — the same methodology as
            # set_scale_bench.py; best of 2 per window.
            t1 = min(timed(w1, obs, act) for _ in range(2))
            t5 = min(timed(w5, obs, act) for _ in range(2))
            dt = (t5 - t1) / (k_big - k_small)
            if dt <= 0:
                return {"nodes": nodes, "max_minibatch": b,
                        "unreliable": "non-positive window slope",
                        "window_s": {"k1": round(t1, 4), "k5": round(t5, 4)}}
            return {"nodes": nodes, "max_minibatch": b,
                    "fwd_bwd_adam_ms": round(dt * 1e3, 1),
                    "us_per_sample": round(dt / b * 1e6, 2),
                    "score_tensor_mb": round(b * nodes * nodes * 2 / 2**20, 1)}
        except Exception as e:  # XlaRuntimeError: out of memory, etc.
            last_err = f"{type(e).__name__}: {str(e)[:120]}"
            continue
    return {"nodes": nodes, "max_minibatch": None, "error": last_err}


def main(argv: list[str] | None = None) -> list[dict]:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--nodes", default="1024,2048,4096,8192")
    p.add_argument("--batches", default="4096,2048,1024,512,256,128,64,32,8,1")
    args = p.parse_args(argv)
    batches = [int(b) for b in args.batches.split(",")]
    rows = []
    for n in (int(x) for x in args.nodes.split(",")):
        row = probe(n, batches)
        print(json.dumps(row), flush=True)
        rows.append(row)
    return rows


if __name__ == "__main__":
    main()
