"""Multi-seed convergence study for the structured fleet recipes.

Since round 11 this is a thin compatibility wrapper over **graftstudy**
(``rl_scheduler_tpu/studies/``, docs/studies.md) — the same CLI that
measured the round-5 fleet fragility (docs/scaling.md §1b) now compiles
to a single-variant :class:`StudySpec` and runs through the resumable
study runner, so this protocol and the subsystem cannot drift: the
per-seed rows below are printed from the SAME ledger records the study
analysis consumes, a killed study resumes instead of restarting, and
the detection-rule verdict (were all final failures flagged by the
deadline or the final acceptance?) is computed from the same fields.

For intervention sweeps, Wilson intervals, and paired-variant verdicts,
use the full CLI: ``python -m rl_scheduler_tpu.studies``.

Usage (unchanged)::

    python loadgen/seed_study.py --env cluster_set --num-nodes 64 \
        --seeds 0-5                  # the set_fleet64 recipe
    python loadgen/seed_study.py --env cluster_graph --num-nodes 64 \
        --seeds 0-2                  # the graph fleet recipe
"""

from __future__ import annotations

import argparse
import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def build_spec(env: str, num_nodes: int, seeds, iterations: int,
               eval_episodes: int, deadline: int):
    """The docs/scaling.md §1b protocol as a single-variant StudySpec
    (no guard — the point is to OBSERVE failures, not skip them)."""
    from rl_scheduler_tpu.studies import StudySpec

    # Historical preset rule: the set family scales the preset with N;
    # cluster_graph always used set_fleet64's scale knobs ("same scale
    # knobs" — the original script), at ANY node count.
    preset = ("set_fleet64" if env == "cluster_graph" or num_nodes <= 64
              else "set_fleet256")
    return StudySpec(
        name=f"seed_study_{env}_n{num_nodes}",
        env=env, preset=preset, num_nodes=num_nodes,
        seeds=tuple(seeds), iterations=iterations,
        eval_every=8, eval_episodes=64,
        final_eval_episodes=eval_episodes,
        stall_deadline=deadline,
    )


def print_rows(records: list, deadline: int) -> list:
    """The historical per-seed row format + guard verdict, from study
    ledger records."""
    import json

    rows = []
    for r in records:
        if r.get("status") != "ok":
            print(json.dumps({"seed": r["seed"], "status": r["status"]}))
            continue
        rows.append({
            "seed": r["seed"],
            "eval_at_deadline": r["eval_at_deadline"],
            "eval_final": r["eval_final"],
            "flagged_early": r["flagged_early"],
            "flagged_final": r["flagged_final"],
            "improvement_pct": r["improvement_pct"],
            "failed_final": r["failed"],
            "wall_s": r["wall_s"],
        })
        print(json.dumps(rows[-1]))
    flagged = {r["seed"] for r in rows
               if r["flagged_early"] or r["flagged_final"]}
    failed = {r["seed"] for r in rows if r["failed_final"]}
    print(f"# failed finally: {sorted(failed)}; flagged by the guard "
          f"(deadline {deadline} OR final acceptance): {sorted(flagged)}")
    if failed <= flagged:
        print("# guard: NO false negatives (every final failure was "
              "flagged at the deadline or the final acceptance)")
    else:
        print(f"# guard MISSED: {sorted(failed - flagged)}")
    if flagged - failed:
        print(f"# false positives (flagged but converged): "
              f"{sorted(flagged - failed)}")
    return rows


def main(argv: list | None = None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--env", default="cluster_set",
                   choices=("cluster_set", "cluster_graph"))
    p.add_argument("--num-nodes", type=int, default=64)
    p.add_argument("--seeds", default="0-2",
                   help="comma list and/or lo-hi ranges, e.g. 0-5 or 0,2,7")
    p.add_argument("--iterations", type=int, default=80)
    p.add_argument("--eval-episodes", type=int, default=100,
                   help="paired greedy episodes for the final comparison")
    p.add_argument("--deadline", type=int, default=16,
                   help="the detection-rule iteration (reseed-on-stall "
                        "default)")
    p.add_argument("--study-dir", default=None,
                   help="persistent study dir (resumable ledger); default "
                        "a fresh temp dir — the historical run-once "
                        "behavior")
    p.add_argument("--dry-run", action="store_true",
                   help="print the compiled trial list and exit")
    args = p.parse_args(argv)

    from rl_scheduler_tpu.studies import (
        StudyRunner,
        configure_jax_cache,
        parse_seeds,
    )

    spec = build_spec(args.env, args.num_nodes, parse_seeds(args.seeds),
                      args.iterations, args.eval_episodes, args.deadline)
    if args.dry_run:
        import json

        for t in spec.trials():
            print(json.dumps({"trial_id": t.trial_id, "seed": t.seed}))
        return []
    print(f"# {args.env} N={args.num_nodes}: graftstudy "
          f"{spec.name} ({len(spec.seeds)} seeds x {spec.iterations} "
          "iters; node-baseline threshold computed per trial — the "
          "reseed-on-stall bar)")
    configure_jax_cache()  # trials re-trace per seed; pay compiles once
    if args.study_dir is not None:
        records = StudyRunner(spec, args.study_dir, jobs=0).run()
    else:
        with tempfile.TemporaryDirectory(prefix="seed_study_") as d:
            records = StudyRunner(spec, d, jobs=0).run()
    return print_rows(records, args.deadline)


if __name__ == "__main__":
    main()
