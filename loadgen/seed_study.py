"""Multi-seed convergence study for the structured fleet recipes.

Round 5 found the fleet recipes' greedy eval is seed-fragile (seed 2
fails at N=64 AND N=256 while its stochastic training reward looks
healthy — docs/scaling.md §1b) and built the detection rule into
``train_ppo --reseed-on-stall``: a bad seed's in-training eval has not
crossed the best node baseline by iteration ~16. This tool measures
that rule over a seed range so the claim rests on more than the seeds
it was discovered with: for each seed it trains the recipe (no guard —
the point is to observe failures, not skip them), records the eval@8/16
readings the guard would have acted on, runs the 100-episode paired
greedy evaluation, and prints one row per seed plus a verdict on the
detection rule (were all final failures already separated from the
baseline threshold at the deadline?).

Usage::

    python loadgen/seed_study.py --env cluster_set --num-nodes 64 \
        --seeds 0-5                  # the set_fleet64 recipe
    python loadgen/seed_study.py --env cluster_graph --num-nodes 64 \
        --seeds 0-2                  # the graph fleet recipe
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def parse_seeds(spec: str) -> list[int]:
    out: list[int] = []
    for part in spec.split(","):
        if "-" in part:
            lo, hi = part.split("-")
            out.extend(range(int(lo), int(hi) + 1))
        else:
            out.append(int(part))
    return out


def main(argv: list[str] | None = None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--env", default="cluster_set",
                   choices=("cluster_set", "cluster_graph"))
    p.add_argument("--num-nodes", type=int, default=64)
    p.add_argument("--seeds", default="0-2",
                   help="comma list and/or lo-hi ranges, e.g. 0-5 or 0,2,7")
    p.add_argument("--iterations", type=int, default=80)
    p.add_argument("--eval-episodes", type=int, default=100,
                   help="paired greedy episodes for the final comparison")
    p.add_argument("--deadline", type=int, default=16,
                   help="the detection-rule iteration (reseed-on-stall "
                        "default)")
    args = p.parse_args(argv)

    from rl_scheduler_tpu.agent.evaluate import (
        best_node_baseline_reward,
        structured_evaluate,
    )
    from rl_scheduler_tpu.agent.ppo import ppo_train
    from rl_scheduler_tpu.agent.presets import PPO_PRESETS
    from rl_scheduler_tpu.agent.train_ppo import make_bundle_and_net

    if args.env == "cluster_set":
        cfg = PPO_PRESETS["set_fleet64" if args.num_nodes <= 64
                          else "set_fleet256"]
    else:
        # The measured graph fleet recipe (docs/scaling.md §1b): flax
        # GNN, bf16, 1 epoch, 1024 envs.
        cfg = dataclasses.replace(
            PPO_PRESETS["set_fleet64"])  # same scale knobs
    cfg = dataclasses.replace(cfg, eval_every=8, eval_episodes=64)
    bundle, net = make_bundle_and_net(args.env, cfg,
                                      num_nodes=args.num_nodes)

    threshold = best_node_baseline_reward(args.env, bundle,
                                          cfg.eval_episodes, seed=0)
    print(f"# {args.env} N={args.num_nodes}: node-baseline threshold "
          f"{threshold:.1f} (the reseed-on-stall bar)")

    rows = []
    for seed in parse_seeds(args.seeds):
        evals: dict[int, float] = {}

        def eval_log(i, metrics, _evals=evals):
            _evals[i + 1] = metrics["eval_episode_reward_mean"]

        t0 = time.time()
        runner, history = ppo_train(bundle, cfg, args.iterations,
                                    seed=seed, net=net,
                                    eval_log_fn=eval_log)
        wall = time.time() - t0
        rep = structured_evaluate(args.env, bundle, net, runner.params,
                                  num_episodes=args.eval_episodes, seed=0)
        by_deadline = max(
            (v for i, v in evals.items() if i <= args.deadline),
            default=float("-inf"),
        )
        final_eval = evals[max(evals)] if evals else float("-inf")
        rows.append({
            "seed": seed,
            "eval_at_deadline": round(by_deadline, 1),
            "eval_final": round(final_eval, 1),
            "flagged_early": by_deadline < threshold,
            # The guard's second checkpoint (--reseed-on-stall final
            # acceptance): the run's last eval must beat the bar too.
            "flagged_final": final_eval < threshold,
            "improvement_pct": round(rep.improvement_vs_best_baseline_pct, 1),
            "failed_final": rep.improvement_vs_best_baseline_pct < 0,
            "wall_s": round(wall),
        })
        print(json.dumps(rows[-1]))

    flagged = {r["seed"] for r in rows
               if r["flagged_early"] or r["flagged_final"]}
    failed = {r["seed"] for r in rows if r["failed_final"]}
    print(f"# failed finally: {sorted(failed)}; flagged by the guard "
          f"(deadline {args.deadline} OR final acceptance): "
          f"{sorted(flagged)}")
    if failed <= flagged:
        print("# guard: NO false negatives (every final failure was "
              "flagged at the deadline or the final acceptance)")
    else:
        print(f"# guard MISSED: {sorted(failed - flagged)}")
    if flagged - failed:
        print(f"# false positives (flagged but converged): "
              f"{sorted(flagged - failed)}")
    return rows


if __name__ == "__main__":
    main()
