"""graftlens part 3: the serving perf report with regression gating.

``tools/traceview`` turned TRAINING profiler traces into budget-checked
numbers; nothing did the same for serving. decisionview is the serving
sibling: a pure-stdlib joiner over the three artifacts the serving plane
already produces —

- a ``/stats`` **snapshot** (single-process or pool body; a JSON file or
  a live ``http://`` URL) carrying the graftlens phase histograms and
  the end-to-end latency lifetime numbers,
- a **trace-log** directory (``scheduler/tracelog.py`` segments) whose
  records carry per-decision span breakdowns, policy generations, and
  the ``endpoint=probe`` tag that excludes synthetic traffic,
- a serving **bench history** ledger (``extender_bench --history``
  JSONL — one ``schema_version: 1`` line per round),

— into one report:

- **Phase decomposition**: per-phase lifetime mean (ms), share of the
  end-to-end decide latency, and the reconciliation row (phases must sum
  to >=90% of end-to-end — a broken span is visible as a gap).
- **Per-generation comparison**: trace records grouped by policy
  generation (probes excluded) with count, mean/max latency and
  fail-open fraction — did the last promote actually get faster?
- **SLO attainment**: lifetime good-fraction per objective from the
  snapshot's SLO section, next to the current burn state.
- **Regression gating**: ``--check`` compares phase means against
  ``tools/decisionview/budgets.json`` (absent phase or over budget =
  exit 2 — the traceview/graftlint fail-the-build contract);
  ``--check-history`` compares the newest bench round against the best
  prior round with a tolerance (throughput down or p50 up = exit 2),
  which turns the serving bench trajectory into a gate instead of a
  scrapbook.

Every input is optional — pass what you have; the report prints the
sections it can compute. ``make serve-report`` runs it against the
checked-in fixture (off-network tier-1) or a live pool
(``SERVE_STATS=http://host:port/stats``). docs/observability.md.
"""

from __future__ import annotations

import json
from pathlib import Path

SCHEMA_VERSION = 1
# The reconciliation bar: the instrumented phases must explain at least
# this share of the end-to-end decide latency, else the decomposition is
# lying by omission (a renamed/broken span must not pass silently).
MIN_PHASE_COVERAGE = 0.90
# Hot-path order for the decomposition table (extender.PHASES, not
# imported: decisionview must stay stdlib-only and runnable anywhere).
# graftfwd added batch_wait (micro-batch admission window) between
# observe and forward; pre-graftfwd snapshots simply lack the phase.
PHASE_ORDER = ("parse", "observe", "batch_wait", "forward", "marshal",
               "trace")


# ------------------------------------------------------------------ inputs


def load_stats(source: str) -> dict:
    """A ``/stats`` body from a JSON file or a live ``http://`` URL —
    a pool control plane or a graftfleet controller's merged body (the
    fleet merge reuses the pool's sections, so both render alike)."""
    if source.startswith(("http://", "https://")):
        import urllib.request

        with urllib.request.urlopen(source, timeout=10) as resp:
            return json.load(resp)
    return json.loads(Path(source).read_text())


def load_bench_history(path: str | Path) -> list:
    """The serving bench ledger: one parsed JSON line per round, in file
    order. Torn/blank lines are skipped (a killed bench must not poison
    the ledger), unknown schema versions are kept — fields are read
    defensively."""
    path = Path(path)
    if not path.is_file():
        return []
    rounds = []
    for line in path.read_text().splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rounds.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    return rounds


def load_trace_records(trace_dir: str | Path,
                       include_probes: bool = False) -> list:
    """Replayed trace records, synthetic probe traffic EXCLUDED by
    default (``endpoint=probe`` — the client-facing numbers must match
    what clients experienced). Reuses the trace log's own merged
    replayer (``scheduler/tracelog.iter_trace_merged`` — a stdlib-only
    module: a pool's per-worker streams heap-merged by timestamp, torn
    trailing lines skipped), so the report can never disagree with the
    writer about segment order and per-generation windows line up
    chronologically across workers."""
    from rl_scheduler_tpu.scheduler.tracelog import (
        is_synthetic_endpoint,
        iter_trace_merged,
    )

    records = []
    for record in iter_trace_merged(trace_dir):
        if not include_probes and \
                is_synthetic_endpoint(record.get("endpoint")):
            continue
        records.append(record)
    return records


# ----------------------------------------------------------------- report


def _phase_rows(stats: dict) -> tuple[list, dict]:
    """``(rows, reconciliation)`` for the phase-decomposition table from
    a /stats body (single-process and pool bodies share the lifetime
    keys). Rows: ``(phase, mean_ms, count, fraction_of_e2e)``."""
    phases = stats.get("phases") or {}
    latency = stats.get("latency") or {}
    e2e_mean = latency.get("lifetime_mean_ms")
    rows = []
    phase_sum = 0.0
    ordered = [p for p in PHASE_ORDER if p in phases]
    ordered += [p for p in sorted(phases) if p not in PHASE_ORDER]
    for phase in ordered:
        entry = phases[phase]
        mean = entry.get("lifetime_mean_ms")
        count = entry.get("lifetime_count", 0)
        frac = (mean / e2e_mean if mean is not None and e2e_mean
                else None)
        if mean is not None:
            phase_sum += mean
        rows.append((phase, mean, count, frac))
    reconciliation = {
        "e2e_mean_ms": e2e_mean,
        "phase_sum_ms": round(phase_sum, 4),
        "coverage": (round(phase_sum / e2e_mean, 4)
                     if e2e_mean else None),
        "min_coverage": MIN_PHASE_COVERAGE,
    }
    return rows, reconciliation


def _generation_rows(records: list) -> list:
    """Per-policy-generation comparison from trace records (probes
    already excluded): ``(generation, count, mean_ms, p95_ms,
    fail_open_fraction)`` sorted by generation."""
    by_gen: dict = {}
    for record in records:
        by_gen.setdefault(record.get("generation", 0), []).append(record)
    rows = []
    for gen in sorted(by_gen):
        recs = by_gen[gen]
        lats = sorted(r.get("latency_ms") for r in recs
                      if r.get("latency_ms") is not None)
        fails = sum(1 for r in recs if r.get("fail_open"))
        mean = round(sum(lats) / len(lats), 3) if lats else None
        p95 = (round(lats[min(len(lats) - 1, int(0.95 * len(lats)))], 3)
               if lats else None)
        rows.append((gen, len(recs), mean, p95,
                     round(fails / len(recs), 4) if recs else 0.0))
    return rows


def _slo_rows(stats: dict) -> list:
    """``(objective, target, lifetime_attainment, burning)`` from the
    snapshot's SLO section. Attainment is lifetime good-fraction —
    latency over decided requests, availability over all."""
    slo = stats.get("slo")
    if not slo:
        return []
    lifetime = slo.get("lifetime", {})
    requests = lifetime.get("requests_total", 0)
    fail_open = lifetime.get("fail_open_total", 0)
    decided = max(requests - fail_open, 0)
    rows = []
    for name, objective in sorted(slo.get("objectives", {}).items()):
        if name == "latency":
            denom, bad = decided, lifetime.get("latency_bad_total", 0)
        else:
            denom, bad = requests, fail_open
        attainment = round(1.0 - bad / denom, 6) if denom else None
        rows.append((name, objective.get("target"), attainment,
                     objective.get("burning", False)))
    return rows


def build_report(stats: dict | None = None, records: list | None = None,
                 history: list | None = None) -> dict:
    """The decisionview report body (one bench-style JSON line). Every
    section is computed from whichever inputs were supplied."""
    out: dict = {"metric": "decisionview-serve-report",
                 "schema_version": SCHEMA_VERSION}
    if stats is not None:
        rows, reconciliation = _phase_rows(stats)
        out["phases"] = {
            phase: {"mean_ms": mean, "count": count, "fraction": frac}
            for phase, mean, count, frac in rows
        }
        out["reconciliation"] = reconciliation
        slo_rows = _slo_rows(stats)
        if slo_rows:
            out["slo"] = {
                name: {"target": target, "attainment": attainment,
                       "burning": burning}
                for name, target, attainment, burning in slo_rows
            }
        latency = stats.get("latency") or {}
        out["e2e"] = {
            "mean_ms": latency.get("lifetime_mean_ms"),
            "count": latency.get("lifetime_count"),
            "p50_ms": latency.get("p50_ms"),
            "p99_ms": latency.get("p99_ms"),
        }
        if stats.get("fastpath"):
            # graftfwd lever counters (score cache / batcher / int8) —
            # passed through for the report and the cache-hit-rate
            # floor (check_budgets).
            out["fastpath"] = stats["fastpath"]
    if records is not None:
        out["generations"] = {
            str(gen): {"count": count, "mean_ms": mean, "p95_ms": p95,
                       "fail_open_fraction": fail_frac}
            for gen, count, mean, p95, fail_frac in _generation_rows(records)
        }
        out["trace_records"] = len(records)
    if history:
        newest = history[-1]
        out["bench"] = {
            "rounds": len(history),
            "newest": {k: newest.get(k) for k in
                       ("req_per_sec", "client_p50_ms", "client_p99_ms",
                        "workers", "nodes", "concurrency", "failures")},
        }
    return out


def format_report(report: dict) -> str:
    """Human-readable tables for the terminal (the JSON line is the
    machine contract; this is the operator's view)."""
    lines = ["decisionview serving report", "=" * 27]
    phases = report.get("phases")
    if phases:
        lines += ["", "Phase decomposition (lifetime means, probe "
                      "traffic excluded):",
                  f"  {'phase':<10} {'mean ms':>10} {'count':>10} "
                  f"{'share of e2e':>13}"]
        for phase, entry in phases.items():
            mean = entry.get("mean_ms")
            frac = entry.get("fraction")
            lines.append(
                f"  {phase:<10} "
                f"{mean if mean is not None else '-':>10} "
                f"{entry.get('count', 0):>10} "
                f"{f'{frac * 100:.1f}%' if frac is not None else '-':>13}")
        rec = report.get("reconciliation", {})
        cov = rec.get("coverage")
        lines.append(
            f"  phases sum {rec.get('phase_sum_ms')} ms vs end-to-end "
            f"{rec.get('e2e_mean_ms')} ms "
            f"({f'{cov * 100:.1f}%' if cov is not None else 'n/a'} "
            f"coverage; bar {rec.get('min_coverage', MIN_PHASE_COVERAGE) * 100:.0f}%)")
    slo = report.get("slo")
    if slo:
        lines += ["", "SLO attainment (lifetime):"]
        for name, entry in slo.items():
            att = entry.get("attainment")
            lines.append(
                f"  {name:<13} target {entry.get('target')}  attainment "
                f"{f'{att:.6f}' if att is not None else 'n/a'}  "
                f"{'BURNING' if entry.get('burning') else 'ok'}")
    gens = report.get("generations")
    if gens:
        lines += ["", "Per-generation latency (trace records, probes "
                      "excluded):",
                  f"  {'gen':>4} {'count':>8} {'mean ms':>9} "
                  f"{'p95 ms':>9} {'fail-open':>10}"]
        for gen, entry in gens.items():
            lines.append(
                f"  {gen:>4} {entry['count']:>8} "
                f"{entry['mean_ms'] if entry['mean_ms'] is not None else '-':>9} "
                f"{entry['p95_ms'] if entry['p95_ms'] is not None else '-':>9} "
                f"{entry['fail_open_fraction'] * 100:>9.1f}%")
    bench = report.get("bench")
    if bench:
        newest = bench["newest"]
        lines += ["", f"Bench history: {bench['rounds']} round(s); newest: "
                      f"{newest.get('req_per_sec')} req/s, p50 "
                      f"{newest.get('client_p50_ms')} ms "
                      f"({newest.get('workers')}w x N="
                      f"{newest.get('nodes')} x c="
                      f"{newest.get('concurrency')})"]
    return "\n".join(lines)


# ----------------------------------------------------------------- checks


def check_budgets(report: dict, budgets: dict) -> list:
    """Violation strings for ``--check`` (empty = pass): a budgeted
    phase over ``budget_ms * (1 + tolerance_pct/100)`` fails, an ABSENT
    budgeted phase fails (a broken span must not pass silently), and a
    phase-coverage reconciliation below the bar fails. Same exit-2
    contract as traceview's budget check."""
    tolerance = float(budgets.get("tolerance_pct", 25.0))
    violations = []
    phases = report.get("phases") or {}
    # Phases a budget file marks optional may be ABSENT without failing
    # (still budget-checked when present): batch_wait only exists on
    # graftfwd-era builds, and `--check` against a still-deployed older
    # pool mid-rollout must not read the version skew as a broken span.
    optional = set(budgets.get("optional_phases") or ())
    for phase, budget_ms in sorted((budgets.get("phases") or {}).items()):
        entry = phases.get(phase)
        mean = entry.get("mean_ms") if entry else None
        limit = float(budget_ms) * (1.0 + tolerance / 100.0)
        if mean is None:
            if phase in optional:
                continue
            violations.append(
                f"phase {phase!r}: absent from the report (budget "
                f"{budget_ms} ms) — spans disabled or a renamed phase?")
        elif mean > limit:
            violations.append(
                f"phase {phase!r}: {mean:.3f} ms mean exceeds budget "
                f"{budget_ms} ms by more than {tolerance:.0f}% "
                f"(limit {limit:.3f} ms)")
    rec = report.get("reconciliation")
    if rec and rec.get("coverage") is not None:
        if rec["coverage"] < rec.get("min_coverage", MIN_PHASE_COVERAGE):
            violations.append(
                f"phase coverage {rec['coverage'] * 100:.1f}% of "
                f"end-to-end is below the "
                f"{rec.get('min_coverage', MIN_PHASE_COVERAGE) * 100:.0f}% "
                "bar — a span is missing time")
    # graftfwd: the cache-hit-rate floor. Only binds when the snapshot
    # actually ran a score cache with enough traffic to judge — a
    # cache-off serve config is a legitimate deployment, not a
    # regression; a cache-ON one whose hit rate collapsed (epoch
    # misconfigured, keys churning) is.
    floor = budgets.get("min_cache_hit_rate")
    cache = (report.get("fastpath") or {}).get("cache")
    if floor is not None and cache:
        requests = (cache.get("hits_total", 0)
                    + cache.get("misses_total", 0))
        min_requests = int(budgets.get("cache_floor_min_requests", 20))
        hit_rate = cache.get("hit_rate")
        if requests >= min_requests and hit_rate is not None \
                and hit_rate < float(floor):
            violations.append(
                f"score-cache hit rate {hit_rate:.3f} over {requests} "
                f"requests is below the {float(floor):.3f} floor — "
                "epoch/key churn is defeating the cache")
    return violations


def check_history(history: list, tolerance_pct: float = 25.0) -> list:
    """Violation strings for ``--check-history``: the newest bench round
    must keep ``req_per_sec`` within ``tolerance_pct`` below — and
    ``client_p50_ms`` within ``tolerance_pct`` above — the BEST prior
    round at the same (workers, nodes, concurrency, lever, front,
    keepalive) shape (``lever`` is graftfwd's matrix dimension;
    ``front``/``keepalive`` are graftfront's — a keep-alive asyncio row
    must not be judged against a reconnect-per-request threading row,
    and vice versa; rows without a key gate against each other as
    before). Fewer than two comparable rounds passes vacuously (the
    ledger is just starting)."""
    if len(history) < 2:
        return []
    newest = history[-1]
    shape_keys = ("workers", "nodes", "concurrency", "lever",
                  "front", "keepalive")
    shape = tuple(newest.get(k) for k in shape_keys)
    priors = [r for r in history[:-1]
              if tuple(r.get(k) for k in shape_keys) == shape]
    violations = []
    tol = tolerance_pct / 100.0
    best_rps = max((r.get("req_per_sec") for r in priors
                    if r.get("req_per_sec") is not None), default=None)
    rps = newest.get("req_per_sec")
    if best_rps is not None and rps is not None and rps < best_rps * (1 - tol):
        violations.append(
            f"req_per_sec regressed: {rps} vs best prior {best_rps} "
            f"(> {tolerance_pct:.0f}% down) at shape "
            f"workers={shape[0]} nodes={shape[1]} concurrency={shape[2]}")
    best_p50 = min((r.get("client_p50_ms") for r in priors
                    if r.get("client_p50_ms") is not None), default=None)
    p50 = newest.get("client_p50_ms")
    if best_p50 is not None and p50 is not None and p50 > best_p50 * (1 + tol):
        violations.append(
            f"client_p50_ms regressed: {p50} vs best prior {best_p50} "
            f"(> {tolerance_pct:.0f}% up) at shape "
            f"workers={shape[0]} nodes={shape[1]} concurrency={shape[2]}")
    return violations


def check_slo(report: dict) -> list:
    """Violation strings for ``--slo-check``: any burning objective
    fails (the gate `make slo-check` runs)."""
    return [
        f"SLO objective {name!r} is burning (target {entry.get('target')}, "
        f"lifetime attainment {entry.get('attainment')})"
        for name, entry in (report.get("slo") or {}).items()
        if entry.get("burning")
    ]
