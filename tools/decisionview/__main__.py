"""decisionview CLI (graftlens part 3 — see the package docstring).

Usage::

    # full report against a live pool's control plane + its trace dir
    python -m tools.decisionview --stats http://127.0.0.1:8788/stats \
        --trace /var/trace --bench BENCH_serving.jsonl

    # the regression gate (tier-1 runs this against the checked-in
    # fixture; exit 2 on an over-budget/absent phase or coverage gap)
    python -m tools.decisionview --stats tests/fixtures/decisionview/stats.json \
        --check --budgets tools/decisionview/budgets.json

    # serving bench trajectory gate (exit 2 when the newest round
    # regressed vs the best prior round at the same shape)
    python -m tools.decisionview --bench BENCH_serving.jsonl --check-history

    # SLO gate: exit 2 while any objective burns (`make slo-check`)
    python -m tools.decisionview --stats http://127.0.0.1:8788/stats --slo-check

Prints the human tables to stdout plus ONE bench.py-style JSON line
(the documented schema); all violations go to stderr.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from tools.decisionview import (
    build_report,
    check_budgets,
    check_history,
    check_slo,
    format_report,
    load_bench_history,
    load_stats,
    load_trace_records,
)


def main(argv: list | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tools.decisionview",
        description="Join a /stats snapshot, a decision-trace directory "
                    "and the serving bench ledger into one phase/SLO/"
                    "generation report, with budget + history regression "
                    "gates.")
    p.add_argument("--stats", default=None, metavar="FILE|URL",
                   help="/stats body: a JSON file or a live http:// URL "
                        "(pool control plane, single-process server, or "
                        "a graftfleet controller's merged /stats)")
    p.add_argument("--trace", default=None, metavar="DIR",
                   help="decision trace-log directory (--trace-dir); "
                        "probe records are excluded")
    p.add_argument("--bench", default=None, metavar="FILE",
                   help="serving bench ledger (extender_bench --history "
                        "JSONL)")
    p.add_argument("--budgets", default=None,
                   help="phase-budget JSON (default with --check: "
                        "tools/decisionview/budgets.json)")
    p.add_argument("--check", action="store_true",
                   help="exit 2 on an over-budget phase, an absent "
                        "budgeted phase, or phase coverage below the bar")
    p.add_argument("--check-history", action="store_true",
                   help="exit 2 when the newest bench round regressed "
                        "vs the best prior round at the same shape")
    p.add_argument("--history-tolerance-pct", type=float, default=25.0,
                   help="tolerance for --check-history (default 25)")
    p.add_argument("--slo-check", action="store_true",
                   help="exit 2 while any SLO objective is burning")
    p.add_argument("--write-budgets", default=None, metavar="OUT",
                   help="record this report's phase means as the new "
                        "budget baseline (traceview's --write-budgets "
                        "contract)")
    p.add_argument("--tolerance-pct", type=float, default=50.0,
                   help="tolerance recorded by --write-budgets "
                        "(default 50)")
    p.add_argument("--json", action="store_true",
                   help="print only the JSON line (no human tables)")
    args = p.parse_args(argv)

    if args.stats is None and args.trace is None and args.bench is None:
        p.error("pass at least one input (--stats / --trace / --bench)")
    if args.check and args.stats is None:
        p.error("--check needs --stats (the phase means live there)")
    if args.check_history and args.bench is None:
        p.error("--check-history needs --bench")
    if args.slo_check and args.stats is None:
        p.error("--slo-check needs --stats")

    try:
        stats = load_stats(args.stats) if args.stats else None
    except (OSError, json.JSONDecodeError) as e:
        print(f"decisionview: cannot load stats {args.stats}: {e}",
              file=sys.stderr)
        return 1
    records = (load_trace_records(args.trace)
               if args.trace is not None else None)
    history = (load_bench_history(args.bench)
               if args.bench is not None else None)

    report = build_report(stats=stats, records=records, history=history)
    if not args.json:
        print(format_report(report))
        print()
    print(json.dumps(report), flush=True)

    if args.write_budgets:
        budgets = {
            "tolerance_pct": args.tolerance_pct,
            "unit": "ms",
            "phases": {
                phase: entry["mean_ms"]
                for phase, entry in (report.get("phases") or {}).items()
                if entry.get("mean_ms") is not None
            },
        }
        Path(args.write_budgets).write_text(
            json.dumps(budgets, indent=2) + "\n")
        print(f"decisionview: budgets written to {args.write_budgets}",
              file=sys.stderr)

    violations = []
    if args.check:
        budgets_path = Path(args.budgets
                            or Path(__file__).parent / "budgets.json")
        try:
            budgets = json.loads(budgets_path.read_text())
        except (OSError, json.JSONDecodeError) as e:
            print(f"decisionview: cannot load budgets {budgets_path}: {e}",
                  file=sys.stderr)
            return 1
        violations += check_budgets(report, budgets)
    if args.check_history:
        violations += check_history(history or [],
                                    args.history_tolerance_pct)
    if args.slo_check:
        violations += check_slo(report)
    for violation in violations:
        print(f"decisionview: REGRESSION: {violation}", file=sys.stderr)
    if violations:
        return 2
    if args.check or args.check_history or args.slo_check:
        print("decisionview: all gates OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
