"""GL016 Python-scalar pytree leaf on a traced argument type.

A ``NamedTuple``/registered-pytree field holding a Python ``bool``/
``int``/``float`` is a pytree LEAF: pass the container as a traced
argument and that leaf becomes a tracer, so the first ``if
params.random_start:`` throws ``TracerBoolConversionError`` — the PR-7
near-miss that would have broken 14 tests had the field ridden
``vmap``. The discipline is: fields of containers that cross the trace
boundary as ARGUMENTS are arrays (``jnp.ndarray`` annotations, array
defaults); Python scalars belong on config objects that stay closed
over (``ClusterSetParams.random_phase`` is safe exactly because
``bundle.py`` closes over it).

Detection needs both halves, possibly in different modules: (a) the
container — a ``NamedTuple`` subclass or a registered pytree class
(``@struct.dataclass``, ``@register_pytree_node_class``,
``register_pytree_node(Cls, ...)``) with a scalar-annotated,
scalar-defaulted field; (b) the flow — some TRACED function (engine
traced-scope verdict) annotating a non-static parameter with that type.
Plain ``@dataclasses.dataclass`` types are deliberately out of scope:
they are not pytrees, and jit fails loudly (not silently late) when
handed one.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.engine import (LintContext, Module, dotted_last,
                                    dotted_name)
from tools.graftlint.rules import Rule, register

_SCALARS = frozenset({"bool", "int", "float"})


def _pytree_decorator(dec: ast.AST) -> bool:
    """``@struct.dataclass`` / ``@register_pytree_node_class`` — NOT the
    stdlib ``@dataclass``/``@dataclasses.dataclass`` (not a pytree)."""
    if isinstance(dec, ast.Call):
        dec = dec.func
    if dotted_last(dec) == "register_pytree_node_class":
        return True
    full = dotted_name(dec) or ""
    return full.endswith("struct.dataclass")


def _pytree_classes(module: Module) -> list:
    """(ClassDef, reason) for pytree-registered classes in the module."""
    registered: set = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call) and \
                dotted_last(node.func) == "register_pytree_node" and \
                node.args and isinstance(node.args[0], ast.Name):
            registered.add(node.args[0].id)
    out = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        if any(dotted_last(b) == "NamedTuple" for b in node.bases):
            out.append((node, "NamedTuple"))
        elif any(_pytree_decorator(d) for d in node.decorator_list):
            out.append((node, "registered pytree"))
        elif node.name in registered:
            out.append((node, "register_pytree_node"))
    return out


def _scalar_fields(cls: ast.ClassDef) -> Iterator:
    """(field name, annotation, line) for Python-scalar-defaulted fields."""
    for stmt in cls.body:
        if not isinstance(stmt, ast.AnnAssign) or stmt.value is None:
            continue
        ann = dotted_last(stmt.annotation)
        if ann not in _SCALARS:
            continue
        if isinstance(stmt.value, ast.Constant) and \
                isinstance(stmt.value.value, (bool, int, float)) and \
                isinstance(stmt.target, ast.Name):
            yield stmt.target.id, ann, stmt.lineno


def _traced_consumers(ctx: LintContext) -> dict:
    """type name -> [(module rel, function qualname, param)] for traced,
    non-static parameters annotated with that type, across the lint set."""
    cached = getattr(ctx, "_gl016_consumers", None)
    if cached is not None:
        return cached
    index: dict = {}
    for module in ctx.modules:
        for rec in module.functions:
            if not rec.traced:
                continue
            args = rec.node.args
            for arg in args.posonlyargs + args.args + args.kwonlyargs:
                if arg.annotation is None or arg.arg in rec.static_params:
                    continue
                ann = dotted_last(arg.annotation)
                if ann:
                    index.setdefault(ann, []).append(
                        (module.rel, rec.qualname, arg.arg))
    ctx._gl016_consumers = index
    return index


@register
class PythonScalarPytreeLeaf(Rule):
    id = "GL016"
    name = "python-scalar-pytree-leaf"
    summary = ("bool/int/float-defaulted field on a NamedTuple/registered "
               "pytree that flows into a traced argument position")

    def check(self, module: Module, ctx: LintContext) -> Iterator:
        classes = _pytree_classes(module)
        if not classes:
            return
        consumers = _traced_consumers(ctx)
        for cls, kind in classes:
            used = consumers.get(cls.name)
            if not used:
                continue
            rel, qual, param = used[0]
            for field, ann, line in _scalar_fields(cls):
                yield self.finding(
                    module, line,
                    f"{cls.name}.{field} is a Python {ann} leaf on a "
                    f"{kind}, and {cls.name} is a traced argument "
                    f"({rel}:{qual}({param})) — under vmap/jit this leaf "
                    f"becomes a tracer and `if .{field}:` raises "
                    f"TracerBoolConversionError; make it a jnp array, or "
                    f"keep the container closed over instead of passed",
                )
