"""graftlint rule registry.

Rules are plugins: each module under ``tools/graftlint/rules/`` defines one
or more :class:`Rule` subclasses and registers them with ``@register``. The
engine asks the registry (not the modules) what to run, so adding a rule is
one new file plus a fixture pair — nothing in the engine changes.

Every rule is grounded in a failure mode this repo has actually paid for;
the rule docstrings and ``docs/static_analysis.md`` carry the receipts.
"""

from __future__ import annotations

from typing import Iterator

from tools.graftlint.engine import Finding, LintContext, Module

RULES: dict = {}  # rule id -> Rule instance


class Rule:
    """Base class: subclass, set ``id``/``name``/``summary``, implement
    ``check(module, ctx) -> Iterator[Finding]``."""

    id: str = "GL999"
    name: str = "unnamed"
    summary: str = ""

    def check(self, module: Module, ctx: LintContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, module: Module, line: int, message: str) -> Finding:
        return Finding(self.id, module.rel, line, message)


def register(cls):
    """Class decorator: instantiate and add to the registry."""
    inst = cls()
    if inst.id in RULES:
        raise ValueError(f"duplicate rule id {inst.id}")
    RULES[inst.id] = inst
    return cls


_LOADED = False


def load_rules() -> dict:
    """Import every rule module exactly once; return the registry."""
    global _LOADED
    if not _LOADED:
        from tools.graftlint.rules import (  # noqa: F401
            async_blocking,
            atomic_write,
            clocks,
            control_flow,
            donate,
            host_sync,
            metrics_loop,
            pallas_tiles,
            prng,
            shared_key,
            swallow,
            test_coverage,
            thread_drain,
            toctou,
            pytree_leaf,
            weak_types,
        )
        _LOADED = True
    return RULES
