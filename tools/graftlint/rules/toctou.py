"""GL014 check-then-act TOCTOU on filesystem paths.

The runner-lock and ``--fresh`` races (CHANGES.md PRs 9/13) were all the
same shape: ``exists()``/``is_file()`` on a path, then a destructive or
creating act on the SAME path expression later in the scope — and
between the two, another process (a resumed study runner, a second
fleet controller, a promote racing a snapshot) changes the world. The
fixes were always one of two idioms, and this rule accepts exactly
those:

- **EAFP**: drop the check, act, and catch ``FileNotFoundError`` /
  pass ``missing_ok=True`` / ``ignore_errors=True`` / ``exist_ok=True``
  (``utils.fsio.fresh_dir`` packages the rmtree+mkdir case);
- **a real lock**: scopes whose flow touches ``O_EXCL`` or the
  ``utils/pidlock`` seam (``acquire_pidfile_lock`` /
  ``acquire_runner_lock`` / ``read_live_pid`` / ``pid_alive``) are
  exempt wholesale — check-then-act UNDER the lock is the lock's whole
  point.

Matching is by canonical path expression (:func:`path_expr`) within one
scope: a check on ``dest`` pairs with ``shutil.rmtree(dest)`` and with
``shutil.rmtree(str(dest))``, not with acts on other paths.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.engine import LintContext, Module, dotted_last
from tools.graftlint.flow import path_expr, scope_walk
from tools.graftlint.rules import Rule, register

# Existence checks: method form (p.exists()) and os.path form.
_CHECK_METHODS = frozenset({"exists", "is_file", "is_dir"})
_CHECK_FUNCS = frozenset({"exists", "isfile", "isdir", "lexists"})

# Acts racing the check: destructive ops and creating writes. The
# atomic renames (os.rename/os.replace) are deliberately absent — they
# overwrite atomically, which is the FIX for this class, not the bug.
_ACT_FUNCS = frozenset({"rmtree", "remove", "unlink", "move"})
_ACT_METHODS = frozenset({"unlink", "rename", "rmdir", "write_text",
                          "touch"})

# Keyword escapes that make the act EAFP on their own.
_EAFP_KWARGS = frozenset({"missing_ok", "ignore_errors", "exist_ok"})

# Names whose presence in a scope means the check-act runs under a real
# inter-process lock (utils/pidlock) or creates with O_EXCL itself.
_LOCK_NAMES = frozenset({
    "O_EXCL", "acquire_pidfile_lock", "acquire_runner_lock",
    "read_live_pid", "_read_live_pid", "pid_alive",
})


def _scope_has_lock(scope) -> bool:
    for node in scope_walk(scope):
        if isinstance(node, ast.Name) and node.id in _LOCK_NAMES:
            return True
        if isinstance(node, ast.Attribute) and node.attr in _LOCK_NAMES:
            return True
    return False


def _eafp_kwargs(call: ast.Call) -> bool:
    return any(kw.arg in _EAFP_KWARGS and
               not (isinstance(kw.value, ast.Constant) and
                    kw.value.value is False)
               for kw in call.keywords)


def _checks(scope) -> dict:
    """path expression -> earliest check line in this scope."""
    out: dict = {}
    for node in scope_walk(scope):
        if not isinstance(node, ast.Call):
            continue
        expr = None
        name = dotted_last(node.func)
        if isinstance(node.func, ast.Attribute) and \
                name in _CHECK_METHODS and not node.args:
            expr = path_expr(node.func.value)
        elif name in _CHECK_FUNCS and node.args and \
                isinstance(node.func, ast.Attribute):  # os.path.exists(p)
            expr = path_expr(node.args[0])
        if expr is not None:
            out.setdefault(expr, node.lineno)
            if node.lineno < out[expr]:
                out[expr] = node.lineno
    return out


def _acts(scope) -> Iterator:
    """(path-expression, call, verb) for racing acts in this scope."""
    for node in scope_walk(scope):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_last(node.func)
        if name in _ACT_FUNCS and node.args and not (
                isinstance(node.func, ast.Attribute) and
                not isinstance(node.func.value, ast.Name)):
            # shutil.rmtree(p) / os.remove(p) / bare rmtree(p) /
            # shutil.move(src, dst): the racing operand is the source.
            expr = path_expr(node.args[0])
            if expr is not None:
                yield expr, node, name
        elif isinstance(node.func, ast.Attribute) and name in _ACT_METHODS:
            expr = path_expr(node.func.value)
            if expr is not None:
                yield expr, node, f".{name}()"
        elif name == "open" and isinstance(node.func, ast.Name) and \
                len(node.args) >= 2 and \
                isinstance(node.args[1], ast.Constant) and \
                any(c in str(node.args[1].value) for c in "wx"):
            expr = path_expr(node.args[0])
            if expr is not None:
                yield expr, node, "open(.., 'w')"


@register
class CheckThenActToctou(Rule):
    id = "GL014"
    name = "check-then-act-toctou"
    summary = ("exists()/is_file() then remove/rmtree/rename/creating "
               "write on the same path expression, without O_EXCL or the "
               "pidlock seam in the flow")

    DIRS = frozenset({"scheduler", "utils", "studies", "loopback", "agent",
                      "mixtures", "scenarios", "data"})

    def check(self, module: Module, ctx: LintContext) -> Iterator:
        if not (self.DIRS & set(module.rel.split("/")[:-1])):
            return
        scopes = [module.tree] + [rec.node for rec in module.functions]
        for scope in scopes:
            checks = _checks(scope)
            if not checks:
                continue
            if _scope_has_lock(scope):
                continue
            for expr, call, verb in _acts(scope):
                check_line = checks.get(expr)
                if check_line is None or call.lineno <= check_line:
                    continue
                if _eafp_kwargs(call):
                    continue
                yield self.finding(
                    module, call.lineno,
                    f"{verb} on `{expr}` races the existence check at "
                    f"line {check_line} — another process can win the "
                    f"window; go EAFP (catch FileNotFoundError / "
                    f"missing_ok / utils.fsio.fresh_dir) or take the "
                    f"pidlock seam first",
                )
