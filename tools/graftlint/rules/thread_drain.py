"""GL017 drain contracts over daemon threads.

Two shapes of the same broken promise — "close() means the data is on
disk" — both shipped and both bitten (CHANGES.md PR 11, the graftroll
record-loss race):

- **timed join without a verdict**: a drain path (``close``/
  ``shutdown``/``stop``/``flush``/...) calls ``handle.join(timeout=..)``
  on a ``daemon=True`` thread and then proceeds as if the thread exited.
  A daemon thread survives the timeout silently — the interpreter will
  kill it mid-write at exit. After a timed join the drain MUST consult
  ``is_alive()`` and take the wedged branch (log, skip the seal, leave
  recovery to the next startup). A bare ``join()`` is a guaranteed
  drain and is never flagged.
- **socketserver daemon handlers**: ``server.daemon_threads = True``
  makes ``server_close()`` skip joining per-connection handler threads
  (stdlib semantics: only non-daemon handler threads are joined), so
  in-flight records die with the process. The pool sets ``False``
  (``scheduler/pool.py``) for exactly this reason.

The first shape uses graftflow end-to-end: daemon construction is found
by value flow (``Thread(..., daemon=True)`` assignments and
``handle.daemon = True`` writes), joins are matched to handles by
canonical path expression, and only supervisor-side drain-named
functions are in scope — worker fan-out helpers that poll with
``join(timeout)`` by design stay unflagged.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.engine import LintContext, Module, dotted_last, walk_own
from tools.graftlint.flow import path_expr
from tools.graftlint.rules import Rule, register

# Function names that promise a drain: after they return, the caller
# may assume buffered work is durable and the worker is gone.
_DRAIN_WORDS = ("close", "shutdown", "stop", "drain", "flush", "terminate")
_DRAIN_EXACT = frozenset({"__exit__", "__del__", "join", "join_all"})


def _is_drain_name(name: str) -> bool:
    low = name.lower()
    return name in _DRAIN_EXACT or any(w in low for w in _DRAIN_WORDS)


def _daemon_handles(module: Module) -> set:
    """Canonical path expressions of thread handles constructed (or
    later marked) ``daemon=True`` anywhere in the module."""
    handles: set = set()
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Assign):
            continue
        value, targets = node.value, node.targets
        if isinstance(value, ast.Call) and \
                dotted_last(value.func) == "Thread" and any(
                    kw.arg == "daemon" and
                    isinstance(kw.value, ast.Constant) and
                    kw.value.value is True
                    for kw in value.keywords):
            for t in targets:
                expr = path_expr(t)
                if expr is not None:
                    handles.add(expr)
        for t in targets:  # handle.daemon = True after construction
            if isinstance(t, ast.Attribute) and t.attr == "daemon" and \
                    isinstance(value, ast.Constant) and value.value is True:
                expr = path_expr(t.value)
                if expr is not None:
                    handles.add(expr)
    return handles


def _join_timeout(call: ast.Call) -> bool:
    if call.args:
        return not (isinstance(call.args[0], ast.Constant) and
                    call.args[0].value is None)
    return any(kw.arg == "timeout" and
               not (isinstance(kw.value, ast.Constant) and
                    kw.value.value is None)
               for kw in call.keywords)


@register
class DaemonDrainContract(Rule):
    id = "GL017"
    name = "daemon-drain-contract"
    summary = ("drain path joins a daemon thread with a timeout but never "
               "checks is_alive(); or socketserver daemon_threads=True "
               "voids server_close()'s join")

    DIRS = frozenset({"scheduler", "utils"})

    def check(self, module: Module, ctx: LintContext) -> Iterator:
        if not (self.DIRS & set(module.rel.split("/")[:-1])):
            return
        handles = _daemon_handles(module)
        for rec in module.functions:
            if not _is_drain_name(rec.name) or not handles:
                continue
            joined: list = []      # (expr, call) timed joins on daemons
            verdicts: set = set()  # exprs consulted via is_alive()
            for node in walk_own(rec.node):
                if not (isinstance(node, ast.Call) and
                        isinstance(node.func, ast.Attribute)):
                    continue
                recv = path_expr(node.func.value)
                if recv not in handles:
                    continue
                if node.func.attr == "join" and _join_timeout(node):
                    joined.append((recv, node))
                elif node.func.attr == "is_alive":
                    verdicts.add(recv)
            for expr, call in joined:
                if expr in verdicts:
                    continue
                yield self.finding(
                    module, call.lineno,
                    f"{rec.qualname} joins daemon thread `{expr}` with a "
                    f"timeout and never checks is_alive() — a wedged "
                    f"writer survives the join silently and dies "
                    f"mid-record at interpreter exit; branch on "
                    f"is_alive() and leave sealing to startup recovery",
                )
        # Shape (b): daemon_threads = True on a socketserver.
        for node in ast.walk(module.tree):
            flagged = None
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Constant) and \
                    node.value.value is True:
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            t.attr == "daemon_threads":
                        flagged = node
                    if isinstance(t, ast.Name) and \
                            t.id == "daemon_threads":
                        flagged = node  # class-body attribute
            if flagged is not None:
                yield self.finding(
                    module, flagged.lineno,
                    "daemon_threads = True makes server_close() skip "
                    "joining per-connection handler threads — in-flight "
                    "records are lost at shutdown (the graftroll race); "
                    "set False and let server_close() drain, as "
                    "scheduler/pool.py does",
                )
