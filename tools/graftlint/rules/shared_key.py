"""GL015 shared guard instance reached by multiple endpoint keys.

A ``CircuitBreaker`` aggregates failures for ONE dependency; share a
single instance across several endpoints and a flapping backend poisons
(or dilutes below threshold) every other backend's signal — the breaker
never opens under mixed traffic. This repo shipped that defect twice
(telemetry's per-cloud HTTP pushes, then the k8s client — CHANGES.md
PRs 8/10) and both fixes landed the same discipline: a dict of per-key
instances (``{cloud: CircuitBreaker(...) for cloud in clouds}``,
``scheduler/telemetry.py`` / ``scheduler/k8s_client.py``).

Detection is flow-shaped: a SINGLE construction of a guard type bound
to a plain name/attribute (dict-comprehension and per-key-subscript
constructions never register), whose methods are then invoked with ≥2
DISTINCT string key literals across the module — two different keys
funneled into one failure domain.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.engine import LintContext, Module, dotted_last
from tools.graftlint.flow import literal_strings, path_expr
from tools.graftlint.rules import Rule, register

# Guard types whose instances aggregate per-dependency state.
GUARD_TYPES = frozenset({"CircuitBreaker", "RetryPolicy", "RateLimiter",
                         "TokenBucket"})


def _constructions(module: Module) -> dict:
    """target path expression -> (guard type, line) for single-instance
    guard constructions (value is DIRECTLY the constructor call)."""
    out: dict = {}
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Assign):
            continue
        if not (isinstance(node.value, ast.Call) and
                dotted_last(node.value.func) in GUARD_TYPES):
            continue
        for target in node.targets:
            if isinstance(target, ast.Subscript):
                continue  # per-key: breakers[cloud] = CircuitBreaker()
            expr = path_expr(target)
            if expr is not None:
                out[expr] = (dotted_last(node.value.func), node.lineno)
    return out


@register
class SharedInstancePerKey(Rule):
    id = "GL015"
    name = "shared-guard-instance-per-key"
    summary = ("one CircuitBreaker/RetryPolicy instance invoked with >=2 "
               "distinct endpoint key literals — per-key instances "
               "required")

    DIRS = frozenset({"scheduler", "utils"})

    def check(self, module: Module, ctx: LintContext) -> Iterator:
        if not (self.DIRS & set(module.rel.split("/")[:-1])):
            return
        owners = _constructions(module)
        if not owners:
            return
        keys_seen: dict = {expr: set() for expr in owners}
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call) and
                    isinstance(node.func, ast.Attribute)):
                continue
            recv = path_expr(node.func.value)
            if recv not in keys_seen:
                continue
            for arg in list(node.args) + [k.value for k in node.keywords]:
                keys_seen[recv] |= literal_strings(arg)
        for expr, (guard, line) in sorted(owners.items(),
                                          key=lambda kv: kv[1][1]):
            keys = sorted(keys_seen[expr])
            if len(keys) < 2:
                continue
            shown = ", ".join(repr(k) for k in keys[:4])
            yield self.finding(
                module, line,
                f"one {guard} instance `{expr}` receives {len(keys)} "
                f"distinct key literals ({shown}) — its failure counts "
                f"mix endpoints and it will never open cleanly under "
                f"mixed traffic; construct per-key instances (dict keyed "
                f"by endpoint, as telemetry/k8s_client do)",
            )
