"""GL006 weak-type-leak: dtype-less float literals materialized in traced
code.

``jnp.array(0.5)`` / ``jnp.asarray(1e-6)`` / ``jnp.full(shape, 0.1)``
without an explicit ``dtype`` produce WEAK-typed arrays. Two silent
failure modes follow:

- **Cache-key churn**: weak and strong types are different jit cache
  entries, so the "same" function retraces when a weak constant meets a
  strong one — the runtime twin of this rule is the recompilation
  regression test (``tests/test_recompile.py``).
- **Promotion drift**: a weak f32 scalar flowing into bf16 math silently
  promotes the whole expression back to f32, undoing a deliberate
  ``compute_dtype=bfloat16`` choice (the torso-matmul knob in
  ``agent/ppo.py``) with no error anywhere — only a slower profile.

Bare Python literals in arithmetic (``x * 0.5``) are FINE — they stay
weak scalars and adopt the array operand's dtype; the leak is
materializing a literal as an ARRAY without saying which dtype.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.engine import LintContext, Module, dotted_name, walk_own
from tools.graftlint.rules import Rule, register

# jnp constructors whose literal-value argument takes the weak type.
# fn name -> index of the value argument to inspect.
_CONSTRUCTORS = {"array": 0, "asarray": 0, "full": 1, "full_like": 1}


def _float_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and isinstance(node.value, float):
        return True
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, (ast.USub, ast.UAdd)):
        return _float_literal(node.operand)
    return False


@register
class WeakTypeLeak(Rule):
    id = "GL006"
    name = "weak-type-leak"
    summary = ("dtype-less jnp.array/asarray/full of a float literal in "
               "traced code — weak type churns the jit cache key and "
               "promotes dtypes")

    def check(self, module: Module, ctx: LintContext) -> Iterator:
        for rec in module.traced_functions():
            for node in walk_own(rec.node):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if not name:
                    continue
                parts = name.split(".")
                if parts[0] not in ("jnp", "jax", "numpy", "np"):
                    continue
                fn = parts[-1]
                if fn not in _CONSTRUCTORS:
                    continue
                value_idx = _CONSTRUCTORS[fn]
                args = list(node.args)
                if len(args) <= value_idx or not _float_literal(args[value_idx]):
                    continue
                has_dtype = any(k.arg == "dtype" for k in node.keywords) or \
                    len(args) > value_idx + 1  # positional dtype
                if has_dtype:
                    continue
                yield self.finding(
                    module, node.lineno,
                    f"`{name}(...)` materializes a float literal with no "
                    f"dtype in traced `{rec.qualname}` — the weak-typed "
                    "array churns the jit cache key and can silently "
                    "promote bf16 math to f32; pass dtype= explicitly",
                )
