"""GL005 pallas-tile-misalignment: TPU tile shapes and the VMEM ceiling.

TPU vector memory is tiled (8, 128) for float32 (sublanes x lanes; wider
for narrower dtypes — (16, 128) bf16, (32, 128) int8). A Pallas BlockSpec
or in-kernel buffer whose trailing dims are not multiples of that tile is
silently padded UP to it: a (48, 100) f32 block occupies (48, 128) — 28%
of the tile rows moved and computed for nothing — and lane-dim padding
breaks the "whole block is MXU work" premise the fused kernels here are
built on. This repo has already measured the failure mode: the deleted
round-2 N=8 kernels underfilled 8x128 tiles and lost 3-5x to XLA
(docs/status.md row 4), which is why ``ops/pallas_set_block.py`` refuses
node counts below 32 that are not multiples of the 8-row sublane group —
this rule is the static, repo-wide form of that guard.

The rule also sums the statically-known per-block buffer footprints
(literal BlockSpec shapes + ``pltpu.VMEM`` scratch) per ``pallas_call``
against the ~16 MiB/core VMEM budget: a kernel that oversubscribes VMEM
fails at Mosaic compile time on the TPU driver, which the CPU container
(interpret mode) never sees — lint catches it before the chip does.

Only applies to files that import ``jax.experimental.pallas`` (i.e. files
that BUILD kernels — a test merely named ``test_pallas_*.py`` builds
observation arrays, not blocks); only literal integer shapes are judged —
symbolic shapes (``block_rows``, ``dim``) are the author's runtime
contract, not lint's.
"""

from __future__ import annotations

import ast
import math
from typing import Iterator

from tools.graftlint.engine import LintContext, Module, dotted_last
from tools.graftlint.rules import Rule, register

SUBLANE = 8     # float32 second-minor tile dim
LANE = 128      # minor tile dim
VMEM_BYTES = 16 * 1024 * 1024
# In-kernel / scratch allocations that live in VMEM per block. NOT
# ShapeDtypeStruct: out_shape is the LOGICAL array — its per-block VMEM
# residency is whatever the out_specs BlockSpec says.
_SHAPED_ALLOCS = frozenset({"zeros", "ones", "full", "empty", "VMEM"})


def _literal_shape(node: ast.AST) -> tuple | None:
    """``(48, 100)`` -> (48, 100); None unless every element is an int
    literal (symbolic shapes are out of scope)."""
    if isinstance(node, (ast.Tuple, ast.List)) and node.elts:
        dims = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int) \
                    and not isinstance(e.value, bool):
                dims.append(e.value)
            else:
                return None
        return tuple(dims)
    return None


def _misaligned(shape: tuple) -> str | None:
    """Why ``shape`` underfills the f32 (8, 128) tile, or None if aligned.

    A second-minor dim of exactly 1 is allowed (a single-row block is a
    legal degenerate layout); everything else must fill whole sublane
    groups and whole lanes.
    """
    if len(shape) == 0:
        return None
    lane = shape[-1]
    if lane % LANE:
        return (f"minor dim {lane} is not a multiple of {LANE} "
                f"(padded to {math.ceil(lane / LANE) * LANE} lanes)")
    if len(shape) >= 2:
        sub = shape[-2]
        if sub != 1 and sub % SUBLANE:
            return (f"second-minor dim {sub} is not a multiple of "
                    f"{SUBLANE} (padded to "
                    f"{math.ceil(sub / SUBLANE) * SUBLANE} sublane rows)")
    return None


@register
class PallasTileMisalignment(Rule):
    id = "GL005"
    name = "pallas-tile-misalignment"
    summary = ("BlockSpec/buffer shape not a multiple of the (8, 128) f32 "
               "TPU tile, or static VMEM footprint over the 16 MiB budget")

    def applies(self, module: Module) -> bool:
        # `from jax.experimental import pallas [as pl]` /
        # `from jax.experimental.pallas import tpu as pltpu` /
        # `import jax.experimental.pallas` — NOT repo modules whose own
        # path merely contains "pallas".
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                if node.module.startswith("jax.experimental.pallas"):
                    return True
                if node.module == "jax.experimental" and any(
                    alias.name == "pallas" for alias in node.names
                ):
                    return True
            elif isinstance(node, ast.Import):
                if any(alias.name.startswith("jax.experimental.pallas")
                       for alias in node.names):
                    return True
        return False

    def check(self, module: Module, ctx: LintContext) -> Iterator:
        if not self.applies(module):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_last(node.func)
            if callee == "BlockSpec":
                shape_node = node.args[0] if node.args else None
                for kw in node.keywords:
                    if kw.arg == "block_shape":
                        shape_node = kw.value
                shape = _literal_shape(shape_node) if shape_node else None
                if shape:
                    why = _misaligned(shape)
                    if why:
                        yield self.finding(
                            module, node.lineno,
                            f"BlockSpec {shape}: {why} — pad the block "
                            "shape (or restructure) to fill whole (8, 128) "
                            "f32 tiles",
                        )
            elif callee in _SHAPED_ALLOCS and node.args:
                shape = _literal_shape(node.args[0])
                if shape and len(shape) >= 2:
                    why = _misaligned(shape)
                    if why:
                        yield self.finding(
                            module, node.lineno,
                            f"`{callee}` buffer {shape}: {why}",
                        )
            elif callee == "pallas_call":
                yield from self._vmem_budget(module, node)

    def _vmem_budget(self, module: Module, call: ast.Call) -> Iterator:
        """Sum literal f32 block footprints inside one pallas_call."""
        total = 0
        shapes = []
        for node in ast.walk(call):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_last(node.func)
            shape_node = None
            if callee == "BlockSpec":
                shape_node = node.args[0] if node.args else None
                for kw in node.keywords:
                    if kw.arg == "block_shape":
                        shape_node = kw.value
            elif callee == "VMEM" and node.args:
                shape_node = node.args[0]
            shape = _literal_shape(shape_node) if shape_node is not None else None
            if shape:
                padded = list(shape)
                if padded:
                    padded[-1] = math.ceil(padded[-1] / LANE) * LANE
                if len(padded) >= 2:
                    padded[-2] = math.ceil(padded[-2] / SUBLANE) * SUBLANE
                total += 4 * math.prod(padded)  # f32 lower bound
                shapes.append(shape)
        if total > VMEM_BYTES:
            yield self.finding(
                module, call.lineno,
                f"pallas_call static VMEM footprint ~{total / 2**20:.1f} "
                f"MiB from literal block shapes {shapes} exceeds the "
                f"~16 MiB/core budget — shrink the block or re-tile",
            )
