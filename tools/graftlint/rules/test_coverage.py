"""GL007 untested-public-op: public kernels/collectives nobody tests.

``ops/`` and ``parallel/`` hold the code with the widest
container-vs-driver behavior gap: Pallas kernels run in interpret mode on
CPU but compile through Mosaic on the TPU driver, and collectives change
behavior across the JAX version split (``parallel/mesh.py``'s shims exist
for exactly that). A public function there with NO reference anywhere in
``tests/`` has zero parity coverage on either side — historically how
"correct" kernels shipped with 10x roofline gaps (docs/roofline.md).

``scenarios/`` joined the covered set with the graftscenario subsystem:
its generators compile seeded tables whose determinism/vmap-parity
contract is exactly the kind of cross-environment invariant that only a
test reference proves, and its env variant has the same CPU-vs-TPU
surface as everything in ``ops/``.

``studies/`` joined with graftstudy: its public surface IS a
reproducibility contract (frozen specs, deterministic trial lists,
bitwise-resumable ledgers, statistical verdicts) — an untested public
study op is an unverified claim about what the chip harvest will do.

The check is a name-reference scan of the configured test corpus, not a
coverage run: pure-AST/text, so it is identical on both JAX versions and
costs milliseconds. Underscore-prefixed functions, dunders, and
re-exports referenced via ``__all__`` conventions are out of scope —
public API only.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from tools.graftlint.engine import LintContext, Module
from tools.graftlint.rules import Rule, register

# Path segments whose public functions must be referenced from tests.
# `scheduler` joined with graftroll: the serving plane's public surface
# is now a zero-downtime contract (trace durability, rolling promotion,
# rollback gates) — an untested public op there is an unverified claim
# about what a live pool does under a promote. `loopback` joined with
# graftloop: its surface is the continual-learning contract (bitwise
# trace compiles, graded promotion verdicts, SIGKILL-safe resume) — the
# same class of claim. `mixtures` joined with graftmix: bitwise trace
# imports, seeded family draws inside vmap, and statistical transfer
# verdicts are exactly the cross-environment determinism contracts this
# rule exists to keep referenced. graftfleet rides the existing
# `scheduler` entry: scheduler/fleet.py's publics (cross-pool promote,
# ledger resume, fleet merges) are the fleet-level zero-downtime
# contract and must stay referenced the same way. `driftview` joined
# with graftdrift: its publics are the retrain-trigger gate (drifting
# verdicts, reference-fingerprint cross-checks, the shadow floor) — an
# untested gate is an unverified claim about when the loop retrains.
OP_DIRS = frozenset({"ops", "parallel", "scenarios", "studies",
                     "scheduler", "loopback", "mixtures", "driftview"})


@register
class UntestedPublicOp(Rule):
    id = "GL007"
    name = "untested-public-op"
    summary = ("public function in ops/ or parallel/ with no reference "
               "anywhere in the test corpus")

    def applies(self, module: Module) -> bool:
        parts = set(module.rel.split("/")[:-1])
        return bool(parts & OP_DIRS)

    def check(self, module: Module, ctx: LintContext) -> Iterator:
        if not self.applies(module):
            return
        corpus = ctx.test_corpus()
        for node in module.tree.body:
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                continue
            name = node.name
            if name.startswith("_"):
                continue
            if re.search(rf"\b{re.escape(name)}\b", corpus):
                continue
            kind = "class" if isinstance(node, ast.ClassDef) else "function"
            yield self.finding(
                module, node.lineno,
                f"public {kind} `{name}` has no reference in the test "
                "corpus — ops/parallel/scenarios/studies code is where "
                "CPU-vs-TPU behavior and seeded-determinism contracts "
                "diverge; add at least a parity, shape, or determinism "
                "test",
            )
