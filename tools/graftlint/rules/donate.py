"""GL004 missing-donate: train-step-shaped jits without buffer donation.

A jitted function that takes a runner/params pytree and returns an UPDATED
version of it holds both the old and new buffers live across the call
unless the input is donated — for this repo's fleet configs that is the
whole optimizer + env state doubled in HBM every iteration, plus an extra
device copy XLA could have elided. ``agent/loop.py::make_update`` jits
every trainer with ``donate_argnums=0`` for exactly this reason; this rule
keeps ad-hoc jit sites honest.

"Train-step-shaped" is structural, not name-based: the jitted function
returns (possibly inside a tuple) either a rebound parameter, a
``._replace(...)``/``dataclasses.replace(...)`` of a parameter-derived
value, an ``optax.apply_updates`` result, or a constructor call of the
same class a parameter is annotated with. Pure producers (init functions
keyed by a PRNG key, evaluators returning fresh metrics) do not match.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.engine import (
    LintContext,
    Module,
    dotted_last,
    param_names,
    taint_set,
)
from tools.graftlint.rules import Rule, register

_DONATE_KWARGS = {"donate_argnums", "donate_argnames"}


def _jit_sites(module: Module):
    """Yield ``(line, fn_name, has_donate)`` for every resolvable
    ``jax.jit`` application (call form, decorator, or partial)."""
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call) and dotted_last(node.func) == "jit":
            kwargs = {k.arg for k in node.keywords}
            if node.args and isinstance(node.args[0], ast.Name):
                yield (node.lineno, node.args[0].id,
                       bool(kwargs & _DONATE_KWARGS))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if dotted_last(dec) == "jit":
                    yield (dec.lineno, node.name, False)
                elif isinstance(dec, ast.Call):
                    kwargs = {k.arg for k in dec.keywords}
                    if dotted_last(dec.func) == "jit":
                        yield (dec.lineno, node.name,
                               bool(kwargs & _DONATE_KWARGS))
                    elif (dotted_last(dec.func) == "partial" and dec.args
                          and dotted_last(dec.args[0]) == "jit"):
                        yield (dec.lineno, node.name,
                               bool(kwargs & _DONATE_KWARGS))


def _annotation_classes(fn_node) -> set:
    out = set()
    args = fn_node.args
    for a in args.posonlyargs + args.args + args.kwonlyargs:
        if a.annotation is not None:
            last = dotted_last(a.annotation)
            if last:
                out.add(last)
    return out


def _returned_exprs(fn_node):
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Return) and node.value is not None:
            val = node.value
            if isinstance(val, ast.Tuple):
                yield from val.elts
            else:
                yield val


def _updates_argument(fn_node) -> bool:
    """Does this function return an updated version of an argument?"""
    params = param_names(fn_node)
    if not params:
        return False
    tainted = taint_set(fn_node)
    ann_classes = _annotation_classes(fn_node)

    # name -> last assignment RHS, for one-hop resolution of returned names
    last_rhs: dict = {}
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    last_rhs[t.id] = node.value
                elif isinstance(t, (ast.Tuple, ast.List)):
                    for e in t.elts:
                        if isinstance(e, ast.Name):
                            last_rhs[e.id] = node.value
        elif isinstance(node, ast.AugAssign) and isinstance(node.target, ast.Name):
            last_rhs[node.target.id] = node.value

    def expr_updates(expr) -> bool:
        if isinstance(expr, ast.Call):
            callee_last = dotted_last(expr.func)
            arg_names = {
                a.id for a in list(expr.args)
                + [k.value for k in expr.keywords]
                if isinstance(a, ast.Name)
            }
            # runner._replace(...) / dataclasses.replace(runner, ...)
            if callee_last in ("_replace", "replace"):
                base = (expr.func.value if isinstance(expr.func, ast.Attribute)
                        else expr.args[0] if expr.args else None)
                if isinstance(base, ast.Name) and base.id in tainted:
                    return True
            # optax.apply_updates(params, updates)
            if callee_last == "apply_updates" and (arg_names & tainted):
                return True
            # RunnerState(...) where a param is annotated `: RunnerState`
            if callee_last in ann_classes and (arg_names & tainted):
                return True
        return False

    for expr in _returned_exprs(fn_node):
        if expr_updates(expr):
            return True
        if isinstance(expr, ast.Name):
            # One-hop resolution: `params = optax.apply_updates(...);
            # return params` / `runner = runner._replace(...); return
            # runner`. A returned name whose last assignment is NOT
            # update-shaped (plain arithmetic rebinding) deliberately does
            # not match — flagging every `x = x * s; return x` would be
            # noise, not discipline.
            rhs = last_rhs.get(expr.id)
            if rhs is not None and expr_updates(rhs):
                return True
    return False


@register
class MissingDonate(Rule):
    id = "GL004"
    name = "missing-donate"
    summary = ("jitted train-step-shaped function returns an updated "
               "argument without donate_argnums")

    def check(self, module: Module, ctx: LintContext) -> Iterator:
        flagged = set()
        for line, fn_name, has_donate in _jit_sites(module):
            if has_donate:
                continue
            for rec in module.records_named(fn_name):
                if (fn_name, line) in flagged:
                    continue
                if _updates_argument(rec.node):
                    flagged.add((fn_name, line))
                    yield self.finding(
                        module, line,
                        f"`{fn_name}` is jitted without donate_argnums but "
                        "returns an updated version of an argument — the "
                        "old and new pytrees stay live simultaneously "
                        "(double HBM) and XLA cannot reuse the buffers",
                    )
