"""GL002 prng-key-reuse: one key, two draws.

JAX PRNG keys are values, not stateful generators: feeding the same key to
two sampling calls yields CORRELATED (often identical) randomness — the
classic silent RL bug where exploration noise repeats or env resets
duplicate across a batch, degrading training with no error anywhere. The
42+ ``jax.random.*`` sites across this repo were audited by hand until
this rule; now the discipline (``split``/``fold_in`` before every
consumption) is machine-checked.

Two patterns are flagged:

- **Linear reuse**: the same key variable consumed by two sampler calls
  with no intervening ``split``/``fold_in``/reassignment.
- **Loop-carried reuse**: a key consumed inside a ``for``/``while`` body
  that never reassigns it — every iteration draws with the same key.

``split`` and ``fold_in`` are derivations, not consumptions: deriving
twice from one key (``fold_in(key, i)`` per step) is the intended idiom.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.engine import LintContext, Module, dotted_name
from tools.graftlint.rules import Rule, register

# jax.random.* callees that CONSUME entropy. Everything else on the module
# (split, fold_in, PRNGKey, key, wrap_key_data, key_data, clone, ...)
# derives or constructs.
_NON_CONSUMING = frozenset({
    "split", "fold_in", "PRNGKey", "key", "wrap_key_data", "key_data",
    "clone", "key_impl",
})

_KEY_SOURCES = frozenset({"PRNGKey", "split", "fold_in", "key"})


def _random_callee(node: ast.Call) -> str | None:
    """``jax.random.categorical(...)`` -> ``categorical``; None if the call
    is not on a ``random`` module path."""
    name = dotted_name(node.func)
    if not name:
        return None
    parts = name.split(".")
    if len(parts) >= 2 and parts[-2] == "random":
        return parts[-1]
    return None


@register
class PRNGKeyReuse(Rule):
    id = "GL002"
    name = "prng-key-reuse"
    summary = ("the same PRNG key consumed by two sampling calls without "
               "an intervening split/fold_in")

    def check(self, module: Module, ctx: LintContext) -> Iterator:
        for rec in module.functions:
            yield from self._check_function(module, rec)

    # ------------------------------------------------------------------

    def _key_names(self, fn_node) -> set:
        """Names that hold PRNG keys: assigned from PRNGKey/split/fold_in
        (incl. tuple-unpacked split results) or key-ish parameters."""
        names = set()
        args = fn_node.args
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            low = a.arg.lower()
            if low == "rng" or low.endswith("key") or low.startswith("rng"):
                names.add(a.arg)
        for node in ast.walk(fn_node):
            if isinstance(node, ast.Assign):
                callee = (
                    _random_callee(node.value)
                    if isinstance(node.value, ast.Call) else None
                )
                if callee in _KEY_SOURCES:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            names.add(t.id)
                        elif isinstance(t, (ast.Tuple, ast.List)):
                            names.update(
                                e.id for e in t.elts if isinstance(e, ast.Name)
                            )
        return names

    def _check_function(self, module: Module, rec) -> Iterator:
        fn = rec.node
        keys = self._key_names(fn)
        if not keys:
            return

        # consumed[name] = line of the consuming sampler call since the
        # last (re)assignment of `name`.
        consumed: dict = {}

        def assigned_names(stmt) -> set:
            out = set()

            def collect(t):
                if isinstance(t, ast.Name):
                    out.add(t.id)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    for e in t.elts:
                        collect(e)

            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    collect(t)
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                collect(stmt.target)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                collect(stmt.target)
            return out

        def key_args(call: ast.Call) -> set:
            used = set()
            for a in list(call.args) + [k.value for k in call.keywords]:
                if isinstance(a, ast.Name) and a.id in keys:
                    used.add(a.id)
            return used

        def scan_expr(expr, findings):
            """Consumption events in one expression, inner-first."""
            for node in ast.walk(expr):
                if not isinstance(node, ast.Call):
                    continue
                callee = _random_callee(node)
                if callee is None or callee in _NON_CONSUMING:
                    continue
                for name in sorted(key_args(node)):
                    if name in consumed:
                        findings.append((node.lineno, name, consumed[name]))
                    consumed[name] = node.lineno

        def walk_block(stmts, loop_depth, loop_assigned):
            findings: list = []
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                # Consumptions in this statement's expressions.
                for field, value in ast.iter_fields(stmt):
                    if field in ("body", "orelse", "finalbody", "handlers"):
                        continue
                    if isinstance(value, ast.AST):
                        scan_expr(value, findings)
                    elif isinstance(value, list):
                        for item in value:
                            if isinstance(item, ast.AST):
                                scan_expr(item, findings)
                # Then assignments clear the consumed state.
                for name in assigned_names(stmt):
                    consumed.pop(name, None)
                    if loop_depth:
                        loop_assigned.add(name)
                # Recurse into compound bodies.
                if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                    inner_assigned: set = set()
                    inner = walk_block(
                        stmt.body, loop_depth + 1, inner_assigned
                    )
                    findings.extend(inner)
                    # Loop-carried reuse: consumed in the body, never
                    # reassigned in the body -> same key every iteration.
                    for name, line in sorted(consumed.items()):
                        body_lines = range(stmt.body[0].lineno,
                                           (stmt.end_lineno or line) + 1)
                        if name not in inner_assigned and line in body_lines:
                            findings.append((line, name, "loop"))
                            consumed.pop(name, None)
                    findings.extend(
                        walk_block(stmt.orelse, loop_depth, loop_assigned)
                    )
                elif isinstance(stmt, ast.If) or (
                    hasattr(ast, "Match") and isinstance(stmt, ast.Match)
                ):
                    # if/else arms (and match cases) are mutually
                    # exclusive: each starts from the pre-branch state;
                    # afterwards a key counts as consumed if ANY arm
                    # consumed it (conservative for what follows, no
                    # false reuse across arms).
                    arms = (
                        [stmt.body, stmt.orelse] if isinstance(stmt, ast.If)
                        else [case.body for case in stmt.cases]
                    )
                    before = dict(consumed)
                    merged = dict(consumed)
                    for arm in arms:
                        consumed.clear()
                        consumed.update(before)
                        findings.extend(
                            walk_block(arm, loop_depth, loop_assigned)
                        )
                        merged.update(consumed)
                    consumed.clear()
                    consumed.update(merged)
                else:
                    for field in ("body", "orelse", "finalbody"):
                        findings.extend(walk_block(
                            getattr(stmt, field, []) or [],
                            loop_depth, loop_assigned,
                        ))
                    for handler in getattr(stmt, "handlers", []) or []:
                        findings.extend(
                            walk_block(handler.body, loop_depth, loop_assigned)
                        )
            return findings

        for lineno, name, prior in walk_block(fn.body, 0, set()):
            if prior == "loop":
                msg = (
                    f"key `{name}` consumed inside a loop in "
                    f"`{rec.qualname}` without reassignment — every "
                    "iteration draws with the SAME key (split or fold_in "
                    "per iteration)"
                )
            else:
                msg = (
                    f"key `{name}` already consumed on line {prior} of "
                    f"`{rec.qualname}` — two draws from one key are "
                    "correlated; split/fold_in first"
                )
            yield self.finding(module, lineno, msg)
