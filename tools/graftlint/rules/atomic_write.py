"""GL013 atomic-write discipline for durable JSON artifacts.

A ``.json`` artifact another process reads (ledgers, manifests,
verdicts, caches) must never be observable half-written: the
threshold-cache race (CHANGES.md PR 9) persisted torn JSON exactly
because a reader overlapped a plain ``open(...,'w')`` + dump, and the
fix — ``atomic_write_json`` (now ``utils/fsio.py``): write a
per-writer-unique ``.{name}.{pid}.tmp`` sibling, then ``os.replace`` —
has been the repo-wide discipline since. This rule makes the discipline
checkable: in the production dirs, a ``.write_text(...)`` or
``json.dump`` landing on a path whose name lattice says ``*.json`` is
flagged UNLESS the flow shows the idiom (a ``tmp`` marker in the name,
or the written path feeding a later ``os.replace``/``os.rename`` in the
same scope).

``.jsonl`` append streams are exempt by construction (their names do
not END in ``.json``): line-framed logs have their own torn-tail
recovery discipline (graftroll's ``_recover``), not tmp-then-rename.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.engine import (LintContext, Module, dotted_last,
                                    dotted_name)
from tools.graftlint.flow import (DefUse, literal_strings, module_contexts,
                                  path_expr, scope_walk)
from tools.graftlint.rules import Rule, register

_RENAMES = frozenset({"replace", "rename", "renames", "move", "link"})


def _scopes(module: Module):
    """(display-name, scope-node, context-tags) for module + functions."""
    contexts = module_contexts(module)
    yield "<module>", module.tree, frozenset({"main"})
    for rec in module.functions:
        yield rec.qualname, rec.node, contexts[rec.qualname]


def _renamed_exprs(scope) -> set:
    """Path expressions fed as the SOURCE of a rename/replace/move in
    this scope — the tmp half of a write-then-rename, even unnamed."""
    out = set()
    for node in scope_walk(scope):
        if isinstance(node, ast.Call) and node.args and \
                dotted_last(node.func) in _RENAMES:
            expr = path_expr(node.args[0])
            if expr:
                out.add(expr)
            # tmp.rename(dst) / tmp.replace(dst): receiver is the source
            if isinstance(node.func, ast.Attribute):
                recv = path_expr(node.func.value)
                if recv:
                    out.add(recv)
    return out


def _opened_path(handle_value: ast.AST) -> tuple:
    """(path-node, mode) for an ``open(p, m)`` / ``p.open(m)`` value."""
    if not isinstance(handle_value, ast.Call):
        return None, ""
    call = handle_value
    mode = "r"
    for i, arg in enumerate(call.args):
        if i == 1 and isinstance(arg, ast.Constant) and \
                isinstance(arg.value, str):
            mode = arg.value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = str(kw.value.value)
    name = dotted_last(call.func)
    if name == "open":
        if isinstance(call.func, ast.Attribute):  # p.open(mode)
            if call.args and isinstance(call.args[0], ast.Constant) and \
                    isinstance(call.args[0].value, str):
                mode = call.args[0].value
            return call.func.value, mode
        if call.args:  # open(p, mode)
            return call.args[0], mode
    return None, ""


@register
class AtomicWriteDiscipline(Rule):
    id = "GL013"
    name = "non-atomic-json-artifact-write"
    summary = ("durable .json artifact written with open('w')/write_text "
               "instead of atomic_write_json / tmp-then-rename")

    # Every production dir that persists JSON artifacts other code reads.
    DIRS = frozenset({"scheduler", "utils", "studies", "loopback", "agent",
                      "mixtures", "scenarios", "data"})

    def check(self, module: Module, ctx: LintContext) -> Iterator:
        if not (self.DIRS & set(module.rel.split("/")[:-1])):
            return
        for qualname, scope, tags in _scopes(module):
            defuse = DefUse(scope)
            renamed = _renamed_exprs(scope)
            for node in scope_walk(scope):
                if not isinstance(node, ast.Call):
                    continue
                path_node = verb = None
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "write_text":
                    path_node, verb = node.func.value, "write_text"
                elif dotted_name(node.func) == "dump" or \
                        dotted_name(node.func) == "json.dump":
                    if len(node.args) >= 2:
                        handle = node.args[1]
                        if isinstance(handle, ast.Name):
                            handle = defuse.value_at(
                                handle.id, node.lineno) or handle
                        path_node, mode = _opened_path(handle)
                        verb = "json.dump"
                        if path_node is None or not any(
                                c in mode for c in "wx"):
                            continue
                if path_node is None:
                    continue
                names = literal_strings(path_node, defuse, node.lineno)
                if not any(s.endswith(".json") for s in names):
                    continue
                if any("tmp" in s for s in names):
                    continue  # the tmp half of the write-then-rename idiom
                expr = path_expr(path_node)
                if expr is not None and expr in renamed:
                    continue  # unnamed tmp: written then renamed in-scope
                where = ""
                racy = tags & {"handler", "thread", "forked-worker"}
                if racy:
                    where = (f" (and {qualname} runs in a "
                             f"{sorted(racy)[0]} context — concurrent "
                             f"writers make the torn window real)")
                yield self.finding(
                    module, node.lineno,
                    f"{verb} lands a .json artifact non-atomically — a "
                    f"reader can observe the torn file; route it through "
                    f"utils.fsio.atomic_write_json (per-writer .pid.tmp "
                    f"sibling + os.replace){where}",
                )
