"""GL009 metrics-loop-host-fetch: per-iteration syncs in logging loops.

The pattern graftscope (``utils/metrics.py``) exists to kill: a host-side,
step-indexed training/driver loop that fetches device values every
iteration — ``jax.device_get``, ``float()``/``int()``/``bool()`` on an
update result, ``.item()`` — and hands them to a logging sink. Each fetch
serializes the async dispatch pipeline once PER ITERATION (~100 ms per
round-trip on this repo's tunneled TPU, ``agent/loop.py``), so a 1000-step
run spends minutes waiting on metrics nobody reads mid-run. The discipline:
accumulate device-side (``MetricsState`` / a pending list) and flush ONE
batched ``jax.device_get`` per logging window.

Scope and exemptions (the fixture pair pins these):

- Only loops of the shape ``for i in range(...)`` (step-indexed) whose body
  also calls a logging sink (callee name containing ``log`` or ``print``)
  are checked — a fetch-synced *measurement* loop (``bench.py``) is the
  measurement, not a logging loop, and stays GL001/GL008 jurisdiction.
- Window-gated fetches are the GOOD pattern, not a finding: statements
  under an ``if`` whose test involves ``%`` or a ``*window*``/``*every*``/
  ``*sync*`` name are exempt (``if (i + 1) % window == 0: flush()``).
- ``float()``-family findings require the converted value to derive from a
  call result in the enclosing scope (the ``runner, metrics = update(...)``
  shape); converting an already-fetched ``jax.device_get`` result is free
  and never flagged.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.engine import (
    LintContext,
    Module,
    dotted_last,
    dotted_name,
    iter_own_statements,
    tracer_valued_names,
)
from tools.graftlint.rules import Rule, register

_CONVERTERS = ("float", "int", "bool")
# Call results that are host values (or host bookkeeping) by construction:
# assigning from these does NOT mark the target as possibly-device.
_HOST_RESULT_CALLS = ("device_get", "perf_counter", "monotonic", "len",
                      "range", "enumerate", "sorted", "open",
                      "float", "int", "bool", "str")
_GATE_NAME_MARKERS = ("window", "every", "sync")


def _target_names(target: ast.AST) -> set:
    out: set = set()
    if isinstance(target, ast.Name):
        out.add(target.id)
    elif isinstance(target, (ast.Tuple, ast.List)):
        for e in target.elts:
            out |= _target_names(e)
    elif isinstance(target, ast.Starred):
        out |= _target_names(target.value)
    return out


def _names_in(node: ast.AST) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _is_window_gate(stmt: ast.AST) -> bool:
    """``if`` statements that look like a logging-window boundary."""
    if not isinstance(stmt, ast.If):
        return False
    for n in ast.walk(stmt.test):
        if isinstance(n, ast.Mod):
            return True
        if isinstance(n, ast.Name) and any(
                m in n.id.lower() for m in _GATE_NAME_MARKERS):
            return True
    return False


def _walk_ungated(node: ast.AST) -> Iterator[ast.AST]:
    """All nodes under ``node`` minus nested defs and window-gated ifs."""
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef, ast.Lambda)):
        return
    if _is_window_gate(node):
        return
    yield node
    for child in ast.iter_child_nodes(node):
        yield from _walk_ungated(child)


def _scope_call_taint(scope: ast.AST) -> set:
    """Names in ``scope`` bound from call results — the static proxy for
    'possibly still a device value'. Two line-ordered passes, same
    convergence argument as ``engine.taint_set``; comprehension and
    for-loop targets iterating a tainted value propagate. Rebinding from a
    host-result call UN-taints (``obs = jax.device_get(obs)`` makes every
    later ``float(obs[...])`` free), so the final set reflects the last
    binding in program order."""
    tainted: set = set()
    for _ in range(2):
        for stmt in iter_own_statements(scope):
            if isinstance(stmt, ast.Assign):
                src = stmt.value
                if isinstance(src, ast.Call):
                    callee = (dotted_last(src.func) or "").lower()
                    host = callee in _HOST_RESULT_CALLS or "parse" in callee
                    for t in stmt.targets:
                        if host:
                            tainted -= _target_names(t)
                        else:
                            tainted |= _target_names(t)
                elif _names_in(src) & tainted:
                    for t in stmt.targets:
                        tainted |= _target_names(t)
                else:
                    for t in stmt.targets:
                        tainted -= _target_names(t)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                if _names_in(stmt.iter) & tainted:
                    tainted |= _target_names(stmt.target)
            for node in ast.walk(stmt):
                if isinstance(node, (ast.ListComp, ast.SetComp,
                                     ast.DictComp, ast.GeneratorExp)):
                    for gen in node.generators:
                        if _names_in(gen.iter) & tainted:
                            tainted |= _target_names(gen.target)
    return tainted


def _has_log_call(loop: ast.For) -> bool:
    for node in ast.walk(loop):
        if isinstance(node, ast.Call):
            name = (dotted_name(node.func) or dotted_last(node.func)
                    or "").lower()
            if "log" in name or "print" in name:
                return True
    return False


@register
class MetricsLoopHostFetch(Rule):
    id = "GL009"
    name = "metrics-loop-host-fetch"
    summary = ("per-iteration host fetch (device_get/float()/.item()) in a "
               "step-indexed logging loop — accumulate on device "
               "(utils/metrics.MetricsState) and flush once per window")

    def check(self, module: Module, ctx: LintContext) -> Iterator:
        scopes = [module.tree] + [
            rec.node for rec in module.functions if not rec.traced
        ]
        for scope in scopes:
            tainted = None  # computed lazily, once per scope
            seen: set = set()
            for stmt in iter_own_statements(scope):
                if not (isinstance(stmt, ast.For)
                        and isinstance(stmt.iter, ast.Call)
                        and dotted_last(stmt.iter.func) == "range"):
                    continue
                if not _has_log_call(stmt):
                    continue
                if tainted is None:
                    tainted = _scope_call_taint(scope)
                for body_stmt in stmt.body + stmt.orelse:
                    for node in _walk_ungated(body_stmt):
                        yield from self._check_node(
                            module, node, tainted, seen)

    def _check_node(self, module, node, tainted, seen):
        if not isinstance(node, ast.Call) or node.lineno in seen:
            return
        if dotted_last(node.func) == "device_get":
            seen.add(node.lineno)
            yield self.finding(
                module, node.lineno,
                "`jax.device_get` every iteration of a logging loop — one "
                "device round-trip per step; accumulate on device and "
                "flush one batched fetch per window",
            )
        elif (isinstance(node.func, ast.Attribute)
                and node.func.attr == "item" and not node.args
                and tracer_valued_names(node.func.value, tainted)):
            seen.add(node.lineno)
            yield self.finding(
                module, node.lineno,
                "`.item()` on an update result every iteration of a "
                "logging loop forces a per-step sync — batch the window's "
                "metrics into one fetch",
            )
        elif (isinstance(node.func, ast.Name)
                and node.func.id in _CONVERTERS and node.args
                and tracer_valued_names(node.args[0], tainted)):
            seen.add(node.lineno)
            yield self.finding(
                module, node.lineno,
                f"`{node.func.id}()` on an update result every iteration "
                "of a logging loop forces a per-step sync — batch the "
                "window's metrics into one fetch (or carry a MetricsState)",
            )
