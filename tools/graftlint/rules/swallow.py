"""GL010 silent-exception-swallow in host-I/O paths.

The failure-domain layer (graftguard, docs/robustness.md) only works if
failures are OBSERVABLE: the reference's ``except: pass`` around its kube
context lookup hid a naming bug for the repo's whole life (SURVEY.md —
``kind-aws`` vs ``kind-kind-aws``), and a fallback that engages silently
is indistinguishable from a healthy primary. In ``scheduler/`` and
``utils/`` — the directories that own every host-I/O boundary
(checkpoints, HTTP telemetry, kube API, dump files) — a handler that
catches broadly (bare ``except``, ``except Exception``/``BaseException``)
must either log what it swallowed or re-raise. Narrow handlers
(``except ValueError``) stay unflagged: catching a SPECIFIC expected
error silently is a deliberate parse-style pattern, not a black hole.

"Logs" means: a call to a ``logging`` method (``logger.debug`` ...
``.exception``), ``warnings.warn``, or ``print``; raising anything
(including a translated exception) also satisfies the rule. Handlers
inside nested function definitions are checked as part of this same walk
(exception handling does not change jurisdiction with nesting).
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.engine import LintContext, Module, dotted_last
from tools.graftlint.rules import Rule, register

# Broad exception type names: catching these without observation swallows
# failures the author did not enumerate.
_BROAD = frozenset({"Exception", "BaseException"})

# Call names that make a swallow observable.
_LOG_METHODS = frozenset({
    "debug", "info", "warning", "warn", "error", "exception", "critical",
    "log",
})
_LOG_CALLS = frozenset({"print"})


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare `except:`
        return True
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    return any(dotted_last(x) in _BROAD for x in types)


def _observes(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = dotted_last(node.func)
            if name in _LOG_CALLS:
                return True
            if isinstance(node.func, ast.Attribute) and name in _LOG_METHODS:
                # The receiver must look like a logger: without this,
                # math.log(x) or a metrics object's .error() would
                # satisfy the rule while observing nothing. Covers
                # logger/log/_log/self._logger/logging.getLogger(...)
                # chains and warnings.warn.
                value = node.func.value
                if isinstance(value, ast.Call):
                    value = value.func  # chained: logging.getLogger(...)
                recv = (dotted_last(value) or "").lower()
                if "log" in recv or recv == "warnings":
                    return True
    return False


@register
class SilentExceptionSwallow(Rule):
    id = "GL010"
    name = "silent-exception-swallow"
    summary = ("broad except (bare/Exception/BaseException) in a "
               "scheduler//utils/ host-I/O path that neither logs nor "
               "re-raises")

    # Directories owning the host-I/O boundaries this rule polices.
    DIRS = frozenset({"scheduler", "utils"})

    def check(self, module: Module, ctx: LintContext) -> Iterator:
        # Same jurisdiction convention as GL007: match on the module's
        # parent directory names (fixtures live under a matching subdir).
        if not (self.DIRS & set(module.rel.split("/")[:-1])):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node) or _observes(node):
                continue
            shape = ("bare `except:`" if node.type is None
                     else "broad `except Exception`")
            yield self.finding(
                module, node.lineno,
                f"{shape} swallows the failure silently — log what was "
                "caught (logger.*/warnings.warn) or re-raise; an invisible "
                "fallback is indistinguishable from a healthy primary",
            )
