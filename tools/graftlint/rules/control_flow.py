"""GL003 tracer-control-flow: Python ``if``/``while`` on tracer values.

Inside a traced function, a Python ``if``/``while`` on a value derived
from the function's (tracer) arguments either raises
ConcretizationTypeError or — worse, with ``static_argnums`` or a stray
host sync — silently BAKES one branch into the compiled program and
retraces per value. Shape-driven branching stays legal: ``x.ndim``,
``x.shape[0]``, ``isinstance(x, tuple)``, ``x is None`` are static under
trace and are excluded by the engine's tracer-value analysis. The fix is
``jnp.where`` / ``lax.cond`` / ``lax.while_loop``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.engine import (
    LintContext,
    Module,
    iter_own_statements,
    tracer_valued_names,
)
from tools.graftlint.rules import Rule, register


@register
class TracerControlFlow(Rule):
    id = "GL003"
    name = "tracer-control-flow"
    summary = ("Python if/while on a tracer-derived boolean inside a "
               "traced function (use jnp.where / lax.cond)")

    def check(self, module: Module, ctx: LintContext) -> Iterator:
        for rec in module.traced_functions():
            tainted = rec.taint()
            for stmt in iter_own_statements(rec.node):
                if not isinstance(stmt, (ast.If, ast.While)):
                    continue
                hits = tracer_valued_names(stmt.test, tainted)
                if not hits:
                    continue
                kind = "if" if isinstance(stmt, ast.If) else "while"
                names = ", ".join(sorted({f"`{n.id}`" for n in hits}))
                yield self.finding(
                    module, stmt.lineno,
                    f"Python `{kind}` on tracer-derived {names} in traced "
                    f"`{rec.qualname}` — branch is resolved at TRACE time, "
                    "not per value (use jnp.where / lax.cond / "
                    "lax.while_loop)",
                )
