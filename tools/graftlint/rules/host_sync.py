"""GL001 host-sync-in-traced-scope and GL008 unbatched-host-transfers.

GL001: inside a function that runs under a JAX trace, ``.item()``,
``float()``/``int()``/``bool()`` on tracer-derived values, ``np.asarray``/
``np.array``, and ``jax.device_get`` all force a device->host sync (or a
ConcretizationTypeError at trace time). The same calls are FINE at adapter
boundaries — ``env/gym_adapter.py`` converts a fetched timestep for the
Gymnasium API — but fatal inside jitted bodies like the training update,
where one stray ``float()`` serializes the whole async dispatch pipeline
(~100 ms per sync through this repo's tunneled TPU, agent/loop.py).

GL008: boundary code that converts SEVERAL fields of one device result
with separate ``float()``/``bool()``/``np.asarray()`` calls pays one full
device round-trip PER FIELD. Fetch the whole structure once with
``jax.device_get`` and convert on the host — the exact fix measured in
``env/gym_adapter.py`` (two syncs per env step became one).
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.engine import (
    LintContext,
    Module,
    dotted_last,
    dotted_name,
    tracer_valued_names,
    walk_own,
)
from tools.graftlint.rules import Rule, register

_CONVERTERS = ("float", "int", "bool")
_NP_PULLS = ("asarray", "array")


def _is_np_call(node: ast.Call, names=("np", "numpy")) -> bool:
    return (
        isinstance(node.func, ast.Attribute)
        and node.func.attr in _NP_PULLS
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id in names
    )


@register
class HostSyncInTracedScope(Rule):
    id = "GL001"
    name = "host-sync-in-traced-scope"
    summary = ("device->host sync (.item()/float()/np.asarray/device_get) "
               "inside a jit/vmap/scan-traced function")

    def check(self, module: Module, ctx: LintContext) -> Iterator:
        for rec in module.traced_functions():
            tainted = rec.taint()
            for node in walk_own(rec.node):
                if not isinstance(node, ast.Call):
                    continue
                # x.item() on a tracer-derived value
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "item" and not node.args
                        and tracer_valued_names(node.func.value, tainted)):
                    yield self.finding(
                        module, node.lineno,
                        f"`.item()` on a tracer-derived value in traced "
                        f"`{rec.qualname}` forces a host sync",
                    )
                # float(x) / int(x) / bool(x) on tracer-derived values
                elif (isinstance(node.func, ast.Name)
                        and node.func.id in _CONVERTERS and node.args
                        and tracer_valued_names(node.args[0], tainted)):
                    yield self.finding(
                        module, node.lineno,
                        f"`{node.func.id}()` on a tracer-derived value in "
                        f"traced `{rec.qualname}` concretizes (host sync or "
                        "ConcretizationTypeError)",
                    )
                # np.asarray / np.array pulls the value to host
                elif _is_np_call(node) and node.args and \
                        tracer_valued_names(node.args[0], tainted):
                    yield self.finding(
                        module, node.lineno,
                        f"`{dotted_name(node.func)}` on a tracer-derived "
                        f"value in traced `{rec.qualname}` materializes on "
                        "host (use jnp.*)",
                    )
                # jax.device_get anywhere in a traced body
                elif dotted_last(node.func) == "device_get":
                    yield self.finding(
                        module, node.lineno,
                        f"`jax.device_get` inside traced `{rec.qualname}` "
                        "— fetch AFTER the jitted call returns",
                    )


@register
class UnbatchedHostTransfers(Rule):
    id = "GL008"
    name = "unbatched-host-transfers"
    summary = ("multiple per-field host conversions of one device result "
               "— batch them into a single jax.device_get")

    # How many separate field conversions of the same result object it
    # takes to flag: two conversions == two device round-trips.
    THRESHOLD = 2

    def check(self, module: Module, ctx: LintContext) -> Iterator:
        for rec in module.functions:
            if rec.traced:
                continue  # traced scopes are GL001's jurisdiction
            # Names bound by tuple-unpacking a call result (the
            # `state, ts = step(...)` shape device APIs return). Single
            # assignments are skipped on purpose: `args = parse_args()`
            # style host objects would be false positives.
            unpacked: set = set()
            for node in walk_own(rec.node):
                if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                    callee = dotted_last(node.value.func) or ""
                    if "parse" in callee.lower():
                        # parse_args / parse_known_args / json parsing —
                        # host objects whose field reads cost nothing.
                        continue
                    for t in node.targets:
                        if isinstance(t, (ast.Tuple, ast.List)):
                            unpacked.update(
                                e.id for e in t.elts if isinstance(e, ast.Name)
                            )
            if not unpacked:
                continue
            # Every `float(ts.field)`-style call is one device round-trip;
            # a device_get elsewhere in the function does NOT excuse the
            # per-field conversions that remain outside it (a partial
            # fetch still pays one sync per leftover field).
            conversions: dict = {}  # base name -> [call nodes]
            for node in walk_own(rec.node):
                if not isinstance(node, ast.Call):
                    continue
                is_converter = (
                    isinstance(node.func, ast.Name)
                    and node.func.id in _CONVERTERS
                )
                if not (is_converter or _is_np_call(node)) or not node.args:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Attribute) and \
                        isinstance(arg.value, ast.Name):
                    base = arg.value.id
                    if base in unpacked:
                        conversions.setdefault(base, []).append(node)
            for base, calls in sorted(conversions.items()):
                if len(calls) >= self.THRESHOLD:
                    first = min(c.lineno for c in calls)
                    yield self.finding(
                        module, first,
                        f"{len(calls)} separate host conversions of "
                        f"`{base}.*` in `{rec.qualname}` — each is a device "
                        f"round-trip; fetch once with "
                        f"`jax.device_get(({base}.…,))`",
                    )
