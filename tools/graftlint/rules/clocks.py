"""GL011 non-monotonic clock used for duration measurement.

``time.time()`` is wall-clock: NTP slews and steps move it, VM
suspend/resume jumps it, and a negative delta is a legal return value.
Every latency number this repo publishes — the extender's per-phase
spans and SLO burn windows (graftlens), the serving bench lines the
history gate compares, the study ledger's wall times — is a DURATION,
and durations must come from ``time.perf_counter()`` / ``time.monotonic()``
(the serving plane's own convention since round 4). A wall-clock delta
sneaking into one of these is a silent data-quality bug: the histogram
records a clock adjustment as a 40 ms decision.

The rule flags subtractions in ``scheduler/``, ``loadgen/`` and
``studies/`` where either operand is ``time.time()`` (directly, or a
name assigned from it in the same module). Wall-clock used as a
TIMESTAMP (``"ts": time.time()``) or shifted by a literal (epoch
arithmetic, ``time.time() - 3600``) stays unflagged — the clock is the
right tool for points in time, just never for distances between them.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.engine import LintContext, Module
from tools.graftlint.rules import Rule, register


def _bare_time_imported(tree: ast.AST) -> set:
    """Local names that mean the wall clock: ``from time import time``
    (with or without ``as``)."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name == "time":
                    names.add(alias.asname or alias.name)
    return names


def _is_wallclock_call(node: ast.AST, bare_names: set) -> bool:
    if not isinstance(node, ast.Call):
        return False
    func = node.func
    if (isinstance(func, ast.Attribute) and func.attr == "time"
            and isinstance(func.value, ast.Name)
            and func.value.id == "time"):
        return True  # time.time()
    return (isinstance(func, ast.Name) and func.id in bare_names)


def _tainted_names(tree: ast.AST, bare_names: set) -> set:
    """Names assigned from a wall-clock call anywhere in the module
    (one pass, scope-agnostic on purpose: a start-time variable's name
    is its identity here, and a false negative costs more than the
    theoretical shadowing false positive)."""
    tainted = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and _is_wallclock_call(
                node.value, bare_names):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    tainted.add(target.id)
        elif (isinstance(node, ast.AnnAssign) and node.value is not None
              and _is_wallclock_call(node.value, bare_names)
              and isinstance(node.target, ast.Name)):
            tainted.add(node.target.id)
    return tainted


@register
class NonMonotonicClockDelta(Rule):
    id = "GL011"
    name = "wallclock-latency"
    summary = ("time.time() delta used as a duration in scheduler//"
               "loadgen//studies/ — use time.perf_counter()/monotonic()")

    # Directories publishing latency/duration numbers (the serving
    # plane, its load generators, and the study ledger).
    DIRS = frozenset({"scheduler", "loadgen", "studies"})

    def check(self, module: Module, ctx: LintContext) -> Iterator:
        if not (self.DIRS & set(module.rel.split("/")[:-1])):
            return
        bare_names = _bare_time_imported(module.tree)
        tainted = _tainted_names(module.tree, bare_names)

        def wallclock(side: ast.AST) -> bool:
            return (_is_wallclock_call(side, bare_names)
                    or (isinstance(side, ast.Name) and side.id in tainted))

        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)):
                continue
            left, right = node.left, node.right
            if not (wallclock(left) or wallclock(right)):
                continue
            if isinstance(left, ast.Constant) or isinstance(right,
                                                            ast.Constant):
                continue  # epoch arithmetic (now - 3600): a timestamp
            yield self.finding(
                module, node.lineno,
                "time.time() delta measures a duration with the WALL "
                "clock (NTP steps/slews corrupt it) — use "
                "time.perf_counter() or time.monotonic() for intervals; "
                "wall-clock is for timestamps only",
            )
