"""GL012 blocking call inside an async def on the serving data plane.

graftfront's asyncio front runs EVERY connection on one event loop:
a single synchronous call inside a coroutine — ``time.sleep``, a bare
``open()``, a ``requests``/``urlopen`` HTTP round-trip, a blocking
socket ``accept``/``recv`` — stalls all 10k connections for its
duration, not just its own. That failure is silent in tests (one
connection never notices the loop pausing for itself) and catastrophic
under fan-in, which is exactly the regime the front exists for. The
repo's convention: coroutines in ``scheduler/`` either await, or hand
blocking work to the bounded executor (``loop.run_in_executor`` — how
``front.py`` runs the policy itself).

The rule flags synchronous calls in ``async def`` bodies under
``scheduler/``: ``time.sleep`` (and a bare ``sleep`` imported from
``time``), the ``open()`` builtin, ``requests.*``, ``urlopen``,
``socket.create_connection``, and blocking socket method calls
(``.accept()``/``.recv()``/``.recvfrom()``). Nested sync defs inside a
coroutine stay unflagged — defining a helper is free; only the
coroutine's own statements run on the loop.
"""

from __future__ import annotations

import ast
from typing import Iterator

from tools.graftlint.engine import Module, walk_own
from tools.graftlint.rules import Rule, register

# Blocking attribute calls by full dotted prefix (module-level APIs).
_BLOCKING_ATTRS = {
    ("time", "sleep"): "time.sleep() parks the whole event loop — "
                       "await asyncio.sleep() instead",
    ("socket", "create_connection"): "socket.create_connection() blocks "
                                     "the loop on the TCP handshake — "
                                     "use asyncio.open_connection()",
}
# Method names that are blocking on any socket-like receiver.
_BLOCKING_METHODS = {
    "accept": ".accept() blocks the loop until a peer connects — "
              "asyncio.start_server() owns the accept loop",
    "recv": ".recv() blocks the loop until bytes arrive — use a "
            "StreamReader (await reader.read/readexactly)",
    "recvfrom": ".recvfrom() blocks the loop until a datagram arrives "
                "— use a DatagramProtocol",
}


def _bare_sleep_names(tree: ast.AST) -> set:
    """Local names meaning ``time.sleep``: ``from time import sleep``."""
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name == "sleep":
                    names.add(alias.asname or alias.name)
    return names


def _blocking_message(func: ast.AST, sleep_names: set) -> str | None:
    """Why this callee blocks the loop, or None if it does not."""
    if isinstance(func, ast.Name):
        if func.id == "open":
            return ("open() is synchronous disk I/O on the event loop "
                    "— run it in the executor (loop.run_in_executor)")
        if func.id in sleep_names:
            return _BLOCKING_ATTRS[("time", "sleep")]
        if func.id == "urlopen":
            return ("urlopen() holds the loop for a full HTTP "
                    "round-trip — run it in the executor")
        return None
    if not isinstance(func, ast.Attribute):
        return None
    if func.attr == "urlopen":
        return ("urlopen() holds the loop for a full HTTP round-trip "
                "— run it in the executor")
    if isinstance(func.value, ast.Name):
        root = func.value.id
        msg = _BLOCKING_ATTRS.get((root, func.attr))
        if msg is not None:
            return msg
        if root == "requests":
            return (f"requests.{func.attr}() is a synchronous HTTP "
                    "client — run it in the executor")
    if func.attr in _BLOCKING_METHODS:
        return _BLOCKING_METHODS[func.attr]
    return None


@register
class BlockingCallInAsync(Rule):
    id = "GL012"
    name = "blocking-call-in-async"
    summary = ("synchronous blocking call inside an async def under "
               "scheduler/ — await, or hand it to the executor")

    # The asyncio front lives on the serving data plane; coroutines
    # elsewhere (tests, tools) are not one-loop-per-10k-connections.
    DIRS = frozenset({"scheduler"})

    def check(self, module: Module, ctx) -> Iterator:
        if not (self.DIRS & set(module.rel.split("/")[:-1])):
            return
        sleep_names = _bare_sleep_names(module.tree)
        for rec in module.functions:
            if not isinstance(rec.node, ast.AsyncFunctionDef):
                continue
            for node in walk_own(rec.node):
                if not isinstance(node, ast.Call):
                    continue
                msg = _blocking_message(node.func, sleep_names)
                if msg is not None:
                    yield self.finding(
                        module, node.lineno,
                        f"async def {rec.qualname} blocks the event "
                        f"loop: {msg}",
                    )
