"""graftlint: a JAX/TPU-aware static analyzer for this repo's invariants.

The codebase depends on unwritten discipline — no host syncs inside traced
scopes, no PRNG key reuse, MXU-aligned Pallas tile shapes, stable jit cache
keys — that nothing machine-checks: CI runs pytest only, and past PRs have
paid for silent violations by benchmarking them back out (the ~65
materialized HBM round-trips behind the fused set-block kernel, the
per-step device syncs in the adapter). In the spirit of chex (assert the
discipline, don't hope for it) and the PPO implementation-details
literature (most regressions are silent, not loud), graftlint turns those
invariants into AST-checked rules.

Pure-AST by design: no imports of the linted code, no JAX at analysis
time, so it runs identically on the CPU-only container and the TPU driver
regardless of their JAX version split (docs/static_analysis.md).

Usage::

    python -m tools.graftlint rl_scheduler_tpu tests loadgen
    python -m tools.graftlint --check          # paths from pyproject.toml
    python -m tools.graftlint --json --list-rules

Suppress a deliberate boundary case with a justified comment on (or
immediately above) the flagged line::

    return float(ts.reward)  # graftlint: disable=GL001 -- adapter boundary

Unjustified or unknown-rule suppressions are themselves findings (GL000).
The pytest gate (``tests/test_graftlint.py``) runs the analyzer over the
whole repo and fails on any unsuppressed finding.
"""

from tools.graftlint.config import LintConfig, load_config
from tools.graftlint.engine import Finding, LintResult, lint_paths
from tools.graftlint.rules import RULES, load_rules

__all__ = [
    "Finding",
    "LintConfig",
    "LintResult",
    "RULES",
    "lint_paths",
    "load_config",
    "load_rules",
]
