"""graftlint analysis engine: modules, traced-scope resolution, suppressions.

Everything here is pure-AST (``ast`` + ``re`` only — no JAX import), so the
analyzer behaves identically under the container's CPU JAX and the driver's
newer TPU JAX. The engine owns the three shared capabilities every rule
builds on:

- **Traced-scope resolution**: which functions run under a JAX trace. A
  function is traced when it is decorated with / passed to a tracing
  transform (``jax.jit``, ``vmap``, ``lax.scan``/``cond``/``while_loop``,
  ``shard_map``, ``pallas_call``, ``grad``, ...), when it is lexically
  nested inside a traced function, or — one call-graph level deep, per the
  design — when a traced function calls it by name within the same module.
- **Taint**: which names inside a traced function derive from its
  parameters (i.e. are tracers under trace). Static metadata reads
  (``x.shape``, ``x.ndim``, ``x.dtype``, ``len(x)``, ``isinstance(x, ..)``)
  are NOT tracer-valued and are excluded, so shape-driven Python control
  flow stays legal.
- **Suppressions**: ``# graftlint: disable=GL001[,GL002] -- justification``
  on the flagged line or the line directly above; ``disable-file=`` within
  the first ten lines for whole-file scope. A suppression without a
  ``--``-separated justification, or naming an unknown rule, is itself a
  finding (GL000) — suppressions are reserved for deliberate boundary
  cases and each must say why.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import re
from pathlib import Path
from typing import Iterable, Iterator

# Last attribute segments that put their function arguments under a JAX
# trace. Bare-name forms (``jit``, ``vmap``, ...) are accepted too: modules
# commonly do ``from jax import jit``. ``map`` is deliberately absent —
# matching the Python builtin would mark arbitrary host callbacks traced.
TRACING_CALL_NAMES = frozenset({
    "jit", "vmap", "pmap", "grad", "value_and_grad", "scan", "cond",
    "while_loop", "fori_loop", "switch", "shard_map", "shard_map_compat",
    "pallas_call", "checkpoint", "remat", "custom_vjp", "checkify",
    "named_scope", "eval_shape",
})

# Attribute reads that are static under trace (Python values, not tracers).
STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "aval", "sharding"})

# Call wrappers whose results are plain Python values even on tracer args.
STATIC_CALLS = frozenset({"isinstance", "hasattr", "getattr", "len", "type",
                          "callable", "id", "repr"})

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*(disable|disable-file)\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_,\s]+?)\s*(?:--\s*(?P<why>\S.*))?$"
)


@dataclasses.dataclass
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str
    line: int
    message: str
    suppressed: bool = False
    severity: str = "error"  # "error" | "warn", from [tool.graftlint.severity]

    def format(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        sev = " [warn]" if self.severity == "warn" else ""
        return f"{self.path}:{self.line}: {self.rule}{sev} {self.message}{tag}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class LintResult:
    findings: list  # list[Finding], sorted by (path, line, rule)
    files_checked: int
    # Justified suppressions whose rule no longer fires on the covered
    # line(s): the justification outlived the code it excused. Reported
    # as Findings (rule GL000) but kept OUT of ``findings`` — they are
    # the audit's verdict, never themselves suppressible.
    stale_suppressions: list = dataclasses.field(default_factory=list)

    @property
    def unsuppressed(self) -> list:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> list:
        return [f for f in self.findings if f.suppressed]

    @property
    def errors(self) -> list:
        """Unsuppressed findings at error severity — what gates exit 1."""
        return [f for f in self.unsuppressed if f.severity != "warn"]

    @property
    def warnings(self) -> list:
        """Unsuppressed findings at warn severity — printed, never gate."""
        return [f for f in self.unsuppressed if f.severity == "warn"]


def _comment_lines(source: str, lines: list) -> Iterator:
    """``(lineno, text)`` for lines carrying a REAL comment token.

    Tokenizing (rather than scanning raw lines) keeps string literals and
    docstrings out: documentation that QUOTES the suppression syntax must
    neither suppress nor trip the malformed-comment check. Falls back to
    every line when tokenization fails (the file already yields a GL000
    parse finding in that case).
    """
    import io
    import tokenize

    try:
        commented = {
            tok.start[0]
            for tok in tokenize.generate_tokens(io.StringIO(source).readline)
            if tok.type == tokenize.COMMENT
        }
    except (tokenize.TokenError, IndentationError, SyntaxError):
        commented = None
    for lineno, text in enumerate(lines, start=1):
        if commented is None or lineno in commented:
            yield lineno, text


class Suppressions:
    """Per-module suppression comments, with justification enforcement."""

    def __init__(self, source: str, lines: list, known_rules: Iterable[str]):
        known = set(known_rules)
        self.line_rules: dict = {}   # line number -> set of rule ids
        self.file_rules: set = set()
        self.bad: list = []          # (line, message) for GL000
        for lineno, text in _comment_lines(source, lines):
            m = _SUPPRESS_RE.search(text)
            if not m:
                if "graftlint:" in text:
                    self.bad.append(
                        (lineno, "malformed graftlint comment (expected "
                         "'# graftlint: disable=GLxxx -- justification')")
                    )
                continue
            kind = m.group(1)
            rules = {r.strip() for r in m.group("rules").split(",") if r.strip()}
            why = m.group("why")
            unknown = sorted(r for r in rules if r not in known)
            if unknown:
                self.bad.append(
                    (lineno, f"suppression names unknown rule(s) "
                             f"{', '.join(unknown)}")
                )
            if not why:
                self.bad.append(
                    (lineno, "suppression has no justification (append "
                             "' -- <why this boundary case is deliberate>')")
                )
                continue  # unjustified suppressions do not suppress
            rules &= known
            if kind == "disable-file":
                if lineno > 10:
                    self.bad.append(
                        (lineno, "disable-file must appear in the first "
                                 "10 lines")
                    )
                else:
                    self.file_rules |= rules
            else:
                self.line_rules.setdefault(lineno, set()).update(rules)

    def covers(self, rule: str, line: int) -> bool:
        if rule in self.file_rules:
            return True
        for candidate in (line, line - 1):
            if rule in self.line_rules.get(candidate, ()):
                return True
        return False


@dataclasses.dataclass
class FunctionRecord:
    """One function/method definition with its traced-scope verdict."""

    node: ast.AST                 # FunctionDef | AsyncFunctionDef
    qualname: str
    parent: "FunctionRecord | None"
    traced: bool = False
    traced_reason: str = ""
    # Parameters declared static at the jit site (static_argnums/
    # static_argnames): plain Python values under trace, never tainted.
    static_params: set = dataclasses.field(default_factory=set)

    @property
    def name(self) -> str:
        return self.node.name

    def taint(self) -> set:
        return taint_set(self.node, self.static_params)


def dotted_last(node: ast.AST) -> str | None:
    """Last segment of a Name/Attribute callee (``jax.lax.scan`` -> ``scan``)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def dotted_name(node: ast.AST) -> str | None:
    """Full dotted name of a Name/Attribute chain, or None if not one."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def iter_own_statements(fn_node: ast.AST) -> Iterator[ast.stmt]:
    """Statements belonging to ``fn_node`` itself, recursing into compound
    statements but NOT into nested function/class definitions (those are
    analyzed as their own scopes)."""

    def walk_block(stmts):
        for stmt in stmts:
            yield stmt
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for field in ("body", "orelse", "finalbody"):
                yield from walk_block(getattr(stmt, field, []) or [])
            for handler in getattr(stmt, "handlers", []) or []:
                yield from walk_block(handler.body)
            for case in getattr(stmt, "cases", []) or []:  # ast.Match
                yield from walk_block(case.body)

    yield from walk_block(fn_node.body)


def walk_own(fn_node: ast.AST) -> Iterator[ast.AST]:
    """All AST nodes of a function's own statements (no nested defs)."""
    for stmt in iter_own_statements(fn_node):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        # Walk the statement but prune nested definitions and compound
        # bodies (already yielded by iter_own_statements).
        yield from _walk_pruned(stmt)


def _walk_pruned(stmt: ast.stmt) -> Iterator[ast.AST]:
    yield stmt
    for field, value in ast.iter_fields(stmt):
        if field in ("body", "orelse", "finalbody", "handlers", "cases"):
            continue  # compound bodies come through iter_own_statements
        if isinstance(value, ast.AST):
            yield from _walk_expr(value)
        elif isinstance(value, list):
            for item in value:
                if isinstance(item, ast.AST):
                    yield from _walk_expr(item)
    for case in getattr(stmt, "cases", []) or []:  # ast.Match: patterns +
        if case.guard is not None:                 # guards are expressions
            yield from _walk_expr(case.guard)      # of this scope; bodies
        yield from _walk_expr(case.pattern)        # come via the caller


def _walk_expr(node: ast.AST) -> Iterator[ast.AST]:
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
        return
    yield node
    for child in ast.iter_child_nodes(node):
        yield from _walk_expr(child)


def param_names(fn_node: ast.AST) -> set:
    args = fn_node.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    for extra in (args.vararg, args.kwarg):
        if extra is not None:
            names.append(extra.arg)
    return {n for n in names if n != "self"}


def _assign_targets(node: ast.AST) -> list:
    """Flat list of simple Name targets of an assignment-ish statement."""
    out = []

    def collect(t):
        if isinstance(t, ast.Name):
            out.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                collect(e)
        elif isinstance(t, ast.Starred):
            collect(t.value)

    if isinstance(node, ast.Assign):
        for t in node.targets:
            collect(t)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        collect(node.target)
    elif isinstance(node, (ast.For, ast.AsyncFor)):
        collect(node.target)
    elif isinstance(node, (ast.With, ast.AsyncWith)):
        for item in node.items:
            if item.optional_vars is not None:
                collect(item.optional_vars)
    return out


def _names_in(node: ast.AST) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def taint_set(fn_node: ast.AST, static_params: set = frozenset()) -> set:
    """Names in ``fn_node`` that (may) derive from its parameters.

    Under trace the parameters are tracers; any value computed from them is
    a tracer too — EXCEPT values computed from static metadata
    (``x.shape``/``x.ndim``/``len(x)``/...), which stay Python values, so
    ``n = x.shape[0]`` does not taint ``n``, and EXCEPT parameters the jit
    site declared static (``static_params``). Two line-ordered passes over
    the function's own statements (enough for the back-reference patterns
    real code has; taint only grows, so this converges fast).
    """
    tainted = set(param_names(fn_node)) - set(static_params)
    for _ in range(2):
        for stmt in iter_own_statements(fn_node):
            targets = _assign_targets(stmt)
            if not targets:
                continue
            if isinstance(stmt, ast.Assign):
                source = stmt.value
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                source = stmt.value
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                source = stmt.iter
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                source = ast.Tuple(
                    elts=[i.context_expr for i in stmt.items], ctx=ast.Load()
                )
            else:
                source = None
            if source is None:
                continue
            if (isinstance(stmt, ast.AugAssign) and
                    set(targets) & tainted) or \
                    tracer_valued_names(source, tainted):
                tainted.update(targets)
    return tainted


def tracer_valued_names(expr: ast.AST, tainted: set) -> list:
    """Tainted Name nodes in ``expr`` that are tracer-VALUED uses.

    Excludes names whose use is static under trace: operands of
    ``isinstance``/``hasattr``/``len``/... calls, ``x is None`` tests, and
    reads of ``.shape``/``.ndim``/``.dtype``/... metadata.
    """
    out = []

    def visit(node, static):
        if isinstance(node, ast.Name):
            if node.id in tainted and not static:
                out.append(node)
            return
        if isinstance(node, ast.Call):
            callee = dotted_last(node.func)
            inner_static = static or callee in STATIC_CALLS
            if isinstance(node.func, ast.Attribute):
                # A method call's receiver is a real use: `state.sum()` is
                # tracer-valued when `state` is. (A bare callee Name is
                # not — referencing a function is not consuming a tracer.)
                visit(node.func.value, inner_static)
            for a in node.args:
                visit(a, inner_static)
            for kw in node.keywords:
                visit(kw.value, inner_static)
            return
        if isinstance(node, ast.Attribute):
            visit(node.value, static or node.attr in STATIC_ATTRS)
            return
        if isinstance(node, ast.Compare):
            is_only = all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops)
            visit(node.left, static or is_only)
            for comp in node.comparators:
                visit(comp, static or is_only)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, static)

    visit(expr, False)
    return out


class Module:
    """A parsed source file plus the shared per-module analyses."""

    def __init__(self, path: Path, rel: str, source: str,
                 known_rules: Iterable[str]):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=rel)
        self.suppressions = Suppressions(source, self.lines, known_rules)
        self.functions: list = []           # list[FunctionRecord]
        self._by_name: dict = {}            # bare name -> [FunctionRecord]
        self._index_functions()
        self._resolve_traced()

    # ---------------------------------------------------------- indexing

    def _index_functions(self) -> None:
        def visit(node, parent, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{prefix}{child.name}"
                    rec = FunctionRecord(child, qual, parent)
                    self.functions.append(rec)
                    self._by_name.setdefault(child.name, []).append(rec)
                    visit(child, rec, qual + ".")
                elif isinstance(child, ast.ClassDef):
                    visit(child, parent, f"{prefix}{child.name}.")
                else:
                    visit(child, parent, prefix)

        visit(self.tree, None, "")

    def records_named(self, name: str) -> list:
        return self._by_name.get(name, [])

    # ----------------------------------------------------- traced scopes

    def _mark(self, rec: FunctionRecord, reason: str) -> None:
        if not rec.traced:
            rec.traced = True
            rec.traced_reason = reason

    def _is_tracing_callee(self, func: ast.AST) -> bool:
        last = dotted_last(func)
        return last in TRACING_CALL_NAMES

    def _tracing_decorator(self, dec: ast.AST) -> bool:
        # @jax.jit / @jit / @jax.custom_vjp
        if self._is_tracing_callee(dec):
            return True
        # @jax.jit(static_argnames=...) / @partial(jax.jit, ...)
        if isinstance(dec, ast.Call):
            if self._is_tracing_callee(dec.func):
                return True
            if dotted_last(dec.func) == "partial" and dec.args:
                return self._is_tracing_callee(dec.args[0])
        return False

    @staticmethod
    def _static_params(keywords: list, fn_node: ast.AST) -> set:
        """Param names declared static by static_argnums/static_argnames
        keywords at a jit site, resolved against ``fn_node``'s signature."""
        out: set = set()
        args = fn_node.args
        positional = [a.arg for a in args.posonlyargs + args.args]
        for kw in keywords:
            if kw.arg == "static_argnames":
                out.update(
                    c.value for c in ast.walk(kw.value)
                    if isinstance(c, ast.Constant) and isinstance(c.value, str)
                )
            elif kw.arg == "static_argnums":
                for c in ast.walk(kw.value):
                    if isinstance(c, ast.Constant) and \
                            isinstance(c.value, int) and \
                            not isinstance(c.value, bool) and \
                            0 <= c.value < len(positional):
                        out.add(positional[c.value])
        return out

    def _resolve_traced(self) -> None:
        # Pass 1: direct marks — tracing decorators, and function names
        # passed as arguments to tracing calls anywhere in the module.
        for rec in self.functions:
            for dec in rec.node.decorator_list:
                if self._tracing_decorator(dec):
                    self._mark(rec, "tracing decorator")
                    if isinstance(dec, ast.Call):
                        rec.static_params |= self._static_params(
                            dec.keywords, rec.node
                        )
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            callee_args: list = []
            if self._is_tracing_callee(node.func):
                callee_args = list(node.args) + [k.value for k in node.keywords]
            elif dotted_last(node.func) == "partial" and node.args and \
                    self._is_tracing_callee(node.args[0]):
                callee_args = list(node.args[1:])
            transform = dotted_last(node.func) or "transform"
            for arg in callee_args:
                if isinstance(arg, ast.Name):
                    for rec in self.records_named(arg.id):
                        self._mark(rec, f"passed to {transform}")
                        rec.static_params |= self._static_params(
                            node.keywords, rec.node
                        )
        # Pass 2: lexical containment — a def nested inside a traced
        # function executes during the trace.
        self._propagate_containment()
        # Pass 3: one call-graph level — functions a traced body calls by
        # name are traced too (deep enough to catch helpers called from
        # jitted bodies without whole-program analysis).
        called: dict = {}
        for rec in [r for r in self.functions if r.traced]:
            for node in walk_own(rec.node):
                if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                    called.setdefault(node.func.id, rec.qualname)
        for name, caller in called.items():
            for rec in self.records_named(name):
                self._mark(rec, f"called from traced {caller}")
        self._propagate_containment()

    def _propagate_containment(self) -> None:
        for rec in self.functions:  # outer-to-inner indexing order
            parent = rec.parent
            if parent is not None and parent.traced:
                self._mark(rec, f"nested in traced {parent.qualname}")

    def traced_functions(self) -> list:
        return [r for r in self.functions if r.traced]


def _rel_to(path: Path, root: Path) -> str:
    try:
        return path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return path.as_posix()


def _is_excluded(rel: str, excludes: Iterable[str]) -> bool:
    return any(
        fnmatch.fnmatch(rel, pat)
        or rel.startswith(pat.rstrip("*").rstrip("/") + "/")
        for pat in excludes
    )


@dataclasses.dataclass
class LintContext:
    """Cross-module state shared with rules."""

    config: "LintConfig"
    modules: list                  # list[Module], the full lint set
    root: Path = dataclasses.field(default_factory=Path.cwd)
    _test_corpus: str | None = None

    def test_corpus(self) -> str:
        """Concatenated text of the configured test paths (GL007).

        Config excludes apply here too: the deliberately-bad fixture
        corpus must not count as "a test references this op"."""
        if self._test_corpus is None:
            chunks = []
            for base in self.config.test_paths:
                base_path = Path(base)
                if base_path.is_file():
                    candidates = [base_path]
                elif base_path.is_dir():
                    candidates = sorted(base_path.rglob("*.py"))
                else:
                    candidates = []
                for p in candidates:
                    if _is_excluded(_rel_to(p, self.root),
                                    self.config.exclude):
                        continue
                    chunks.append(p.read_text(errors="replace"))
            self._test_corpus = "\n".join(chunks)
        return self._test_corpus


def collect_files(paths: Iterable, excludes: Iterable[str],
                  root: Path) -> list:
    """Resolve CLI/config paths to the sorted list of .py files to lint."""
    files: list = []
    seen = set()
    for p in paths:
        p = Path(p)
        if p.is_dir():
            candidates = sorted(p.rglob("*.py"))
            apply_excludes = True
        elif p.suffix == ".py":
            # A file named explicitly is linted even if a config exclude
            # covers it — `python -m tools.graftlint <fixture>.py` is how
            # rule authors iterate on deliberately-bad fixture files.
            candidates = [p]
            apply_excludes = False
        else:
            candidates = []
            apply_excludes = True
        for c in candidates:
            rel = _rel_to(c, root)
            if rel in seen:
                continue
            if apply_excludes and _is_excluded(rel, excludes):
                continue
            seen.add(rel)
            files.append((c, rel))
    return files


def lint_paths(paths: Iterable, config: "LintConfig | None" = None,
               root: "Path | str | None" = None) -> LintResult:
    """Run every enabled rule over ``paths`` and return all findings
    (suppressed ones included, flagged)."""
    from tools.graftlint.config import LintConfig
    from tools.graftlint.rules import RULES, load_rules

    load_rules()
    config = config or LintConfig()
    root = Path(root) if root is not None else Path.cwd()
    known = set(RULES) | {"GL000"}
    files = collect_files(paths, config.exclude, root)

    modules: list = []
    findings: list = []
    for path, rel in files:
        try:
            source = path.read_text(errors="replace")
            modules.append(Module(path, rel, source, known))
        except SyntaxError as e:
            findings.append(Finding(
                "GL000", rel, e.lineno or 1,
                f"file does not parse: {e.msg} (graftlint needs valid "
                "Python to check invariants)"))

    ctx = LintContext(config=config, modules=modules, root=root)
    enabled = [r for rid, r in sorted(RULES.items())
               if rid not in config.disable]
    for module in modules:
        ignored_here = config.rules_ignored_for(module.rel)
        for lineno, msg in module.suppressions.bad:
            # GL000 is itself suppressible (with a justified
            # `disable=GL000`) so documenting or deliberately exercising
            # broken suppression syntax has an escape hatch.
            findings.append(Finding(
                "GL000", module.rel, lineno, msg,
                suppressed=module.suppressions.covers("GL000", lineno),
            ))
        for rule in enabled:
            if rule.id in ignored_here:
                continue
            for finding in rule.check(module, ctx):
                finding.suppressed = module.suppressions.covers(
                    finding.rule, finding.line
                )
                findings.append(finding)

    for finding in findings:
        finding.severity = config.severity_for(finding.rule)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    stale = _audit_suppressions(modules, findings, enabled, config)
    return LintResult(findings=findings, files_checked=len(files),
                      stale_suppressions=stale)


def _audit_suppressions(modules: list, findings: list, enabled: list,
                        config) -> list:
    """Justified suppressions whose rule no longer fires where they point.

    A line suppression for rule R at line S is stale when R actually RAN
    for that module (enabled, not per-path-ignored — a suppression for a
    rule the config skipped is unverifiable, not stale) and no R finding
    landed at S or S+1 (the two lines ``covers`` serves). A disable-file
    suppression is stale when R fires nowhere in the module. Stale
    entries are deliberate gate-failures: a justification whose target
    healed is a silenced alarm nobody will re-arm.
    """
    fired: dict = {}  # (rel, rule) -> set of lines
    for f in findings:
        fired.setdefault((f.path, f.rule), set()).add(f.line)
    stale: list = []
    for module in modules:
        ignored_here = config.rules_ignored_for(module.rel)
        ran = {r.id for r in enabled if r.id not in ignored_here} | {"GL000"}
        for lineno, rules in sorted(module.suppressions.line_rules.items()):
            for rule in sorted(rules):
                if rule not in ran:
                    continue
                lines = fired.get((module.rel, rule), set())
                if lineno not in lines and lineno + 1 not in lines:
                    stale.append(Finding(
                        "GL000", module.rel, lineno,
                        f"stale suppression: {rule} no longer fires on "
                        f"this line — the code it excused is gone; delete "
                        f"the disable comment (audit)"))
        for rule in sorted(module.suppressions.file_rules):
            if rule not in ran:
                continue
            if not fired.get((module.rel, rule)):
                stale.append(Finding(
                    "GL000", module.rel, 1,
                    f"stale suppression: disable-file={rule} but {rule} "
                    f"fires nowhere in this file; delete the disable "
                    f"comment (audit)"))
    stale.sort(key=lambda f: (f.path, f.line, f.rule))
    return stale
