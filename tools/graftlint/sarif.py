"""SARIF 2.1.0 serialization of a :class:`~tools.graftlint.engine.LintResult`.

SARIF is the interchange format CI annotators (GitHub code scanning,
Gitea, reviewdog) consume natively, so ``python -m tools.graftlint
--sarif out.sarif`` turns the gate's findings into inline PR annotations
with zero bespoke glue. The mapping is deliberately minimal and pinned
by ``tests/test_graftlint.py``:

- one ``run``, driver ``graftlint``, with the full rule registry (plus
  the synthetic GL000) in ``tool.driver.rules`` so viewers can resolve
  ``ruleId`` -> description without the repo checked out;
- one ``result`` per finding: ``level`` is ``error``/``warning`` from
  the per-rule severity, ``suppressions: [{kind: "inSource"}]`` marks
  in-source-suppressed findings (SARIF's own vocabulary for exactly our
  ``# graftlint: disable`` mechanism — consumers hide but retain them);
- stale-suppression audit findings ride along as ordinary ``error``
  results so a stale justification is visible in the same annotation
  stream that the suppression once silenced.
"""

from __future__ import annotations

import json
from pathlib import Path

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = "https://json.schemastore.org/sarif-2.1.0.json"


def _rule_descriptor(rule_id: str, name: str, summary: str) -> dict:
    return {
        "id": rule_id,
        "name": name,
        "shortDescription": {"text": summary},
    }


def _result(finding) -> dict:
    out = {
        "ruleId": finding.rule,
        "level": "warning" if finding.severity == "warn" else "error",
        "message": {"text": finding.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": finding.path},
                "region": {"startLine": max(1, finding.line)},
            },
        }],
    }
    if finding.suppressed:
        out["suppressions"] = [{"kind": "inSource"}]
    return out


def to_sarif(result) -> dict:
    """Build the SARIF document for a LintResult (rules registry included)."""
    from tools.graftlint.rules import RULES, load_rules

    load_rules()
    rules = [_rule_descriptor(
        "GL000", "bad-suppression",
        "suppression without justification / unknown rule / unparsable "
        "file / stale audit target")]
    rules += [_rule_descriptor(r.id, r.name, r.summary)
              for _, r in sorted(RULES.items())]
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri": "docs/static_analysis.md",
                "rules": rules,
            }},
            "results": [_result(f) for f in result.findings]
                       + [_result(f) for f in result.stale_suppressions],
        }],
    }


def write_sarif(result, path) -> None:
    Path(path).write_text(json.dumps(to_sarif(result), indent=2) + "\n")
