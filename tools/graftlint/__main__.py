"""graftlint CLI.

::

    python -m tools.graftlint [paths ...] [--json] [--list-rules]
                              [--select GL001,GL002] [--disable GL007]
                              [--show-suppressed] [--check]
                              [--sarif out.sarif] [--audit-suppressions]

With no paths, lints the ``[tool.graftlint]`` paths from pyproject.toml
(falling back to the repo defaults). Suppressed findings are counted
in the summary (and listed with ``--show-suppressed``) so deliberate
boundary cases stay visible without failing the gate.

Exit codes (``make lint`` relies on these):

- **0** — no unsuppressed error-severity finding (warn-severity findings
  are printed but do not gate: the ``[tool.graftlint.severity]``
  warn-first landing lane), and no stale suppression when
  ``--audit-suppressions`` is on.
- **1** — at least one unsuppressed error-severity finding, or (under
  ``--audit-suppressions``) a justified suppression whose rule no longer
  fires on its line.
- **2** — usage error (argparse).

``--check`` is an explicit alias for the default gate behavior so
``make lint`` reads honestly. ``--sarif PATH`` additionally writes the
findings as a SARIF 2.1.0 document for CI annotation (suppressed
findings carry ``suppressions: [{kind: "inSource"}]``).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys

from tools.graftlint.config import load_config
from tools.graftlint.engine import lint_paths
from tools.graftlint.rules import RULES, load_rules


def main(argv: list | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="JAX/TPU-aware static analyzer for this repo's trace, "
                    "PRNG, sync, and Pallas-tile invariants",
    )
    parser.add_argument("paths", nargs="*",
                        help="files/directories to lint (default: "
                             "[tool.graftlint] paths from pyproject.toml)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable JSON on stdout")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule registry and exit")
    parser.add_argument("--select", default=None,
                        help="comma-separated rule ids to run exclusively")
    parser.add_argument("--disable", default=None,
                        help="comma-separated rule ids to skip (adds to "
                             "the config's disable list)")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also print suppressed findings")
    parser.add_argument("--check", action="store_true",
                        help="explicit gate mode (the default behavior): "
                             "exit 1 on any unsuppressed error finding")
    parser.add_argument("--sarif", default=None, metavar="PATH",
                        help="also write findings as a SARIF 2.1.0 "
                             "document to PATH")
    parser.add_argument("--audit-suppressions", action="store_true",
                        help="fail (exit 1) on justified suppressions "
                             "whose rule no longer fires on their line")
    parser.add_argument("--config", default=None,
                        help="path to a pyproject.toml (default: ./pyproject.toml)")
    args = parser.parse_args(argv)

    load_rules()
    if args.list_rules:
        rows = [("GL000", "bad-suppression",
                 "suppression without justification / unknown rule / "
                 "unparsable file")]
        rows += [(r.id, r.name, r.summary) for _, r in sorted(RULES.items())]
        if args.as_json:
            print(json.dumps(
                [{"id": i, "name": n, "summary": s} for i, n, s in rows],
                indent=2))
        else:
            for rid, name, summary in rows:
                print(f"{rid}  {name:28s} {summary}")
        return 0

    config = load_config(args.config)
    if args.select:
        selected = {r.strip() for r in args.select.split(",") if r.strip()}
        config = dataclasses.replace(
            config,
            disable=tuple(set(RULES) - selected) + tuple(config.disable),
        )
    if args.disable:
        extra = tuple(r.strip() for r in args.disable.split(",") if r.strip())
        config = dataclasses.replace(config, disable=config.disable + extra)

    paths = args.paths or list(config.paths)
    result = lint_paths(paths, config)

    if args.sarif:
        from tools.graftlint.sarif import write_sarif

        write_sarif(result, args.sarif)

    stale = result.stale_suppressions if args.audit_suppressions else []
    if args.as_json:
        print(json.dumps({
            "files_checked": result.files_checked,
            "unsuppressed": [f.to_dict() for f in result.unsuppressed],
            "suppressed": [f.to_dict() for f in result.suppressed],
            "stale_suppressions": [
                f.to_dict() for f in result.stale_suppressions],
        }, indent=2))
    else:
        shown = result.findings if args.show_suppressed else result.unsuppressed
        for f in shown:
            print(f.format())
        for f in stale:
            print(f.format())
        print(
            f"graftlint: {len(result.errors)} error(s), "
            f"{len(result.warnings)} warning(s), "
            f"{len(result.suppressed)} suppressed "
            f"({len(result.stale_suppressions)} stale), "
            f"{result.files_checked} file(s) checked",
            file=sys.stderr,
        )
    return 1 if (result.errors or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
