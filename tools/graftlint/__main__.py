"""graftlint CLI.

::

    python -m tools.graftlint [paths ...] [--json] [--list-rules]
                              [--select GL001,GL002] [--disable GL007]
                              [--show-suppressed] [--check]

With no paths, lints the ``[tool.graftlint]`` paths from pyproject.toml
(falling back to the repo defaults). Exit status is 0 when no unsuppressed
finding remains, 1 otherwise — ``--check`` is an explicit alias for that
default so ``make lint`` reads honestly. Suppressed findings are counted
in the summary (and listed with ``--show-suppressed``) so deliberate
boundary cases stay visible without failing the gate.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys

from tools.graftlint.config import load_config
from tools.graftlint.engine import lint_paths
from tools.graftlint.rules import RULES, load_rules


def main(argv: list | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="JAX/TPU-aware static analyzer for this repo's trace, "
                    "PRNG, sync, and Pallas-tile invariants",
    )
    parser.add_argument("paths", nargs="*",
                        help="files/directories to lint (default: "
                             "[tool.graftlint] paths from pyproject.toml)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable JSON on stdout")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule registry and exit")
    parser.add_argument("--select", default=None,
                        help="comma-separated rule ids to run exclusively")
    parser.add_argument("--disable", default=None,
                        help="comma-separated rule ids to skip (adds to "
                             "the config's disable list)")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also print suppressed findings")
    parser.add_argument("--check", action="store_true",
                        help="explicit gate mode (the default behavior): "
                             "exit 1 on any unsuppressed finding")
    parser.add_argument("--config", default=None,
                        help="path to a pyproject.toml (default: ./pyproject.toml)")
    args = parser.parse_args(argv)

    load_rules()
    if args.list_rules:
        rows = [("GL000", "bad-suppression",
                 "suppression without justification / unknown rule / "
                 "unparsable file")]
        rows += [(r.id, r.name, r.summary) for _, r in sorted(RULES.items())]
        if args.as_json:
            print(json.dumps(
                [{"id": i, "name": n, "summary": s} for i, n, s in rows],
                indent=2))
        else:
            for rid, name, summary in rows:
                print(f"{rid}  {name:28s} {summary}")
        return 0

    config = load_config(args.config)
    if args.select:
        selected = {r.strip() for r in args.select.split(",") if r.strip()}
        config = dataclasses.replace(
            config,
            disable=tuple(set(RULES) - selected) + tuple(config.disable),
        )
    if args.disable:
        extra = tuple(r.strip() for r in args.disable.split(",") if r.strip())
        config = dataclasses.replace(config, disable=config.disable + extra)

    paths = args.paths or list(config.paths)
    result = lint_paths(paths, config)

    if args.as_json:
        print(json.dumps({
            "files_checked": result.files_checked,
            "unsuppressed": [f.to_dict() for f in result.unsuppressed],
            "suppressed": [f.to_dict() for f in result.suppressed],
        }, indent=2))
    else:
        shown = result.findings if args.show_suppressed else result.unsuppressed
        for f in shown:
            print(f.format())
        print(
            f"graftlint: {len(result.unsuppressed)} finding(s), "
            f"{len(result.suppressed)} suppressed, "
            f"{result.files_checked} file(s) checked",
            file=sys.stderr,
        )
    return 1 if result.unsuppressed else 0


if __name__ == "__main__":
    sys.exit(main())
