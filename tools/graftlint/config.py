"""graftlint configuration: defaults + the ``[tool.graftlint]`` pyproject
section.

Keys (all optional — defaults are this repo's layout)::

    [tool.graftlint]
    paths = ["rl_scheduler_tpu", "tests", "loadgen"]   # default lint set
    exclude = ["tests/graftlint_fixtures"]             # never linted
    test-paths = ["tests"]          # reference corpus for GL007
    disable = []                    # rule ids disabled everywhere

    [tool.graftlint.per-path-ignore]            # glob -> rule ids
    "loadgen/*" = ["GL007"]

    [tool.graftlint.severity]       # rule id -> "error" (default) | "warn"
    GL018 = "warn"                  # warn-first landing lane for new rules

TOML parsing uses stdlib ``tomllib`` when available (3.11+) and falls back
to ``tomli`` (the container's 3.10); with neither present the defaults
apply and a note goes to stderr — the analyzer itself never needs more
than the standard library.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import sys
from pathlib import Path

DEFAULT_PATHS = ("rl_scheduler_tpu", "tests", "loadgen", "tools")
DEFAULT_EXCLUDE = ("tests/graftlint_fixtures",)
DEFAULT_TEST_PATHS = ("tests",)


@dataclasses.dataclass
class LintConfig:
    paths: tuple = DEFAULT_PATHS
    exclude: tuple = DEFAULT_EXCLUDE
    test_paths: tuple = DEFAULT_TEST_PATHS
    disable: tuple = ()
    per_path_ignore: dict = dataclasses.field(default_factory=dict)
    severity: dict = dataclasses.field(default_factory=dict)

    def severity_for(self, rule_id: str) -> str:
        """Per-rule severity: "error" unless the config demotes to "warn".

        Warn findings are printed but never fail the gate — the lane a
        new rule lands in while its false-positive rate is unproven."""
        level = self.severity.get(rule_id, "error")
        return "warn" if level == "warn" else "error"

    def rules_ignored_for(self, rel: str) -> set:
        ignored: set = set()
        for pattern, rules in self.per_path_ignore.items():
            if fnmatch.fnmatch(rel, pattern) or rel.startswith(
                pattern.rstrip("*").rstrip("/") + "/"
            ):
                ignored.update(rules)
        return ignored


def _load_toml(path: Path) -> dict:
    try:
        import tomllib  # Python 3.11+
    except ImportError:
        try:
            import tomli as tomllib
        except ImportError:
            print(
                "graftlint: no TOML parser (tomllib/tomli); using built-in "
                "defaults instead of [tool.graftlint]",
                file=sys.stderr,
            )
            return {}
    with path.open("rb") as fh:
        return tomllib.load(fh)


def load_config(pyproject: Path | str | None = None) -> LintConfig:
    """Read ``[tool.graftlint]`` from ``pyproject.toml`` (cwd by default)."""
    path = Path(pyproject) if pyproject is not None else Path("pyproject.toml")
    if not path.is_file():
        return LintConfig()
    section = _load_toml(path).get("tool", {}).get("graftlint", {})
    if not section:
        return LintConfig()
    return LintConfig(
        paths=tuple(section.get("paths", DEFAULT_PATHS)),
        exclude=tuple(section.get("exclude", DEFAULT_EXCLUDE)),
        test_paths=tuple(section.get("test-paths", DEFAULT_TEST_PATHS)),
        disable=tuple(section.get("disable", ())),
        per_path_ignore={
            k: tuple(v)
            for k, v in section.get("per-path-ignore", {}).items()
        },
        severity=dict(section.get("severity", {})),
    )
