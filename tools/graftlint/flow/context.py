"""Execution-context tagging: which thread/process/loop runs a function.

The concurrency rule pack needs to know WHERE code runs before it can
say what discipline applies: a write inside an HTTP handler races with
its siblings, a ``close()`` on the supervisor thread owns the drain
contract, an ``async def`` body must not block the front's event loop.
None of that is spelled in the function — it is spelled at the *entry
seams*, and this repo has a small closed set of them:

- HTTP/socketserver handler classes (``BaseHTTPRequestHandler``
  subclasses — graftserve/graftfleet's request paths), where every
  ``do_*``/``handle*`` method runs on a per-connection daemon thread;
- ``threading.Thread(target=...)`` construction sites (tracelog's
  writer, the async placer, fleet scrape fan-out);
- ``multiprocessing``/fork worker targets (the pool's forked workers);
- ``async def`` (graftfront's event loop) and
  ``run_in_executor``/``Executor.submit`` seams (sync helpers hopped
  onto executor threads);
- everything else: the supervisor/main context that constructs and
  joins the above.

:func:`module_contexts` derives a per-function tag set from those seams
in one module pass. Tags are a may-analysis — a function referenced by
two seams carries both tags — and lexical nesting inherits the parent's
context (a closure defined on the writer thread runs on the writer
thread).
"""

from __future__ import annotations

import ast

from tools.graftlint.engine import dotted_last

# The closed tag vocabulary. "main" is the default (module import /
# supervisor call chain); "supervisor" additionally marks functions that
# CONSTRUCT threads/processes/servers and therefore own drain contracts.
CONTEXTS = frozenset({
    "main", "handler", "async", "thread", "forked-worker",
    "executor", "supervisor",
})

# Base classes whose subclasses' methods run per-connection, usually on
# daemon threads owned by a ThreadingMixIn server.
_HANDLER_BASES = frozenset({
    "BaseHTTPRequestHandler", "SimpleHTTPRequestHandler",
    "BaseRequestHandler", "StreamRequestHandler", "DatagramRequestHandler",
})

# Server/executor types whose construction marks the enclosing function
# as a supervisor (it owns lifecycle for some other context).
_SUPERVISED_TYPES = frozenset({
    "Thread", "Process", "ThreadPoolExecutor", "ProcessPoolExecutor",
    "ThreadingHTTPServer", "HTTPServer", "TCPServer", "UDPServer",
})


def _target_names(call: ast.Call) -> list:
    """Bare names a Thread/Process ``target=``/``submit`` seam invokes."""
    out = []
    for kw in call.keywords:
        if kw.arg == "target":
            name = dotted_last(kw.value)
            if name:
                out.append(name)
    return out


def module_contexts(module) -> dict:
    """``qualname -> frozenset(tags)`` for every function in ``module``.

    ``module`` is an engine :class:`~tools.graftlint.engine.Module`.
    Every function gets at least ``{"main"}``; seam-derived tags are
    added on top, then lexical nesting inherits the parent's tags.
    """
    tags: dict = {rec.qualname: {"main"} for rec in module.functions}

    def add(name: str, tag: str) -> None:
        for rec in module.records_named(name):
            tags[rec.qualname].add(tag)

    # Seam 1: handler classes. Transitive within the module: a subclass
    # of a local handler subclass is a handler class too.
    handler_classes: set = set()
    class_bases: dict = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ClassDef):
            bases = {dotted_last(b) for b in node.bases} - {None}
            class_bases[node.name] = bases
            if bases & _HANDLER_BASES:
                handler_classes.add(node.name)
    changed = True
    while changed:
        changed = False
        for cls, bases in class_bases.items():
            if cls not in handler_classes and bases & handler_classes:
                handler_classes.add(cls)
                changed = True
    for rec in module.functions:
        cls = rec.qualname.rsplit(".", 1)[0] if "." in rec.qualname else None
        if cls in handler_classes:
            tags[rec.qualname].add("handler")

    # Seam 2: async defs run on the event loop.
    for rec in module.functions:
        if isinstance(rec.node, ast.AsyncFunctionDef):
            tags[rec.qualname].add("async")

    # Seams 3–5: construction/submission sites, one walk.
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = dotted_last(node.func)
        if callee == "Thread":
            for name in _target_names(node):
                add(name, "thread")
        elif callee == "Process":
            for name in _target_names(node):
                add(name, "forked-worker")
        elif callee == "submit" and node.args:
            name = dotted_last(node.args[0])
            if name:
                add(name, "executor")
        elif callee == "run_in_executor" and len(node.args) >= 2:
            name = dotted_last(node.args[1])
            if name:
                add(name, "executor")

    # Supervisor: a function whose own body constructs a supervised type
    # owns lifecycle for another context.
    from tools.graftlint.engine import walk_own

    for rec in module.functions:
        for node in walk_own(rec.node):
            if isinstance(node, ast.Call) and \
                    dotted_last(node.func) in _SUPERVISED_TYPES:
                tags[rec.qualname].add("supervisor")
                break

    # Lexical nesting inherits: a closure defined in a thread-target
    # executes on that thread (minus "supervisor", which is about the
    # parent's own body).
    for rec in module.functions:  # outer-to-inner indexing order
        if rec.parent is not None:
            tags[rec.qualname] |= tags[rec.parent.qualname] - {"supervisor"}

    return {q: frozenset(t) for q, t in tags.items()}
