"""graftflow: the intra-function dataflow tier under graftlint.

The GL001–GL012 rules are per-statement matchers; the concurrency/
atomicity defect classes the review rounds kept re-catching (CHANGES.md
PRs 4–17) all require tracking a VALUE across statements: the path
expression that was ``exists()``-checked and then ``rmtree``'d, the file
handle that was opened ``"w"`` and then ``json.dump``'ed into, the
daemon thread handle that a ``close()`` joins, the one shared breaker
instance that two endpoint keys reach. graftflow provides exactly that
much dataflow — no more:

- :mod:`tools.graftlint.flow.defuse` — def-use chains over simple
  names within one scope, canonical path expressions for
  attribute/subscript roots, and a small string-constant lattice good
  enough to answer "does this path expression name a ``.json``
  artifact?" / "does this value flow from ``tempfile``/``O_EXCL``?".
- :mod:`tools.graftlint.flow.context` — a class-level execution-context
  model tagging each function with the thread/process/event-loop it
  runs on, derived from the known entry seams (HTTP handler classes,
  ``threading.Thread(target=...)``, fork supervisor vs forked child,
  ``async def`` on the front's loop vs nested sync helpers on the
  executor).

Everything stays pure-AST (``ast`` only), same as the engine: the
analyzer behaves identically on the container's CPU JAX and the
driver's TPU JAX, because it never imports either.
"""

from tools.graftlint.flow.context import (  # noqa: F401
    CONTEXTS,
    module_contexts,
)
from tools.graftlint.flow.defuse import (  # noqa: F401
    DefUse,
    flows_through,
    literal_strings,
    path_expr,
    scope_statements,
    scope_walk,
)
