"""Def-use chains, canonical path expressions, and the string lattice.

The unit of analysis is one *scope* — a function body (via the engine's
``iter_own_statements``, which recurses into compound statements but not
nested defs) or a whole module's top-level statements. Within a scope,
:class:`DefUse` records every binding of every simple name in line
order, so a rule can ask "what value reached ``dest`` by line 96?" and
follow it backwards a bounded number of hops.

Three deliberately-small abstractions ride on top:

- :func:`path_expr` — a canonical string for a path-like expression
  (``self._queue``, ``dest``, ``qdir / str(step)``), used to decide
  "is this the same path expression that was checked?" Textual identity
  over one scope is the right granularity for the TOCTOU class: the
  review-round defects were literally check-then-act on the same
  spelled expression.
- :func:`literal_strings` — the value lattice's string facet: every
  string constant reachable in an expression (through f-strings,
  ``+``/``/`` concatenation, ``Path(...)``/``str(...)`` wrappers, and
  def-use hops), so a rule can ask "does this path name a ``.json``
  artifact?" or "is there a ``.tmp`` marker in this name?".
- :func:`flows_through` — "does this value's construction involve a
  call to one of these names?" (``tempfile``/``mkstemp``/``O_EXCL``
  handling, ``Thread(daemon=True)`` construction).
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from tools.graftlint.engine import dotted_last, iter_own_statements, walk_own

# Call wrappers that are path-transparent: the path identity of
# ``str(p)`` / ``Path(p)`` is the identity of ``p``.
_PATH_WRAPPERS = frozenset({"str", "Path", "PurePath", "PosixPath",
                            "fspath", "abspath", "resolve", "absolute"})

_MAX_HOPS = 3  # def-use resolution depth bound (keeps the lattice O(1))


def _shell(node: ast.AST) -> ast.AST:
    """A function-shaped wrapper so engine scope walks accept Modules."""
    if not isinstance(node, ast.Module):
        return node
    return ast.FunctionDef(
        name="<module>", args=ast.arguments(
            posonlyargs=[], args=[], kwonlyargs=[], kw_defaults=[],
            defaults=[]),
        body=node.body, decorator_list=[], returns=None,
    )


def scope_statements(node: ast.AST) -> Iterator[ast.stmt]:
    """Line-ordered own statements of a function OR module scope."""
    yield from iter_own_statements(_shell(node))


def scope_walk(node: ast.AST) -> Iterator[ast.AST]:
    """All AST nodes of a scope's own statements (no nested defs) — the
    module-capable sibling of the engine's ``walk_own``."""
    yield from walk_own(_shell(node))


class DefUse:
    """Intra-scope def-use chains over simple names.

    Bindings are recorded in line order for ``Assign``/``AnnAssign``/
    ``AugAssign``, ``for`` targets (the loop-carried case: the binding's
    value is the iterable), and ``with ... as`` targets (value = the
    context expression). Tuple targets record each element against the
    whole right-hand side — coarse, but sound for the string/flow
    queries rules make.
    """

    def __init__(self, scope: ast.AST):
        self.bindings: dict = {}  # name -> [(lineno, value_node)]
        for stmt in scope_statements(scope):
            for name, value in _stmt_bindings(stmt):
                if value is not None:
                    self.bindings.setdefault(name, []).append(
                        (stmt.lineno, value))

    def values(self, name: str) -> list:
        """Every value node ever bound to ``name`` in this scope."""
        return [v for _, v in self.bindings.get(name, [])]

    def value_at(self, name: str, lineno: int) -> ast.AST | None:
        """The value of the LAST binding of ``name`` at or before
        ``lineno`` (the reaching definition, straight-line approximation
        — reassignment picks the newest, loop-carried bindings resolve
        to the iterable)."""
        best = None
        for bound_line, value in self.bindings.get(name, []):
            if bound_line <= lineno:
                best = value
        return best


def _stmt_bindings(stmt: ast.stmt) -> Iterator:
    """(name, value_node) pairs bound by one statement."""

    def targets_of(t) -> Iterator[str]:
        if isinstance(t, ast.Name):
            yield t.id
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                yield from targets_of(e)
        elif isinstance(t, ast.Starred):
            yield from targets_of(t.value)

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            for name in targets_of(t):
                yield name, stmt.value
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        for name in targets_of(stmt.target):
            yield name, stmt.value
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        for name in targets_of(stmt.target):
            yield name, stmt.iter
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                for name in targets_of(item.optional_vars):
                    yield name, item.context_expr
    elif isinstance(stmt, ast.NamedExpr):  # walrus at statement level
        for name in targets_of(stmt.target):
            yield name, stmt.value


def path_expr(node: ast.AST) -> str | None:
    """Canonical textual identity of a path-like expression.

    ``None`` means "no stable identity" (a call result, a literal-free
    computation) — rules treat that as never-matching rather than
    guessing. Path-transparent wrappers (``str(p)``, ``Path(p)``,
    ``p.resolve()``) canonicalize to their operand so a check on ``p``
    matches an act on ``str(p)``.
    """
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        if node.attr in ("parent",):  # p.parent is a DIFFERENT path
            base = path_expr(node.value)
            return f"{base}.parent" if base else None
        base = path_expr(node.value)
        return f"{base}.{node.attr}" if base else None
    if isinstance(node, ast.Subscript):
        base = path_expr(node.value)
        if base is None:
            return None
        if isinstance(node.slice, ast.Constant):
            return f"{base}[{node.slice.value!r}]"
        inner = path_expr(node.slice)
        return f"{base}[{inner}]" if inner else None
    if isinstance(node, ast.Call):
        callee = dotted_last(node.func)
        if callee in _PATH_WRAPPERS:
            if node.args:
                return path_expr(node.args[0])
            # p.resolve() / p.absolute(): identity of the receiver
            if isinstance(node.func, ast.Attribute):
                return path_expr(node.func.value)
        return None
    if isinstance(node, ast.BinOp) and isinstance(node.op, (ast.Div, ast.Add)):
        left, right = path_expr(node.left), path_expr(node.right)
        if left and right:
            return f"({left}/{right})"
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return repr(node.value)
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(repr(v.value))
            else:
                inner = path_expr(
                    v.value if isinstance(v, ast.FormattedValue) else v)
                if inner is None:
                    return None
                parts.append(inner)
        return "+".join(parts)
    return None


def literal_strings(node: ast.AST, defuse: DefUse | None = None,
                    lineno: int | None = None,
                    _hops: int = _MAX_HOPS) -> set:
    """Every string constant reachable in ``node``'s construction.

    Follows f-string parts, ``+``/``/`` concatenation, call arguments
    (``Path("x") / name`` and formatting helpers alike), and — when a
    :class:`DefUse` is given — up to ``_MAX_HOPS`` def-use hops through
    simple names (resolved at ``lineno`` when given, else every binding
    contributes: the lattice is a may-analysis).
    """
    out: set = set()

    def visit(n: ast.AST, hops: int) -> None:
        if isinstance(n, ast.Constant):
            if isinstance(n.value, str):
                out.add(n.value)
            return
        if isinstance(n, ast.Name):
            if defuse is not None and hops > 0:
                if lineno is not None:
                    value = defuse.value_at(n.id, lineno)
                    values = [value] if value is not None else []
                else:
                    values = defuse.values(n.id)
                for v in values:
                    visit(v, hops - 1)
            return
        if isinstance(n, ast.JoinedStr):
            for v in n.values:
                visit(v, hops)
            return
        if isinstance(n, ast.FormattedValue):
            visit(n.value, hops)
            return
        if isinstance(n, (ast.BinOp, ast.Call, ast.Attribute, ast.Subscript,
                          ast.Tuple, ast.List, ast.IfExp, ast.NamedExpr)):
            for child in ast.iter_child_nodes(n):
                visit(child, hops)
            return

    visit(node, _hops)
    return out


def flows_through(node: ast.AST, call_names: Iterable[str],
                  defuse: DefUse | None = None,
                  _hops: int = _MAX_HOPS) -> bool:
    """Whether ``node``'s construction involves a call to (or attribute
    read of) one of ``call_names`` — transitively through def-use hops.

    Answers the lattice's provenance questions: "does this handle flow
    from ``tempfile``?", "is ``O_EXCL`` in this open's flag
    expression?", "was this thread constructed ``daemon=True``?".
    """
    names = set(call_names)

    def visit(n: ast.AST, hops: int) -> bool:
        for sub in ast.walk(n):
            if isinstance(sub, ast.Call) and dotted_last(sub.func) in names:
                return True
            if isinstance(sub, ast.Attribute) and sub.attr in names:
                return True
            if isinstance(sub, ast.Name):
                if sub.id in names:
                    return True
                if defuse is not None and hops > 0 and sub is not n:
                    for v in defuse.values(sub.id):
                        if visit(v, hops - 1):
                            return True
        if isinstance(n, ast.Name) and defuse is not None and hops > 0:
            for v in defuse.values(n.id):
                if visit(v, hops - 1):
                    return True
        return False

    return visit(node, _hops)
