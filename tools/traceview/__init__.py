"""graftscope part 3: trace-derived phase profiling (docs/observability.md).

``utils/profiling.trace_iterations`` (and ``train_ppo --profile-dir``)
writes Perfetto/Chrome-trace ``.trace.json.gz`` artifacts that, until now,
were only ever eyeballed in a UI — nothing parsed them into numbers a
regression check could hold. traceview does exactly that, offline:

- **Self-time attribution**: duration events nest (an XLA module event
  spans every op inside it); naive summing double-counts. Per thread, a
  stack pass subtracts each event's duration from its parent, so every
  microsecond is attributed exactly once.
- **Phase classification**: the trainers annotate their update with
  ``jax.named_scope`` (``rollout``/``gae``/``sgd`` in PPO,
  ``collect``/``learn`` in DQN, ``scope_metrics`` for the metrics layer
  itself). On op-metadata-bearing traces (the TPU driver) those scopes
  appear in event ``long_name``/arg strings and events classify by
  substring; events without a marker land in ``other``. CPU-container
  traces carry op names only, so phases mostly read ``other`` there —
  the CATEGORY split still works, and the parser itself is pure offline
  JSON: it runs identically on both sides of the version split.
- **Category classification**: ``transfer`` (copies, infeed/outfeed,
  collectives — the HBM/ICI traffic the roofline docs reason about),
  ``host`` (python frames, callbacks, executor scaffolding), else
  ``compute``.
- **Budgets**: ``budgets.json`` records per-phase millisecond budgets
  with a tolerance; ``--check`` exits nonzero when a phase exceeds its
  budget by more than the tolerance (or vanished entirely — renamed
  scopes must not pass silently), the same fail-the-build contract as a
  graftlint finding. ``--write-budgets`` records the current trace as
  the new baseline.

Pure stdlib (json/gzip) — no JAX import, usable on any checkout.
"""

from __future__ import annotations

import gzip
import json
from pathlib import Path

SCHEMA_VERSION = 1

# Phase markers: substrings searched in each event's name + argument
# strings. Ordered — first hit wins (longer/rarer markers first so e.g.
# "scope_metrics" is not swallowed by a hypothetical "metrics" phase, and
# graftpipe's "overlap_collect"/"prologue" scopes are claimed before the
# generic "collect"/"sgd" markers could swallow them — prologue events
# nest INSIDE the sgd scan, so "prologue" must outrank "sgd").
DEFAULT_PHASES = (
    ("scope_metrics", ("scope_metrics",)),
    ("overlap", ("overlap_collect",)),
    ("prologue", ("prologue",)),
    ("rollout", ("rollout",)),
    ("gae", ("/gae/", "gae/", "(gae)")),
    ("sgd", ("sgd",)),
    ("collect", ("/collect/", "collect/", "(collect)")),
    ("learn", ("/learn/", "learn/", "(learn)")),
)

TRANSFER_MARKERS = ("copy", "transfer", "infeed", "outfeed", "memset",
                    "all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "send", "recv")
HOST_MARKERS = ("python", "callback", "pjit", "executehelper",
                "parsearguments", "threadpool", "$")
CATEGORIES = ("compute", "transfer", "host")


def find_trace(path: str | Path) -> Path:
    """Resolve a trace artifact: a file as-is, or the newest
    ``*.trace.json.gz`` under a directory (the layout
    ``jax.profiler.trace`` writes: ``<dir>/plugins/profile/<ts>/...``)."""
    path = Path(path)
    if path.is_file():
        return path
    if path.is_dir():
        candidates = sorted(path.rglob("*.trace.json.gz"),
                            key=lambda p: p.stat().st_mtime)
        if candidates:
            return candidates[-1]
    raise FileNotFoundError(
        f"no trace at {path} (expected a .trace.json[.gz] file or a "
        "profiler log dir containing one)")


def load_trace(path: str | Path) -> dict:
    path = find_trace(path)
    opener = gzip.open if path.name.endswith(".gz") else open
    with opener(path, "rt") as fh:
        return json.load(fh)


def _event_text(event: dict, thread_names: dict) -> str:
    parts = [str(event.get("name", ""))]
    for v in (event.get("args") or {}).values():
        if isinstance(v, str):
            parts.append(v)
    tname = thread_names.get((event.get("pid"), event.get("tid")))
    if tname:
        parts.append(tname)
    return " ".join(parts).lower()


def _classify_phase(text: str, phases) -> str:
    for phase, markers in phases:
        if any(m in text for m in markers):
            return phase
    return "other"


def _classify_category(text: str) -> str:
    if any(m in text for m in HOST_MARKERS):
        return "host"
    if any(m in text for m in TRANSFER_MARKERS):
        return "transfer"
    return "compute"


def _self_times(events: list) -> list:
    """``(event, self_dur_us)`` with child durations subtracted, per the
    Chrome-trace nesting convention (same thread, enclosing [ts, ts+dur)).
    Events are attributed exactly once; partial overlaps (clock skew in
    real traces) degrade gracefully to inner-wins."""
    out = []
    by_thread: dict = {}
    for e in events:
        by_thread.setdefault((e.get("pid"), e.get("tid")), []).append(e)
    for thread_events in by_thread.values():
        thread_events.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack: list = []  # [event, end, self_dur]
        for e in thread_events:
            while stack and stack[-1][1] <= e["ts"]:
                out.append((stack[-1][0], max(stack[-1][2], 0.0)))
                stack.pop()
            if stack:
                stack[-1][2] -= e["dur"]
            stack.append([e, e["ts"] + e["dur"], float(e["dur"])])
        for ev, _, self_dur in stack:
            out.append((ev, max(self_dur, 0.0)))
    return out


def summarize(data: dict, source: str = "", phases=DEFAULT_PHASES) -> dict:
    """The documented traceview schema (docs/observability.md): total and
    per-phase self-time in ms, each phase split by category."""
    events = data.get("traceEvents", data if isinstance(data, list) else [])
    thread_names = {}
    durations = []
    for e in events:
        if e.get("ph") == "M" and e.get("name") in ("thread_name",
                                                    "process_name"):
            thread_names[(e.get("pid"), e.get("tid"))] = \
                (e.get("args") or {}).get("name", "")
        elif e.get("ph") == "X" and e.get("dur", 0) > 0:
            durations.append(e)

    buckets: dict = {}
    total_us = 0.0
    for event, self_us in _self_times(durations):
        if self_us <= 0:
            continue
        text = _event_text(event, thread_names)
        phase = _classify_phase(text, phases)
        category = _classify_category(text)
        row = buckets.setdefault(phase, {c: 0.0 for c in CATEGORIES})
        row[category] += self_us
        total_us += self_us

    phase_out = {}
    for phase, cats in sorted(buckets.items()):
        phase_total = sum(cats.values())
        phase_out[phase] = {
            "total_ms": round(phase_total / 1e3, 6),
            "fraction": round(phase_total / total_us, 6) if total_us else 0.0,
            "categories": {c: round(v / 1e3, 6) for c, v in cats.items()},
        }
    return {
        "metric": "traceview-phase-breakdown",
        "unit": "ms",
        "schema_version": SCHEMA_VERSION,
        "source": source,
        "total_ms": round(total_us / 1e3, 6),
        "phases": phase_out,
    }


def check_budgets(summary: dict, budgets: dict) -> list:
    """Violation strings (empty = within budget). A phase fails when its
    self-time exceeds ``budget_ms * (1 + tolerance_pct/100)``, or when a
    budgeted phase produced NO time at all — a renamed named_scope would
    otherwise zero a phase and sail through."""
    tolerance = float(budgets.get("tolerance_pct", 20.0))
    violations = []
    for phase, budget_ms in sorted(budgets.get("phases", {}).items()):
        measured = summary["phases"].get(phase, {}).get("total_ms", 0.0)
        limit = float(budget_ms) * (1.0 + tolerance / 100.0)
        if measured == 0.0 and float(budget_ms) > 0.0:
            violations.append(
                f"phase {phase!r}: absent from the trace (budget "
                f"{budget_ms} ms) — renamed scope or broken attribution?")
        elif measured > limit:
            violations.append(
                f"phase {phase!r}: {measured:.3f} ms exceeds budget "
                f"{budget_ms} ms by more than {tolerance:.0f}% "
                f"(limit {limit:.3f} ms)")
    return violations


def budgets_from_summary(summary: dict, tolerance_pct: float = 20.0) -> dict:
    """Record the current trace as the new per-phase baseline (the
    ``--write-budgets`` path). ``other`` is excluded: it aggregates
    unattributed time and would make the budget meaninglessly broad."""
    return {
        "tolerance_pct": tolerance_pct,
        "unit": "ms",
        "phases": {
            phase: entry["total_ms"]
            for phase, entry in summary["phases"].items()
            if phase != "other"
        },
    }
