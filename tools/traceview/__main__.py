"""traceview CLI.

Usage::

    python -m tools.traceview <trace.json[.gz] | profiler-log-dir>
    python -m tools.traceview --check --budgets tools/traceview/budgets.json \
        tests/fixtures/traceview/fixture.trace.json.gz
    python -m tools.traceview --write-budgets tools/traceview/budgets.json \
        /tmp/profile_dir

Prints ONE bench.py-style JSON summary line to stdout (the documented
schema, docs/observability.md); ``--check`` exits 2 on any budget
violation, the same fail-the-build contract as graftlint.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from tools.traceview import (
    budgets_from_summary,
    check_budgets,
    find_trace,
    load_trace,
    summarize,
)


def main(argv: list | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tools.traceview",
        description="Parse a jax.profiler Perfetto trace into a per-phase/"
                    "per-category breakdown and check it against budgets.")
    p.add_argument("trace", help="a .trace.json[.gz] file or a profiler "
                                 "log dir (newest trace inside is used)")
    p.add_argument("--budgets", default=None,
                   help="budgets JSON (per-phase ms + tolerance_pct); "
                        "violations print to stderr")
    p.add_argument("--check", action="store_true",
                   help="exit 2 when any budgeted phase exceeds its "
                        "budget by more than the tolerance")
    p.add_argument("--write-budgets", default=None, metavar="OUT",
                   help="record this trace's per-phase totals as the new "
                        "budget baseline")
    p.add_argument("--tolerance-pct", type=float, default=20.0,
                   help="tolerance recorded by --write-budgets "
                        "(default 20)")
    args = p.parse_args(argv)

    try:
        source = find_trace(args.trace)
        summary = summarize(load_trace(source), source=str(source))
    except FileNotFoundError as e:
        print(f"traceview: {e}", file=sys.stderr)
        return 1
    print(json.dumps(summary), flush=True)

    if args.write_budgets:
        budgets = budgets_from_summary(summary, args.tolerance_pct)
        Path(args.write_budgets).write_text(json.dumps(budgets, indent=2) + "\n")
        print(f"traceview: budgets written to {args.write_budgets}",
              file=sys.stderr)

    if args.budgets:
        budgets = json.loads(Path(args.budgets).read_text())
        violations = check_budgets(summary, budgets)
        for v in violations:
            print(f"traceview: BUDGET VIOLATION: {v}", file=sys.stderr)
        if violations and args.check:
            return 2
        if not violations:
            print(f"traceview: {len(budgets.get('phases', {}))} phase "
                  "budget(s) OK", file=sys.stderr)
    elif args.check:
        print("traceview: --check needs --budgets", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
