"""graftdrift part 2: the drift report with retrain-trigger gating.

``tools/decisionview`` joined the serving plane's latency artifacts into
a budget-gated perf report; nothing did the same for the DISTRIBUTION
artifacts graftdrift produces. driftview is the drift sibling: a
pure-stdlib joiner over three inputs —

- a ``/stats`` **snapshot** (single-process, pool, or fleet body; JSON
  file or live ``http://`` URL) carrying the ``drift`` section
  (per-stream PSI/KS vs the loaded reference, window counts, the
  burn-style drifting verdicts) and the optional ``shadow`` section
  (incumbent-vs-candidate top-1 agreement, score-delta histogram),
- a frozen **reference** file (``drift snapshot`` CLI output,
  fingerprint-verified) to cross-check what the server actually loaded,
- a **trace-log** directory, summarized per generation with synthetic
  (probe/shadow) records counted apart — the corpus a reference would
  be re-frozen from after a promote,

— into one report:

- **Per-stream drift table**: status (``ok`` / ``no_reference`` /
  ``generation_mismatch``), fast/slow PSI and KS, window sample counts
  with sufficiency, and the drifting verdict (burn semantics: BOTH
  windows over threshold — a transient spike never trips it).
- **Reference lineage**: the fingerprint/generation the server loaded
  vs the ``--reference`` file on disk — a stale file is visible before
  anyone trusts a green gate.
- **Shadow verdict**: candidate agreement rate and score-delta mean
  next to the drop/error counters that bound how much was graded.
- **Gating** (``--check``, exit 2 — the decisionview/graftlint
  fail-the-build contract): any drifting stream (unless the budgets
  allow it), a gradable stream with no/mismatched reference when the
  budgets require one, a server/file fingerprint mismatch, and a shadow
  agreement rate under the floor. ``make drift-report`` runs it against
  the checked-in fixture (off-network tier-1) or a live pool.

Every input is optional — pass what you have. The module stays
stdlib-only (no numpy, no scheduler imports at module scope) so the
report runs anywhere the JSON artifacts land; the fingerprint recompute
below mirrors ``scheduler/drift.reference_fingerprint`` and is pinned
equal by test. docs/observability.md §5.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path

SCHEMA_VERSION = 1
REFERENCE_SCHEMA = 1  # scheduler/drift.REFERENCE_SCHEMA (pinned by test)


# ------------------------------------------------------------------ inputs


def load_stats(source: str) -> dict:
    """A ``/stats`` body from a JSON file or a live ``http://`` URL —
    single-process server, pool control plane, or a graftfleet
    controller's merged body (all carry the same ``drift`` shape)."""
    if source.startswith(("http://", "https://")):
        import urllib.request

        with urllib.request.urlopen(source, timeout=10) as resp:
            return json.load(resp)
    return json.loads(Path(source).read_text())


def reference_fingerprint(reference: dict) -> str:
    """Recompute the reference's content fingerprint — the SAME
    canonicalization as ``scheduler/drift.reference_fingerprint``
    (schema + generation + streams, sorted keys, compact separators),
    duplicated here so the report stays stdlib-only; a cross-check test
    pins the two implementations equal."""
    body = {
        "schema": reference.get("schema", REFERENCE_SCHEMA),
        "generation": reference.get("generation", 0),
        "streams": reference.get("streams") or {},
    }
    canonical = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canonical.encode()).hexdigest()


def load_reference(path: str | Path) -> dict:
    """A frozen reference file, fingerprint-verified on load (an edited
    or truncated file is refused, same contract as the server's
    ``--drift-ref``)."""
    ref = json.loads(Path(path).read_text())
    if not isinstance(ref, dict) or ref.get("schema") != REFERENCE_SCHEMA:
        raise ValueError(f"{path}: not a drift reference "
                         f"(schema {REFERENCE_SCHEMA} expected)")
    expected = reference_fingerprint(ref)
    if ref.get("fingerprint") != expected:
        raise ValueError(
            f"{path}: reference fingerprint mismatch (stored "
            f"{str(ref.get('fingerprint'))[:12]}…, distribution hashes "
            f"to {expected[:12]}…) — re-snapshot instead of repairing "
            "by hand")
    return ref


def load_budgets(path: str | Path) -> dict:
    return json.loads(Path(path).read_text())


def summarize_trace(trace_dir: str | Path) -> dict:
    """Per-generation record counts from a trace dir, synthetic
    (probe/shadow) traffic counted APART — the served corpus a
    post-promote reference re-freeze would draw from."""
    from rl_scheduler_tpu.scheduler.tracelog import (
        is_synthetic_endpoint,
        iter_trace_merged,
    )

    generations: dict = {}
    synthetic = fail_opens = 0
    for record in iter_trace_merged(trace_dir):
        if is_synthetic_endpoint(record.get("endpoint")):
            synthetic += 1
            continue
        if record.get("fail_open"):
            fail_opens += 1
            continue
        gen = int(record.get("generation", 0))
        generations[gen] = generations.get(gen, 0) + 1
    return {
        "generations": {str(g): n for g, n in sorted(generations.items())},
        "served_records": sum(generations.values()),
        "synthetic_excluded": synthetic,
        "fail_opens_excluded": fail_opens,
    }


# ------------------------------------------------------------------ report


def build_report(stats: dict | None = None,
                 reference: dict | None = None,
                 trace_summary: dict | None = None) -> dict:
    """Join the inputs into the report dict the formatter and the gates
    consume. Sections are present only when their input was."""
    report: dict = {"schema_version": SCHEMA_VERSION}
    drift = (stats or {}).get("drift")
    if drift is not None:
        streams = {}
        for name, score in (drift.get("scores") or {}).items():
            entry = dict(score)
            lifetime = ((drift.get("streams") or {}).get(name) or {}) \
                .get("lifetime") or {}
            entry["lifetime_count"] = lifetime.get("count", 0)
            streams[name] = entry
        loaded_ref = drift.get("reference") or None
        report["drift"] = {
            "generation": drift.get("generation", 0),
            "streams": streams,
            "drifting": list(drift.get("drifting") or []),
            "reference_loaded": bool(loaded_ref),
            "reference_fingerprint": (loaded_ref or {}).get("fingerprint"),
            "reference_generation": (loaded_ref or {}).get("generation"),
            "reference_mixed": bool(drift.get("reference_mixed")),
        }
    shadow = (stats or {}).get("shadow")
    if shadow is not None:
        delta = shadow.get("score_delta") or {}
        report["shadow"] = {
            "scored_total": shadow.get("scored_total", 0),
            "submitted_total": shadow.get("submitted_total", 0),
            "dropped_total": shadow.get("dropped_total", 0),
            "errors_total": shadow.get("errors_total", 0),
            "agreement_rate": shadow.get("agreement_rate"),
            "score_delta_mean": delta.get("mean"),
        }
    if reference is not None:
        report["reference_file"] = {
            "fingerprint": reference.get("fingerprint"),
            "generation": reference.get("generation"),
            "source": reference.get("source", ""),
            "streams": sorted((reference.get("streams") or {}).keys()),
        }
    if trace_summary is not None:
        report["trace"] = dict(trace_summary)
    return report


def format_report(report: dict) -> str:
    """Human tables (stdout). The JSON line is the machine surface;
    this is the operator's."""
    lines = []
    drift = report.get("drift")
    if drift is not None:
        lines.append("== drift (generation "
                     f"{drift['generation']}) ==")
        header = (f"{'stream':<10} {'status':<20} {'fast_psi':>9} "
                  f"{'slow_psi':>9} {'fast_ks':>8} {'slow_ks':>8} "
                  f"{'n_fast':>7} {'n_slow':>7}  drifting")
        lines.append(header)
        for name, s in sorted(drift["streams"].items()):
            psi = s.get("psi") or {}
            ks = s.get("ks") or {}
            windows = s.get("windows") or {}

            def _f(v):
                return "-" if v is None else f"{v:.4f}"

            lines.append(
                f"{name:<10} {s.get('status', '?'):<20} "
                f"{_f(psi.get('fast')):>9} {_f(psi.get('slow')):>9} "
                f"{_f(ks.get('fast')):>8} {_f(ks.get('slow')):>8} "
                f"{(windows.get('fast') or {}).get('count', 0):>7} "
                f"{(windows.get('slow') or {}).get('count', 0):>7}  "
                f"{'DRIFTING' if s.get('drifting') else 'ok'}")
        ref_fp = drift.get("reference_fingerprint")
        lines.append(
            "reference: "
            + (f"{ref_fp[:12]}… (generation "
               f"{drift.get('reference_generation')})"
               if ref_fp else "NONE LOADED")
            + ("  [MIXED across workers]" if drift.get("reference_mixed")
               else ""))
    shadow = report.get("shadow")
    if shadow is not None:
        lines.append("== shadow ==")
        rate = shadow.get("agreement_rate")
        lines.append(
            f"scored {shadow['scored_total']}/"
            f"{shadow['submitted_total']} submitted "
            f"(dropped {shadow['dropped_total']}, "
            f"errors {shadow['errors_total']}); "
            "agreement "
            + ("-" if rate is None else f"{rate:.4f}")
            + ", score-delta mean "
            + ("-" if shadow.get("score_delta_mean") is None
               else f"{shadow['score_delta_mean']:+.4f}"))
    ref_file = report.get("reference_file")
    if ref_file is not None:
        lines.append("== reference file ==")
        lines.append(
            f"{str(ref_file.get('fingerprint'))[:12]}… generation "
            f"{ref_file.get('generation')} "
            f"streams={','.join(ref_file.get('streams') or [])} "
            f"source={ref_file.get('source') or '-'}")
    trace = report.get("trace")
    if trace is not None:
        lines.append("== trace ==")
        gens = ", ".join(f"gen {g}: {n}"
                         for g, n in trace["generations"].items()) or "-"
        lines.append(
            f"served {trace['served_records']} ({gens}); "
            f"{trace['synthetic_excluded']} synthetic + "
            f"{trace['fail_opens_excluded']} fail-open excluded")
    return "\n".join(lines)


# ------------------------------------------------------------------- gates


def grade_report(report: dict, budgets: dict,
                 shadow_floor: float | None = None) -> dict:
    """The machine verdict behind ``--check`` AND ``--json``, derived
    ONCE: per-stream grades, named gate results (each carrying its
    violations), and the exit decision. :func:`check_drift` flattens
    this object's violations, so the human gate and the JSON verdict
    line can never disagree — the pin test only confirms it.

    Gates, in severity order: a missing ``drift`` section (a gate that
    cannot see drift must fail loudly, not pass vacuously); any
    DRIFTING stream unless ``allow_drifting``; a stream without a
    usable reference (``no_reference`` / ``generation_mismatch``) when
    ``require_reference``; the server's loaded fingerprint disagreeing
    with the ``--reference`` file; a mixed reference across workers;
    and a shadow agreement rate under the floor once enough requests
    were scored (``shadow_floor_min_scored`` — an idle shadow must not
    fail on one early disagreement)."""
    gates: list = []
    grades: dict = {}
    drift = report.get("drift")
    if drift is None:
        gates.append({"gate": "drift_section", "ok": False,
                      "violations": [
                          "no drift section in the stats body — serve "
                          "with --drift (or scrape a pool whose workers "
                          "do)"]})
    else:
        gates.append({"gate": "drift_section", "ok": True,
                      "violations": []})
        for name, s in sorted(drift["streams"].items()):
            if s.get("drifting"):
                grades[name] = "drifting"
            elif s.get("status") == "ok":
                grades[name] = "ok"
            elif s.get("status") == "no_reference" \
                    and not s.get("lifetime_count"):
                # A stream the deployment never feeds (e.g. the graph
                # family's feature columns) is not gradable — absence
                # of data is not absence of a reference.
                grades[name] = "idle"
            else:
                grades[name] = str(s.get("status"))
        drifting_violations = []
        if not budgets.get("allow_drifting", False):
            for name in drift.get("drifting") or []:
                s = (drift["streams"].get(name) or {})
                psi = s.get("psi") or {}
                drifting_violations.append(
                    f"stream `{name}` is DRIFTING (fast PSI "
                    f"{psi.get('fast')}, slow PSI {psi.get('slow')}) — "
                    "re-snapshot the reference if this regime change is "
                    "intended, retrain if not")
        gates.append({"gate": "drifting_streams",
                      "ok": not drifting_violations,
                      "violations": drifting_violations})
        coverage_violations = []
        if budgets.get("require_reference", True):
            for name, grade in sorted(grades.items()):
                if grade in ("ok", "drifting", "idle"):
                    continue
                coverage_violations.append(
                    f"stream `{name}` has status `{grade}` — "
                    "freeze a reference for the serving generation "
                    "(`drift snapshot`; mandatory re-snapshot after "
                    "every promote)")
        gates.append({"gate": "reference_coverage",
                      "ok": not coverage_violations,
                      "violations": coverage_violations})
        match_violations = []
        ref_file = report.get("reference_file")
        if ref_file is not None and drift.get("reference_fingerprint") \
                and ref_file.get("fingerprint") \
                != drift.get("reference_fingerprint"):
            match_violations.append(
                "reference mismatch: server loaded "
                f"{str(drift['reference_fingerprint'])[:12]}… but the "
                f"--reference file is "
                f"{str(ref_file['fingerprint'])[:12]}… "
                "— load the file (POST /drift/reference) or re-snapshot")
        gates.append({"gate": "reference_match",
                      "ok": not match_violations,
                      "violations": match_violations})
        uniform_violations = []
        if drift.get("reference_mixed"):
            uniform_violations.append(
                "workers disagree on the loaded reference (mixed "
                "fingerprints in the merged section) — re-fan the load "
                "(POST /drift/reference reaches every worker)")
        gates.append({"gate": "reference_uniform",
                      "ok": not uniform_violations,
                      "violations": uniform_violations})
        shadow_violations = []
        shadow = report.get("shadow")
        floor = (shadow_floor if shadow_floor is not None
                 else budgets.get("shadow_agreement_floor"))
        if shadow is not None and floor is not None:
            min_scored = int(budgets.get("shadow_floor_min_scored", 20))
            rate = shadow.get("agreement_rate")
            if shadow.get("scored_total", 0) >= min_scored \
                    and rate is not None and rate < floor:
                shadow_violations.append(
                    f"shadow agreement {rate:.4f} under the floor "
                    f"{floor:.4f} over {shadow['scored_total']} scored "
                    "requests — the candidate disagrees with the "
                    "incumbent too often to promote blind")
        gates.append({"gate": "shadow_floor",
                      "ok": not shadow_violations,
                      "violations": shadow_violations})
    violations = [v for g in gates for v in g["violations"]]
    failing = [g["gate"] for g in gates if not g["ok"]]
    return {
        "schema_version": SCHEMA_VERSION,
        "streams": grades,
        "gates": gates,
        "violations": violations,
        "ok": not violations,
        "exit_code": 2 if violations else 0,
        "exit_reason": failing[0] if failing else "ok",
    }


def check_drift(report: dict, budgets: dict,
                shadow_floor: float | None = None) -> list:
    """The ``--check`` violations (each a string; non-empty = exit 2).
    A flat view of :func:`grade_report` — one derivation, two
    surfaces."""
    return grade_report(report, budgets,
                        shadow_floor=shadow_floor)["violations"]
