"""driftview CLI (graftdrift part 2 — see the package docstring).

Usage::

    # full report against a live pool's control plane + its artifacts
    python -m tools.driftview --stats http://127.0.0.1:8788/stats \
        --reference /var/drift/reference.json --trace /var/trace

    # the regression gate (tier-1 runs this against the checked-in
    # fixture; exit 2 on a drifting stream / missing reference /
    # shadow-agreement floor)
    python -m tools.driftview --stats tests/fixtures/driftview/stats.json \
        --check --budgets tools/driftview/budgets.json

Prints the human tables to stdout plus ONE bench.py-style JSON line
(the documented schema); all violations go to stderr.
"""

from __future__ import annotations

import argparse
import json
import sys

from tools.driftview import (
    build_report,
    format_report,
    grade_report,
    load_budgets,
    load_reference,
    load_stats,
    summarize_trace,
)


def main(argv: list | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tools.driftview",
        description="Join a /stats drift section, a frozen reference "
                    "file and a decision-trace directory into one "
                    "distribution-shift report, with retrain-trigger "
                    "gates.")
    p.add_argument("--stats", default=None, metavar="FILE|URL",
                   help="/stats body: a JSON file or a live http:// URL "
                        "(single-process server, pool control plane, or "
                        "a graftfleet controller's merged /stats)")
    p.add_argument("--reference", default=None, metavar="FILE",
                   help="frozen reference (drift snapshot output); "
                        "fingerprint-verified on load and cross-checked "
                        "against what the server loaded")
    p.add_argument("--trace", default=None, metavar="DIR",
                   help="decision trace-log directory; summarized per "
                        "generation with synthetic (probe/shadow) "
                        "records counted apart")
    p.add_argument("--budgets", default="tools/driftview/budgets.json",
                   metavar="FILE",
                   help="gate config for --check (default "
                        "tools/driftview/budgets.json)")
    p.add_argument("--check", action="store_true",
                   help="gate mode: exit 2 on a drifting stream, a "
                        "gradable stream without a reference, a "
                        "server/file reference mismatch, or a shadow "
                        "agreement rate under the floor")
    p.add_argument("--shadow-floor", type=float, default=None,
                   metavar="RATE",
                   help="override the budgets' shadow_agreement_floor "
                        "for this run")
    p.add_argument("--json", action="store_true",
                   help="suppress the human tables; print only the "
                        "machine verdict line (schema_version:1 — "
                        "per-stream grades, named gate results, exit "
                        "reason; graded identically to --check)")
    args = p.parse_args(argv)
    if args.stats is None and args.reference is None \
            and args.trace is None:
        p.error("pass at least one of --stats / --reference / --trace")

    stats = load_stats(args.stats) if args.stats else None
    reference = load_reference(args.reference) if args.reference else None
    trace_summary = summarize_trace(args.trace) if args.trace else None
    report = build_report(stats=stats, reference=reference,
                          trace_summary=trace_summary)

    if not args.json:
        formatted = format_report(report)
        if formatted:
            print(formatted)
    line = {"schema_version": report["schema_version"],
            "report": "driftview", **{k: v for k, v in report.items()
                                      if k != "schema_version"}}
    violations: list = []
    if args.check or args.json:
        # ONE derivation for both surfaces: --check's exit decision and
        # --json's verdict line come from the same grade_report object,
        # so a script parsing the line and an operator reading the exit
        # code can never disagree (pinned by test).
        budgets = load_budgets(args.budgets)
        grade = grade_report(report, budgets,
                             shadow_floor=args.shadow_floor)
        line["verdict"] = {
            "streams": grade["streams"],
            "gates": grade["gates"],
            "ok": grade["ok"],
            "exit_reason": grade["exit_reason"],
            "would_exit": grade["exit_code"],
        }
        line["violations"] = grade["violations"]
        if args.check:
            violations = grade["violations"]
    print(json.dumps(line))
    for violation in violations:
        print(f"driftview: {violation}", file=sys.stderr)
    return 2 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main())
